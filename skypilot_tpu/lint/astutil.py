"""Shared AST helpers for the skylint passes (stdlib ``ast`` only)."""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple


def dotted(node: ast.AST) -> Optional[str]:
    """Dotted source name for a Name/Attribute chain ('os.environ.get'),
    None for anything dynamic (subscripts, calls, lambdas)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_head(node: ast.AST) -> Optional[str]:
    """Leading literal text of an f-string / string concatenation, for
    prefix-pattern matching (f'SKYT_RANK_{x}' -> 'SKYT_RANK_')."""
    if isinstance(node, ast.JoinedStr) and node.values:
        return const_str(node.values[0])
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return const_str(node.left) or fstring_head(node.left)
    return None


def import_map(tree: ast.Module) -> Dict[str, str]:
    """Local name -> fully qualified module/attr it was imported as.

    ``from skypilot_tpu.server import metrics`` -> {'metrics':
    'skypilot_tpu.server.metrics'}; ``import os`` -> {'os': 'os'};
    ``from x import y as z`` -> {'z': 'x.y'}.
    """
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split('.')[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                out[alias.asname or alias.name] = (
                    f'{node.module}.{alias.name}')
    return out


def resolve_call(func: ast.AST, imports: Dict[str, str]
                 ) -> Optional[str]:
    """Fully-qualified dotted name of a call target, resolving the
    leading segment through the module's imports."""
    name = dotted(func)
    if name is None:
        return None
    head, _, rest = name.partition('.')
    base = imports.get(head)
    if base is None:
        return name
    return f'{base}.{rest}' if rest else base


def walk_strings(tree: ast.AST) -> Iterator[Tuple[str, int]]:
    """All string constants (including f-string literal parts) with
    their line numbers."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node.lineno


def docstring_nodes(tree: ast.AST) -> set:
    """id()s of docstring Constant nodes (module/class/function), so
    passes can skip prose."""
    out = set()
    nodes = [tree] if isinstance(tree, ast.Module) else []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            nodes.append(node)
    for node in nodes:
        body = getattr(node, 'body', [])
        if (body and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)):
            out.add(id(body[0].value))
    return out


class ParentedVisit:
    """ast.walk with a parent map, built lazily once per tree."""

    def __init__(self, tree: ast.AST) -> None:
        self.parents: Dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))
