"""skylint core: file model, finding model, baseline, runner.

The linter is a plain AST pass (stdlib ``ast`` only — no third-party
deps, importable on the leanest runner). Checkers live in the
``checks_*`` modules; each exposes a class with:

* ``code``  — the stable finding code (``SKYT001``..``SKYT013``);
* ``name``  — short human label;
* ``run(ctx)`` — yields :class:`Finding`s over a :class:`Context`.

``SKYT000`` is reserved for meta findings the runner itself emits
(unparsable file, stale/unreviewed baseline entry, generated docs out
of sync).

Baseline: a committed JSON file of *reviewed* suppressions. Each entry
is ``{"code", "key", "reason"}`` — ``key`` is a stable identifier the
checker derives from the finding's content (never a line number, so
unrelated churn doesn't invalidate it), and ``reason`` must be a real
justification: empty or ``UNREVIEWED``-prefixed reasons fail the run.
Stale entries (matching no current finding) fail the run too, so the
baseline can only shrink or be consciously re-reviewed.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

META_CODE = 'SKYT000'


@dataclasses.dataclass
class Finding:
    code: str
    path: str          # repo-relative
    line: int
    message: str
    slug: str          # stable content-derived id (baseline matching)
    baselined: bool = False

    @property
    def key(self) -> str:
        return f'{self.path}:{self.slug}'

    def render(self) -> str:
        mark = ' [baselined]' if self.baselined else ''
        return f'{self.path}:{self.line}: {self.code} {self.message}{mark}'

    def to_json(self) -> Dict:
        return {'code': self.code, 'path': self.path, 'line': self.line,
                'message': self.message, 'key': self.key,
                'baselined': self.baselined}


class Module:
    """One parsed source file."""

    def __init__(self, path: str, rel: str, source: str,
                 tree: ast.Module) -> None:
        self.path = path
        self.rel = rel
        self.source = source
        self.tree = tree

    @classmethod
    def load(cls, path: str, rel: str) -> 'Module':
        with open(path, encoding='utf-8') as f:
            source = f.read()
        return cls(path, rel, source, ast.parse(source, filename=path))


class Context:
    """Everything a checker may look at.

    ``package_modules`` are the lint subjects; ``test_modules`` and
    ``doc_texts`` feed the cross-reference passes (chaos-site and
    event-topic coverage). Tests construct Contexts over fixture file
    sets; the CLI builds one over the real repo.
    """

    def __init__(self, repo_root: str,
                 package_files: Sequence[str],
                 test_files: Sequence[str] = (),
                 doc_files: Sequence[str] = ()) -> None:
        self.repo_root = repo_root
        self.package_modules: List[Module] = []
        self.test_modules: List[Module] = []
        self.doc_texts: Dict[str, str] = {}
        self.parse_errors: List[Finding] = []
        for path in package_files:
            self._load(path, self.package_modules)
        for path in test_files:
            self._load(path, self.test_modules)
        for path in doc_files:
            rel = os.path.relpath(path, repo_root)
            try:
                with open(path, encoding='utf-8') as f:
                    self.doc_texts[rel] = f.read()
            except OSError as e:
                self.parse_errors.append(Finding(
                    META_CODE, rel, 0, f'unreadable doc: {e}',
                    slug=f'unreadable:{rel}'))

    def _load(self, path: str, into: List[Module]) -> None:
        rel = os.path.relpath(path, self.repo_root)
        try:
            into.append(Module.load(path, rel))
        except (OSError, SyntaxError) as e:
            self.parse_errors.append(Finding(
                META_CODE, rel, getattr(e, 'lineno', 0) or 0,
                f'unparsable file: {e}', slug=f'unparsable:{rel}'))

    def module(self, rel_suffix: str) -> Optional[Module]:
        """The package module whose repo-relative path ends with
        ``rel_suffix`` (e.g. 'server/metrics.py')."""
        for mod in self.package_modules:
            if mod.rel.replace(os.sep, '/').endswith(rel_suffix):
                return mod
        return None


# -- repo discovery -----------------------------------------------------

def repo_paths(repo_root: str) -> Tuple[List[str], List[str], List[str]]:
    """(package_files, test_files, doc_files) for a real repo run.

    ``tests/lint_fixtures`` is excluded from the test scan: fixtures
    contain deliberate violations for the linter's own test suite.
    """
    package_files: List[str] = []
    pkg_root = os.path.join(repo_root, 'skypilot_tpu')
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != '__pycache__']
        for name in sorted(filenames):
            if name.endswith('.py'):
                package_files.append(os.path.join(dirpath, name))
    test_files: List[str] = []
    tests_root = os.path.join(repo_root, 'tests')
    if os.path.isdir(tests_root):
        for dirpath, dirnames, filenames in os.walk(tests_root):
            dirnames[:] = [d for d in dirnames
                           if d not in ('__pycache__', 'lint_fixtures')]
            for name in sorted(filenames):
                if name.endswith('.py'):
                    test_files.append(os.path.join(dirpath, name))
    doc_files: List[str] = []
    docs_root = os.path.join(repo_root, 'docs')
    if os.path.isdir(docs_root):
        for dirpath, dirnames, filenames in os.walk(docs_root):
            for name in sorted(filenames):
                if name.endswith('.md'):
                    doc_files.append(os.path.join(dirpath, name))
    readme = os.path.join(repo_root, 'README.md')
    if os.path.exists(readme):
        doc_files.append(readme)
    return package_files, test_files, doc_files


def find_repo_root() -> str:
    """The checkout root: parent of the installed/source package dir."""
    here = os.path.dirname(os.path.abspath(__file__))   # .../lint
    return os.path.dirname(os.path.dirname(here))        # repo root


# -- baseline -----------------------------------------------------------

UNREVIEWED_PREFIX = 'UNREVIEWED'


def load_baseline(path: str) -> List[Dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding='utf-8') as f:
        data = json.load(f)
    entries = data.get('suppressions', [])
    if not isinstance(entries, list):
        raise ValueError(f'{path}: "suppressions" must be a list')
    return entries


def apply_baseline(findings: List[Finding], entries: List[Dict],
                   baseline_path: str) -> List[Finding]:
    """Mark baselined findings; append meta findings for stale or
    unreviewed entries. Returns the merged list."""
    by_key: Dict[Tuple[str, str], Finding] = {
        (f.code, f.key): f for f in findings}
    meta: List[Finding] = []
    rel = os.path.basename(baseline_path)
    for i, entry in enumerate(entries):
        code = entry.get('code', '')
        key = entry.get('key', '')
        reason = (entry.get('reason') or '').strip()
        if not reason or reason.startswith(UNREVIEWED_PREFIX):
            meta.append(Finding(
                META_CODE, rel, 0,
                f'baseline entry {code}:{key} has no reviewed reason '
                '(write a justification or fix the finding)',
                slug=f'unreviewed:{code}:{key}'))
            continue
        finding = by_key.get((code, key))
        if finding is None:
            meta.append(Finding(
                META_CODE, rel, 0,
                f'stale baseline entry {code}:{key} matches no current '
                'finding (delete it)',
                slug=f'stale:{code}:{key}'))
        else:
            finding.baselined = True
    return findings + meta


def write_baseline(findings: Iterable[Finding], path: str) -> int:
    """--write-baseline: dump every ACTIVE finding as an UNREVIEWED
    suppression. Each entry must then be hand-reviewed (reason filled
    in) or fixed — the linter fails on UNREVIEWED reasons."""
    entries = [{
        'code': f.code,
        'key': f.key,
        'reason': f'{UNREVIEWED_PREFIX} — justify or fix: {f.message}',
    } for f in findings if not f.baselined and f.code != META_CODE]
    with open(path, 'w', encoding='utf-8') as f:
        json.dump({'version': 1, 'suppressions': entries}, f, indent=2,
                  sort_keys=True)
        f.write('\n')
    return len(entries)


# -- runner -------------------------------------------------------------

def all_checkers() -> List:
    from skypilot_tpu.lint import (checks_async, checks_chaos,
                                   checks_concurrency, checks_env,
                                   checks_events, checks_metrics,
                                   checks_portability,
                                   checks_resources,
                                   checks_shared_state,
                                   checks_simreach,
                                   checks_transactions,
                                   checks_wallclock)
    return [
        checks_async.AsyncBlockingChecker(),        # SKYT001
        checks_env.EnvRegistryChecker(),            # SKYT002
        checks_metrics.MetricsRegistryChecker(),    # SKYT003
        checks_chaos.ChaosCoverageChecker(),        # SKYT004
        checks_events.EventTopicChecker(),          # SKYT005
        checks_concurrency.LockOrderChecker(),      # SKYT006
        checks_portability.SqlitePortabilityChecker(),  # SKYT007
        checks_portability.JaxPurityChecker(),      # SKYT008
        checks_wallclock.WallClockChecker(),        # SKYT009
        checks_transactions.TransactionHygieneChecker(),  # SKYT010
        checks_resources.ResourcePairingChecker(),  # SKYT011
        checks_shared_state.SharedStateChecker(),   # SKYT012
        checks_simreach.SimReachDeterminismChecker(),   # SKYT013
    ]


def run_checks(ctx: Context, checkers: Optional[List] = None
               ) -> List[Finding]:
    findings: List[Finding] = list(ctx.parse_errors)
    for checker in (checkers if checkers is not None else all_checkers()):
        findings.extend(checker.run(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.slug))
    return findings
