"""SKYT011 — resource acquire/release pairing on every CFG path.

Four resource vocabularies whose leak mode is silent and cumulative:

* **bare lock ``.acquire()``** (receiver named ``*lock*``/``*sem*``)
  without a ``.release()`` reachable on every path — a raised
  exception between them deadlocks the next acquirer forever. The
  ``with`` form never flags (the context manager IS the pairing).
  Try-lock calls (``blocking=False`` / ``timeout=``) are exempt: their
  conditional release is matched to the conditional claim by hand.
* **multipart uploads**: ``create_multipart_upload`` must reach
  ``complete_…``/``abort_…`` — an abandoned upload id is billed
  storage forever (the exact orphan PR 5's review fixed once).
* **tempfiles**: ``tempfile.mkstemp``/``mktemp``/
  ``NamedTemporaryFile(delete=False)`` must reach
  ``os.unlink``/``os.remove``/``os.replace``/``os.rename``/
  ``shutil.move`` — a failure before the final rename leaks spool
  files into long-lived cache dirs.
* **BlockPool refcounts**: ``.incref(x)`` / ``.decref(x)`` on a
  ``*pool*`` receiver must balance. Only functions that already
  mention a ``decref`` on the same receiver are analyzed — a function
  that increfs and hands the reference to a long-lived structure (the
  prefix cache) transfers ownership by design.

The analysis is a may-leak forward pass over the shared CFG with
exception edges: the state is the set of outstanding resources; an
open statement's OWN exception edge carries the pre-state (if the
acquire itself raised, nothing was acquired); any other raising
statement propagates the open state to the innermost handler/finally
or out of the function. Ownership escapes (returning the token,
storing it into an attribute/container, yielding it, passing an
upload context to a helper) kill tracking silently — imprecision
degrades to silence, not noise.

Context-manager classes get a protocol check instead: an ``__enter__``
that acquires ``self._lock`` pairs with its class's ``__exit__``,
which must release on EVERY path — an ``__exit__`` that only releases
after a successful flush keeps the lock when the flush raises.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.lint import astutil, dataflow
from skypilot_tpu.lint.core import Context, Finding

CODE = 'SKYT011'

_TMP_OPENERS = frozenset({'tempfile.mkstemp', 'tempfile.mktemp'})
_TMP_CLOSERS = frozenset({'os.unlink', 'os.remove', 'os.replace',
                          'os.rename', 'shutil.move'})
_LOCKISH = ('lock', 'sem')


def _names_in(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


class _Facts:
    """Resource effects of one statement."""

    __slots__ = ('opens', 'closes', 'escapes_all')

    def __init__(self) -> None:
        self.opens: List[Tuple[object, int]] = []   # (token, lineno)
        self.closes: List[object] = []   # exact token or ('by-name',
        #                                   kind, frozenset(names))
        # Names whose tokens escape (returned/stored/yielded).
        self.escapes_all: Set[str] = set()


def _token_names(token) -> Set[str]:
    if token[0] in ('upload', 'tmp'):
        return set(token[1])
    return set()


class ResourcePairingChecker:
    code = CODE
    name = 'resource acquire/release pairing'

    def run(self, ctx: Context) -> Iterator[Finding]:
        for mod in ctx.package_modules:
            imports = astutil.import_map(mod.tree)
            fns = list(dataflow.functions_of(mod.tree))
            exempt, proto_findings = self._protocol_pairs(mod, fns,
                                                          imports)
            yield from proto_findings
            for class_name, fn in fns:
                if fn.name in ('acquire', 'release', '__exit__'):
                    continue   # wrapper / protocol counterpart
                if (class_name, fn.name) in exempt:
                    continue   # __enter__ paired with checked __exit__
                yield from self._check_fn(mod, class_name, fn, imports)

    # -- __enter__/__exit__ protocol ------------------------------------

    def _protocol_pairs(self, mod, fns, imports):
        by_class: Dict[str, Dict[str, ast.AST]] = {}
        for class_name, fn in fns:
            if class_name and fn.name in ('__enter__', '__exit__'):
                by_class.setdefault(class_name, {})[fn.name] = fn
        exempt: Set[Tuple[str, str]] = set()
        findings: List[Finding] = []
        for class_name, pair in sorted(by_class.items()):
            enter = pair.get('__enter__')
            exit_fn = pair.get('__exit__')
            if enter is None:
                continue
            receivers = sorted({
                recv for c in ast.walk(enter)
                if isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr == 'acquire'
                and not _is_tryacquire(c)
                for recv in [astutil.dotted(c.func.value)]
                if recv and _is_lockish(recv)})
            if not receivers:
                continue
            exempt.add((class_name, '__enter__'))
            if exit_fn is None:
                findings.append(Finding(
                    CODE, mod.rel, enter.lineno,
                    f'{class_name}.__enter__ acquires {receivers} but '
                    'the class has no __exit__ to release it',
                    slug=f'proto-noexit:{class_name}'))
                continue
            for recv in receivers:
                if self._exit_may_skip_release(exit_fn, recv):
                    findings.append(Finding(
                        CODE, mod.rel, exit_fn.lineno,
                        f'{class_name}.__exit__ releases `{recv}` only '
                        'on the no-exception path — an error before '
                        'the release keeps the lock held forever '
                        '(wrap the body in try/finally)',
                        slug=f'proto-leak:{class_name}:{recv}'))
        return exempt, findings

    def _exit_may_skip_release(self, exit_fn, recv: str) -> bool:
        cfg = dataflow.CFG(exit_fn)

        def transfer(node, state):
            stmt = node.stmt
            if stmt is not None and state == 'open':
                for call in dataflow.owned_calls(stmt):
                    if (isinstance(call.func, ast.Attribute)
                            and call.func.attr == 'release'
                            and astutil.dotted(call.func.value) == recv):
                        return 'closed', 'closed'
            return state, state

        def merge(a, b):
            return 'open' if 'open' in (a, b) else 'closed'

        in_states = dataflow.forward(cfg, 'open', transfer, merge)
        return in_states.get(id(cfg.exit)) == 'open'

    # -- per-function may-leak analysis ---------------------------------

    def _check_fn(self, mod, class_name, fn, imports
                  ) -> Iterator[Finding]:
        cfg = dataflow.CFG(fn)
        decref_receivers = {
            recv for c in ast.walk(fn) if isinstance(c, ast.Call)
            and isinstance(c.func, ast.Attribute)
            and c.func.attr == 'decref'
            for recv in [astutil.dotted(c.func.value)] if recv}

        facts_by_node: Dict[int, _Facts] = {}
        open_lines: Dict[object, int] = {}
        for node in dataflow.statement_nodes(cfg):
            facts = self._stmt_facts(node.stmt, imports,
                                     decref_receivers)
            if isinstance(node.stmt, (ast.For, ast.AsyncFor,
                                      ast.While)):
                # Cleanup loops (`for b in blocks: pool.decref(b)`)
                # iterate the same collection as their open loops;
                # apply their closes at the loop head too, so the
                # zero-iteration CFG path (empty collection = nothing
                # was opened either) doesn't read as a leak.
                body_closes = self._subtree_closes(
                    node.stmt, imports, decref_receivers)
                if body_closes:
                    facts = facts or _Facts()
                    facts.closes.extend(body_closes)
            if facts is not None:
                facts_by_node[id(node)] = facts
                for token, line in facts.opens:
                    open_lines.setdefault(token, line)
        if not open_lines:
            return

        def closes_token(close, token) -> bool:
            if isinstance(close, tuple) and close[0] == 'by-name':
                _, kind, names = close
                return token[0] == kind and bool(
                    _token_names(token) & names)
            return close == token

        def transfer(node, state):
            facts = facts_by_node.get(id(node))
            if facts is None:
                return state, state
            normal = set(state)
            opened_here = set()
            for close in facts.closes:
                normal = {t for t in normal
                          if not closes_token(close, t)}
            if facts.escapes_all:
                normal = {t for t in normal
                          if not (_token_names(t) & facts.escapes_all)}
            for token, _ in facts.opens:
                normal.add(token)
                opened_here.add(token)
            # The open call's own exception edge drops its token: a
            # raising acquire acquired nothing (loop-carried re-opens
            # of the same token read the same way — silence over
            # noise when iterations are indistinguishable).
            exc = normal - opened_here
            return frozenset(normal), frozenset(exc)

        in_states = dataflow.forward(
            cfg, frozenset(), transfer,
            merge=lambda a, b: frozenset(a | b))
        leaked = in_states.get(id(cfg.exit), frozenset())
        qual = f'{class_name}.{fn.name}' if class_name else fn.name
        for token in sorted(leaked, key=repr):
            desc = _describe(token)
            yield Finding(
                CODE, mod.rel, open_lines.get(token, fn.lineno),
                f'{desc} in {qual}() may leak on some path (including '
                'exception edges) — pair it in a finally/with, or '
                'abort/release before raising',
                slug=f'leak:{qual}:{desc}')

    # -- statement classification ---------------------------------------

    def _subtree_closes(self, stmt, imports, decref_receivers):
        """Close operations anywhere in a compound statement's body."""
        closes: List[object] = []
        for sub in ast.walk(stmt):
            if sub is stmt or not isinstance(sub, ast.stmt):
                continue
            facts = self._stmt_facts(sub, imports, decref_receivers)
            if facts is not None:
                closes.extend(facts.closes)
        return closes

    def _stmt_facts(self, stmt, imports, decref_receivers
                    ) -> Optional[_Facts]:
        facts = _Facts()
        assigned: Tuple[str, ...] = ()
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            assigned = tuple(sorted(
                name for name, _ in dataflow._assign_pairs(
                    stmt.targets[0], dataflow.UNKNOWN)))
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            names: List[str] = []
            for item in stmt.items:
                if item.optional_vars is not None:
                    names.extend(n for n, _ in dataflow._assign_pairs(
                        item.optional_vars, dataflow.UNKNOWN))
            assigned = tuple(sorted(names))

        for call in dataflow.owned_calls(stmt):
            resolved = astutil.resolve_call(call.func, imports) or ''
            tail = resolved.rsplit('.', 1)[-1]
            recv = (astutil.dotted(call.func.value)
                    if isinstance(call.func, ast.Attribute) else None)

            if (tail == 'acquire' and recv and _is_lockish(recv)
                    and not isinstance(stmt, (ast.With, ast.AsyncWith))
                    and not _is_tryacquire(call)):
                facts.opens.append((('lock', recv), call.lineno))
            elif tail == 'release' and recv:
                facts.closes.append(('lock', recv))

            elif tail == 'create_multipart_upload' and assigned:
                facts.opens.append((('upload', assigned), call.lineno))
            elif ('multipart' in tail
                  and ('abort' in tail or 'complete' in tail)):
                facts.closes.append(
                    ('by-name', 'upload', _call_arg_names(call)))

            elif ((resolved in _TMP_OPENERS
                   or (tail == 'NamedTemporaryFile'
                       and _kw_false(call, 'delete')))
                  and assigned):
                facts.opens.append((('tmp', assigned), call.lineno))
            elif resolved in _TMP_CLOSERS:
                facts.closes.append(
                    ('by-name', 'tmp', _call_arg_names(call)))

            elif (tail == 'incref' and recv and 'pool' in recv.lower()
                  and recv in decref_receivers and call.args):
                arg = astutil.dotted(call.args[0])
                if arg:
                    facts.opens.append((('ref', recv, arg),
                                        call.lineno))
            elif tail == 'decref' and recv and call.args:
                arg = astutil.dotted(call.args[0])
                if arg:
                    facts.closes.append(('ref', recv, arg))


        escape_names: Set[str] = set()
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            escape_names |= _names_in(stmt.value)
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                if isinstance(target, (ast.Attribute, ast.Subscript)):
                    escape_names |= _names_in(stmt.value)
        for expr in dataflow.owned_exprs(stmt):
            for sub in ast.walk(expr):
                if isinstance(sub, (ast.Yield, ast.YieldFrom)) and \
                        getattr(sub, 'value', None) is not None:
                    escape_names |= _names_in(sub.value)
        facts.escapes_all |= escape_names
        if facts.opens or facts.closes or facts.escapes_all:
            return facts
        return None


def _is_lockish(name: Optional[str]) -> bool:
    if not name:
        return False
    last = name.rsplit('.', 1)[-1].lower()
    return any(part in last for part in _LOCKISH)


def _is_tryacquire(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == 'blocking' and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
        if kw.arg == 'timeout':
            return True
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is False:
        return True
    return False


def _kw_false(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return kw.value.value is False
    return False


def _call_arg_names(call: ast.Call) -> frozenset:
    names: Set[str] = set()
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        names |= _names_in(arg)
    return frozenset(names)


def _describe(token) -> str:
    kind = token[0]
    if kind == 'lock':
        return f'bare {token[1]}.acquire()'
    if kind == 'upload':
        return f'multipart upload `{"/".join(token[1])}`'
    if kind == 'tmp':
        return f'tempfile `{"/".join(token[1])}`'
    if kind == 'ref':
        return f'{token[1]}.incref({token[2]})'
    return repr(token)
