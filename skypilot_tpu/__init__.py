"""skypilot-tpu: a TPU-native AI workload orchestrator.

A brand-new framework with the capability set of SkyPilot (reference:
``sky/__init__.py``): task YAML / SDK front end, cost+availability optimizer
over a hardware catalog, failover provisioning of multi-host TPU pod slices,
an on-node runtime daemon with a cluster-local job queue, an async
client->API-server architecture, managed jobs with preemption recovery, and
replica-autoscaled serving -- built TPU-first:

* TPU topology (generation / chips / hosts / ICI topology) is a first-class
  type in ``Resources`` (the reference special-cases TPU names in
  ``sky/resources.py:990-1014``; here it is ``spec.TpuTopology``).
* Multi-host gang launch wires ``jax.distributed`` coordinator +
  ``TPU_WORKER_ID`` env vars across pod hosts (the reference injects
  NCCL/torchrun-shaped env vars, ``sky/backends/task_codegen.py:626-666``).
* No Ray: TPU pod slices are created atomically, so gang semantics come from
  the provisioner + per-host runtime daemon (``runtime/``).
* The payload story is in-tree and JAX-native: ``models/`` (Llama family,
  MoE), ``ops/`` (Pallas kernels), ``parallel/`` (mesh + shardings, ring
  attention), ``train/`` (pretraining loop) -- replacing the reference's
  GPU-only ``llm/`` recipe dirs.
"""

__version__ = '0.1.0'

# Lazy re-exports: keep `import skypilot_tpu` fast (the reference keeps
# `import sky` fast via adaptors, sky/adaptors/common.py:10).
_LAZY_ATTRS = {
    'Task': ('skypilot_tpu.spec.task', 'Task'),
    'Resources': ('skypilot_tpu.spec.resources', 'Resources'),
    'Dag': ('skypilot_tpu.spec.dag', 'Dag'),
    'TpuTopology': ('skypilot_tpu.spec.topology', 'TpuTopology'),
    'launch': ('skypilot_tpu.execution', 'launch'),
    'exec': ('skypilot_tpu.execution', 'exec_'),
    'status': ('skypilot_tpu.core', 'status'),
    'stop': ('skypilot_tpu.core', 'stop'),
    'start': ('skypilot_tpu.core', 'start'),
    'down': ('skypilot_tpu.core', 'down'),
    'queue': ('skypilot_tpu.core', 'queue'),
    'cancel': ('skypilot_tpu.core', 'cancel'),
    'tail_logs': ('skypilot_tpu.core', 'tail_logs'),
    'autostop': ('skypilot_tpu.core', 'autostop'),
    'ClusterStatus': ('skypilot_tpu.state', 'ClusterStatus'),
    'JobStatus': ('skypilot_tpu.runtime.job_lib', 'JobStatus'),
}


def __getattr__(name):
    try:
        module_name, attr = _LAZY_ATTRS[name]
    except KeyError:
        raise AttributeError(
            f'module {__name__!r} has no attribute {name!r}') from None
    import importlib
    module = importlib.import_module(module_name)
    return getattr(module, attr)


def __dir__():
    return sorted(list(globals()) + list(_LAZY_ATTRS))
