"""Tokenizers behind one encode/decode interface.

* ``ByteTokenizer`` — self-contained byte-level fallback (no vocab
  files), reversible for any UTF-8 text; the tiny test models
  (vocab 512) cover its full id range.
* ``HFTokenizer`` — a real BPE tokenizer loaded from an HF checkpoint
  dir's ``tokenizer.json`` (Llama-3 ships its 128k-token BPE this way:
  ref ``llm/llama-3_1``). Backed by the ``tokenizers`` library.
* ``get_tokenizer(dir)`` — factory: HF when a tokenizer.json is
  present, byte-level otherwise. Engines only ever see ids.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
_OFFSET = 3


def render_transcript(messages,
                      add_generation_prompt: bool = True) -> str:
    """Plain role-prefixed chat transcript (the no-template fallback)."""
    text = ''.join(
        f"{m.get('role', 'user')}: {m.get('content', '')}\n"
        for m in messages)
    return text + ('assistant:' if add_generation_prompt else '')


class ByteTokenizer:
    vocab_size = 256 + _OFFSET
    pad_id = PAD_ID
    bos_id = BOS_ID
    eos_id = EOS_ID
    chat_template = None

    def encode(self, text: str, *, add_bos: bool = True) -> List[int]:
        ids = [b + _OFFSET for b in text.encode('utf-8')]
        return [BOS_ID] + ids if add_bos else ids

    def decode(self, ids: List[int]) -> str:
        data = bytes(i - _OFFSET for i in ids
                     if i >= _OFFSET and i - _OFFSET < 256)
        return data.decode('utf-8', errors='replace')

    def apply_chat_template(self, messages,
                            add_generation_prompt: bool = True) -> str:
        return render_transcript(messages, add_generation_prompt)


class HFTokenizer:
    """BPE tokenizer from an HF checkpoint dir (tokenizer.json).

    Special-token ids come from tokenizer_config.json (bos/eos token
    strings -> ids); pad defaults to eos the way HF generation does
    when no pad token is defined.
    """

    def __init__(self, path: str) -> None:
        from tokenizers import Tokenizer  # rust-backed, baked in
        tok_file = (path if path.endswith('.json')
                    else os.path.join(path, 'tokenizer.json'))
        self._tok = Tokenizer.from_file(tok_file)
        self.vocab_size = self._tok.get_vocab_size()
        base = os.path.dirname(tok_file)
        self.bos_id, self.eos_id = self._special_ids(base)
        self.pad_id = self.eos_id
        self.chat_template = self._load_chat_template(base)
        self._compiled_template = None
        if self.chat_template:
            # Compile ONCE (the serving hot path must not re-parse a
            # multi-KB template per request), and in a SANDBOX: the
            # template ships with a third-party checkpoint — plain
            # jinja would let it reach __globals__/os (transformers
            # uses ImmutableSandboxedEnvironment for the same reason).
            import jinja2
            from jinja2.sandbox import ImmutableSandboxedEnvironment
            env = ImmutableSandboxedEnvironment(
                trim_blocks=True, lstrip_blocks=True,
                undefined=jinja2.ChainableUndefined)
            env.globals['raise_exception'] = _template_raise
            self._compiled_template = env.from_string(
                self.chat_template)

    @staticmethod
    def _load_chat_template(base: str):
        cfg_file = os.path.join(base, 'tokenizer_config.json')
        if not os.path.exists(cfg_file):
            return None
        with open(cfg_file) as f:
            template = json.load(f).get('chat_template')
        if isinstance(template, list):
            # HF also allows [{name, template}, ...]; 'default' wins.
            by_name = {t.get('name'): t.get('template')
                       for t in template if isinstance(t, dict)}
            return by_name.get('default') or next(
                iter(by_name.values()), None)
        return template

    def apply_chat_template(self, messages,
                            add_generation_prompt: bool = True) -> str:
        """Render messages with the checkpoint's own chat template
        (tokenizer_config.json, jinja — the same artifact transformers
        renders), falling back to a plain role-prefixed transcript.

        Templated prompts carry their own BOS — encode them with
        ``add_bos=False`` (server: the template controls specials).
        """
        if self._compiled_template is not None:
            bos = self._tok.id_to_token(self.bos_id) or ''
            eos = self._tok.id_to_token(self.eos_id) or ''
            return self._compiled_template.render(
                messages=messages,
                add_generation_prompt=add_generation_prompt,
                bos_token=bos, eos_token=eos)
        return render_transcript(messages, add_generation_prompt)

    def _special_ids(self, base: str):
        def token_str(v):
            return v['content'] if isinstance(v, dict) else v

        bos = eos = None
        cfg_file = os.path.join(base, 'tokenizer_config.json')
        if os.path.exists(cfg_file):
            with open(cfg_file) as f:
                tc = json.load(f)
            if tc.get('bos_token'):
                bos = self._tok.token_to_id(token_str(tc['bos_token']))
            if tc.get('eos_token'):
                eos = self._tok.token_to_id(token_str(tc['eos_token']))
        if bos is None:
            for cand in ('<|begin_of_text|>', '<s>', '<bos>'):
                bos = self._tok.token_to_id(cand)
                if bos is not None:
                    break
        if eos is None:
            for cand in ('<|end_of_text|>', '</s>', '<eos>',
                         '<|eot_id|>'):
                eos = self._tok.token_to_id(cand)
                if eos is not None:
                    break
        if eos is None:
            raise ValueError(
                f'no eos token found for tokenizer under {base}: add a '
                'tokenizer_config.json with "eos_token" (an arbitrary '
                'vocab id must not silently become a stop token)')
        if bos is None:
            bos = eos
        return bos, eos

    def encode(self, text: str, *, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        return [self.bos_id] + ids if add_bos else ids

    def decode(self, ids: List[int]) -> str:
        specials = {self.bos_id, self.eos_id, self.pad_id}
        return self._tok.decode([i for i in ids if i not in specials],
                                skip_special_tokens=True)


def get_tokenizer(checkpoint_dir: Optional[str] = None, *,
                  require: bool = False):
    """HFTokenizer when the dir ships a tokenizer.json, else bytes.

    ``require=True`` (the engines' explicit ``hf_checkpoint`` path):
    a missing tokenizer.json raises instead of silently serving real
    weights through the byte fallback's nonsense vocabulary.
    """
    if checkpoint_dir and os.path.exists(
            os.path.join(checkpoint_dir, 'tokenizer.json')):
        return HFTokenizer(checkpoint_dir)
    if checkpoint_dir and require:
        raise ValueError(
            f'no tokenizer.json under {checkpoint_dir}: an HF '
            'checkpoint must ship its tokenizer (sentencepiece-only '
            'exports: convert with transformers '
            "`AutoTokenizer...save_pretrained`), or the byte fallback "
            'would silently mis-encode every prompt')
    return ByteTokenizer()


def _template_raise(message):  # chat templates call raise_exception()
    raise ValueError(f'chat template error: {message}')
