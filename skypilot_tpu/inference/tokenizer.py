"""Byte-level tokenizer: ids 0..2 reserved (pad/bos/eos), byte b -> b+3.

Self-contained (no external vocab files), reversible for any UTF-8 text,
and small enough that the tiny test models (vocab 512) cover the full id
range. Real deployments can swap in a sentencepiece/HF tokenizer behind
the same encode/decode interface; the engine only needs ids.
"""
from __future__ import annotations

from typing import List

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
_OFFSET = 3


class ByteTokenizer:
    vocab_size = 256 + _OFFSET
    pad_id = PAD_ID
    bos_id = BOS_ID
    eos_id = EOS_ID

    def encode(self, text: str, *, add_bos: bool = True) -> List[int]:
        ids = [b + _OFFSET for b in text.encode('utf-8')]
        return [BOS_ID] + ids if add_bos else ids

    def decode(self, ids: List[int]) -> str:
        data = bytes(i - _OFFSET for i in ids
                     if i >= _OFFSET and i - _OFFSET < 256)
        return data.decode('utf-8', errors='replace')
