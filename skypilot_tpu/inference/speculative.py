"""Draft proposers for speculative decoding (Leviathan et al., ICML 2023).

The continuous engine's speculative mode turns one verify step into up
to ``draft_k + 1`` emitted tokens: a draft proposes ``draft_k`` tokens
after the pending one, the fused paged kernel verifies the whole window
in one program, and the engine accepts the longest prefix that matches
what the target model would have sampled at each position (fold-in-
position sampling makes that target deterministic, so the accepted
stream is token-for-token the non-speculative stream).

Drafts are HOST-side and must be cheap — they run on the serving-loop
thread between device steps. Two proposers behind one interface:

* :class:`NGramDraft` — prompt-lookup decoding (Saxena's PLD / vLLM's
  ``ngram`` speculator): find the most recent occurrence of the
  history's trailing n-gram and propose the tokens that followed it.
  Free (no model), and very effective on the agentic/RAG shape where
  generation quotes its own context. The default.
* :class:`ModelDraft` — a small draft model behind the same interface
  (``models/decode.generate`` greedy over the history tail). A
  reference implementation of the pluggable-model contract: it
  re-prefills per call, so use it with genuinely small configs or swap
  in an incremental implementation for production.

A proposer may return FEWER than ``k`` tokens (including none) — the
engine shrinks that slot's verify window accordingly, so a miss costs
one ordinary decode step, never a stall.
"""
from __future__ import annotations

from typing import Any, List, Sequence


class DraftModel:
    """Interface: propose up to ``k`` tokens continuing ``history``.

    ``history`` is the slot's full visible token stream — prompt,
    accepted generations, and the pending (sampled-but-unverified)
    token last. Implementations must be pure lookups or cheap model
    calls; they run on the engine's serving-loop thread.
    """

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        raise NotImplementedError


class NGramDraft(DraftModel):
    """Prompt-lookup + recent-completion retrieval.

    Two lookup tiers; at equal n-gram order the slot's own history
    wins (local recency), but the corpus outranks LOW-order history
    backoff — a 1-gram history guess fires on almost any natural text
    and must not shadow a ``max_ngram`` retrieval hit:

    1. **Slot history** (Saxena-style PLD): take the history's last n
       tokens, find their most recent earlier occurrence, and propose
       what followed it. O(len(history) * max_ngram) over a
       max_len-bounded history.
    2. **Completion corpus** (REST-shaped retrieval, He et al. 2023):
       the engine ``observe``s finished streams; their ``max_ngram``-
       grams index short continuations in a dict, and a trailing-n-gram
       hit drafts the remembered continuation. This is what fires on
       the agentic fleet shape — repeated/near-repeated queries whose
       answers were just generated (the decode-side sibling of the
       prefill prefix cache). O(1) per proposal; the index is bounded
       by ``corpus_entries`` (crudely cleared when full — recency
       rebuilds it in a few requests, and a draft miss only costs the
       speculation, never correctness).
    """

    DRAFT_LEN = 16  # continuation tokens remembered per indexed n-gram

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 corpus_entries: int = 0) -> None:
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f'need 1 <= min_ngram <= max_ngram, got '
                f'({min_ngram}, {max_ngram})')
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.corpus_entries = corpus_entries
        self._index: dict = {}

    def observe(self, tokens: Sequence[int]) -> None:
        """Index a finished stream's n-grams (most recent wins)."""
        if not self.corpus_entries:
            return
        toks = list(tokens)
        n = self.max_ngram
        if len(self._index) + max(len(toks) - n, 0) > self.corpus_entries:
            self._index.clear()
        for i in range(len(toks) - n):
            cont = tuple(toks[i + n:i + n + self.DRAFT_LEN])
            if cont:
                self._index[tuple(toks[i:i + n])] = cont

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        hist = list(history)
        n_hist = len(hist)
        if k <= 0 or n_hist < self.min_ngram + 1:
            return []
        # Priority: slot history at the corpus's own n-gram order or
        # longer (local recency wins ties), then the corpus, then
        # shorter history n-grams — a max_ngram retrieval hit must not
        # be shadowed by a low-order (often 1-gram) history guess,
        # which on natural text would fire almost every step.
        n_top = min(self.max_ngram, n_hist - 1)
        cont = self._history_lookup(hist, n_top, k)
        if cont:
            return cont
        if self._index and n_hist >= self.max_ngram:
            indexed = self._index.get(tuple(hist[-self.max_ngram:]))
            if indexed:
                return list(indexed[:k])
        for n in range(n_top - 1, self.min_ngram - 1, -1):
            cont = self._history_lookup(hist, n, k)
            if cont:
                return cont
        return []

    @staticmethod
    def _history_lookup(hist: List[int], n: int, k: int) -> List[int]:
        """Most recent earlier occurrence of the trailing n-gram; the
        (always non-empty, since i + n < len(hist)) continuation that
        followed it."""
        if n < 1:
            return []
        suffix = hist[-n:]
        for i in range(len(hist) - n - 1, -1, -1):
            if hist[i:i + n] == suffix:
                return hist[i + n:i + n + k]
        return []


class ModelDraft(DraftModel):
    """Greedy draft from a (small) model — the pluggable-model shape.

    Wraps ``models/decode.generate`` over the history tail. Reference
    implementation: it pays a fresh prefill every call (fine for tiny
    draft configs and tests; a production draft would keep its own
    incremental KV state behind this same interface).
    """

    def __init__(self, params: Any, cfg: Any,
                 context_tokens: int = 64) -> None:
        self.params = params
        self.cfg = cfg
        self.context_tokens = max(1, context_tokens)

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        if k <= 0 or not history:
            return []
        import jax.numpy as jnp
        import numpy as np
        from skypilot_tpu.models import decode as decode_lib
        window = min(self.context_tokens, self.cfg.max_seq_len - k)
        ids = list(history)[-window:]
        tokens = jnp.asarray([ids], jnp.int32)
        lengths = jnp.asarray([len(ids)], jnp.int32)
        generated, gen_len = decode_lib.generate(
            self.params, tokens, lengths, self.cfg,
            max_new_tokens=k, temperature=0.0)
        return [int(t) for t in
                np.asarray(generated)[0][:int(gen_len[0])]]
