"""Batched KV-cache inference engine.

JetStream-lite: requests are batched, prompts right-padded into shape
buckets (powers of two) so each (batch, prompt_bucket, decode_bucket)
triple compiles exactly once; decode runs as one lax.scan program on the
chip. Weights can be sharded over a mesh (tensor axis) -- single-chip by
default.

Parity target: the serving payload of
``examples/tpu/v6e/benchmark-llama2-7b.yaml`` (JetStream); the
orchestration side (replicas/autoscaler/LB) lives in ``serve/``.
"""
from __future__ import annotations

import functools
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import decode as decode_lib
from skypilot_tpu.models import llama
from skypilot_tpu.models.config import ModelConfig, get_model_config
from skypilot_tpu.inference.tokenizer import get_tokenizer


def _bucket(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


@functools.partial(jax.jit, static_argnames=('cfg',))
def _embed_pooled(params, tokens, lengths, cfg):
    """Masked mean-pool of final hidden states, L2-normalized."""
    hidden = llama.forward(params, tokens, cfg, return_hidden=True)
    s = tokens.shape[1]
    mask = (jnp.arange(s)[None, :] < lengths[:, None]).astype(
        hidden.dtype)
    summed = jnp.einsum('bsd,bs->bd', hidden, mask,
                        preferred_element_type=jnp.float32)
    pooled = summed / jnp.maximum(
        lengths[:, None].astype(jnp.float32), 1.0)
    # fp32 normalization: bf16 rsqrt drifts ~2e-3 off unit norm.
    norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
    return pooled / jnp.maximum(norm, 1e-6)


class InferenceEngine:
    """Thread-safe generate() over a fixed model."""

    def __init__(self,
                 model: str = 'tiny',
                 *,
                 cfg: Optional[ModelConfig] = None,
                 params: Optional[Any] = None,
                 checkpoint_dir: Optional[str] = None,
                 hf_checkpoint: Optional[str] = None,
                 seed: int = 0,
                 max_batch: int = 8,
                 quantize: bool = False,
                 quantize_kv: bool = False,
                 mesh: Optional[Any] = None) -> None:
        # hf_checkpoint: an HF-layout dir (config.json + safetensors +
        # tokenizer.json) — real published weights + real BPE tokenizer
        # (models/hf_interop.py). The cfg/params args then come from it.
        if hf_checkpoint:
            from skypilot_tpu.models import hf_interop
            params, cfg = hf_interop.resolve_engine_inputs(
                hf_checkpoint, params, cfg)
        self.cfg = cfg or get_model_config(model)
        if quantize_kv:
            # int8 KV cache: half the cache memory (2x context/slots per
            # chip); the decode kernel dequantizes in-VMEM.
            from skypilot_tpu.models.config import with_int8_kv_cache
            self.cfg = with_int8_kv_cache(self.cfg)
        self.tokenizer = get_tokenizer(hf_checkpoint,
                                       require=bool(hf_checkpoint))
        if self.tokenizer.vocab_size > self.cfg.vocab_size:
            raise ValueError(
                f'Model vocab {self.cfg.vocab_size} < tokenizer '
                f'vocab {self.tokenizer.vocab_size}')
        self.max_batch = max_batch
        self._lock = threading.Lock()
        if params is not None:
            self.params = params
        elif checkpoint_dir:
            from skypilot_tpu.train.checkpoint import restore_latest
            restored = restore_latest(
                checkpoint_dir,
                lambda: llama.init_params(jax.random.key(seed), self.cfg))
            self.params = (restored['params']
                           if isinstance(restored, dict) and
                           'params' in restored else restored)
        else:
            self.params = llama.init_params(jax.random.key(seed), self.cfg)
        # Tensor-parallel serving: 'tensor=N' shards params over the
        # mesh (inference/sharding.py) — how flagship models span a slice.
        # Mesh placement FIRST: quantizing sharded params propagates the
        # shardings onto the int8/scale leaves, while device_put on an
        # already-quantized tree would choke on the squeezed scale axes.
        from skypilot_tpu.inference.sharding import prepare_engine
        self.params, self.cfg, self._mesh = prepare_engine(
            self.params, self.cfg, mesh)
        # W8A8 int8: halves weight HBM traffic on the decode path and
        # rides the MXU's 2x int8 throughput (models/quant.py).
        from skypilot_tpu.models.quant import maybe_quantize
        self.params = maybe_quantize(self.params, quantize)
        self.stats: Dict[str, float] = {
            'requests': 0, 'tokens_generated': 0, 'decode_seconds': 0.0}

    # ------------------------------------------------------------------

    def generate_ids(self, prompts: List[List[int]],
                     max_new_tokens: int = 32,
                     temperature: float = 0.0,
                     seed: int = 0) -> List[List[int]]:
        if not prompts:
            return []
        if len(prompts) > self.max_batch:
            out: List[List[int]] = []
            for i in range(0, len(prompts), self.max_batch):
                out.extend(self.generate_ids(
                    prompts[i:i + self.max_batch], max_new_tokens,
                    temperature, seed))
            return out
        b = len(prompts)
        lengths = np.array([len(p) for p in prompts], np.int32)
        s = _bucket(int(lengths.max()))
        n_new = _bucket(max_new_tokens, minimum=8)
        batch_b = _bucket(b, minimum=1)
        tokens = np.full((batch_b, s), self.tokenizer.pad_id, np.int32)
        for i, p in enumerate(prompts):
            tokens[i, :len(p)] = p
        pad_lengths = np.concatenate(
            [lengths, np.ones(batch_b - b, np.int32)])
        from skypilot_tpu.inference.sharding import mesh_context
        with self._lock, mesh_context(self._mesh):
            t0 = time.perf_counter()
            generated, gen_lengths = decode_lib.generate(
                self.params, jnp.asarray(tokens),
                jnp.asarray(pad_lengths), self.cfg,
                max_new_tokens=n_new, temperature=temperature,
                eos_id=self.tokenizer.eos_id,
                rng=jax.random.key(seed))
            generated = np.asarray(generated)
            gen_lengths = np.asarray(gen_lengths)
            elapsed = time.perf_counter() - t0
            self.stats['requests'] += b
            self.stats['tokens_generated'] += int(gen_lengths[:b].sum())
            self.stats['decode_seconds'] += elapsed
        return [
            generated[i, :min(int(gen_lengths[i]), max_new_tokens)].tolist()
            for i in range(b)
        ]

    def generate_text(self, prompts: List[str],
                      max_new_tokens: int = 32,
                      temperature: float = 0.0,
                      seed: int = 0) -> List[str]:
        ids = [self.tokenizer.encode(p) for p in prompts]
        outs = self.generate_ids(ids, max_new_tokens, temperature, seed)
        return [self.tokenizer.decode(o) for o in outs]

    # -- text embeddings (ref: llm/ embeddings + batch-inference
    # variants) ---------------------------------------------------------

    def embed_text(self, texts: List[str]) -> np.ndarray:
        """[len(texts), d_model] L2-normalized embeddings: final-layer
        hidden states (llama.forward(return_hidden=True) — the LM head
        matmul is skipped entirely), masked mean-pooled over the real
        tokens of each right-padded prompt. Shape-bucketed like
        generate, so each (batch, seq) bucket compiles once."""
        if not texts:
            return np.zeros((0, self.cfg.d_model), np.float32)
        if len(texts) > self.max_batch:
            parts = [self.embed_text(texts[i:i + self.max_batch])
                     for i in range(0, len(texts), self.max_batch)]
            return np.concatenate(parts, axis=0)
        ids = [self.tokenizer.encode(t)[:self.cfg.max_seq_len]
               for t in texts]
        b = len(ids)
        lengths = np.array([max(len(p), 1) for p in ids], np.int32)
        s = _bucket(int(lengths.max()))
        batch_b = _bucket(b, minimum=1)
        tokens = np.full((batch_b, s), self.tokenizer.pad_id, np.int32)
        for i, p in enumerate(ids):
            tokens[i, :len(p)] = p
        pad_lengths = np.concatenate(
            [lengths, np.ones(batch_b - b, np.int32)])
        from skypilot_tpu.inference.sharding import mesh_context
        with self._lock, mesh_context(self._mesh):
            pooled = _embed_pooled(self.params, jnp.asarray(tokens),
                                   jnp.asarray(pad_lengths), self.cfg)
            self.stats['requests'] += b
        return np.asarray(pooled)[:b]
