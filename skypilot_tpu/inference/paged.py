"""Host-side bookkeeping for the paged KV cache: block pool + prefix cache.

The device side (``models/decode.py`` ``PagedKVCache``) is pure data —
fixed-size block pool arrays and per-slot block tables. The policy
lives here, on the serving-loop thread:

* ``BlockPool`` — refcounted free-list allocator over pool block ids.
  Block 0 is reserved as the null block (masked/inactive writes land
  there; unused table entries point at it), so it is never handed out.
* ``PrefixCache`` — digest-chain keyed, read-only, block-granular
  sharing of prompt prefixes (the vLLM/SGLang prefix-caching shape): a
  full block of prompt tokens is keyed by (parent digest, its token
  tuple), so a shared system prompt prefills once and later requests
  reference the same pool blocks copy-on-write style. Decode never
  writes into a shared block: only FULL prompt blocks are ever shared,
  and a slot's tail block is always private. Entries hold their own
  block reference; LRU eviction releases it back to the pool when HBM
  pressure needs the block. Chains are ROOTED: multi-LoRA serving
  salts the chain root per adapter (LoRA v-deltas make cached V rows
  adapter-specific), so adapters never cross-hit each other's blocks
  while the base-model chains (root 0) behave exactly as before.
* ``AdapterPagePool`` — S-LoRA-style unified paging: a resident LoRA
  adapter charges ``ceil(bytes / block_bytes)`` blocks against the
  SAME :class:`BlockPool` as KV while it holds one of the fixed device
  page slots, so the adapter working set and the KV working set
  compete for one HBM budget instead of two static carve-outs.

All structures are single-threaded by design — they are only touched
from the engine's serving loop.
"""
from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Callable, List, Optional, Sequence, Tuple

NULL_BLOCK = 0


class BlockPool:
    """Refcounted allocator over pool block ids 1..num_blocks-1."""

    def __init__(self, num_blocks: int) -> None:
        if num_blocks < 2:
            raise ValueError('BlockPool needs >= 2 blocks '
                             '(block 0 is the reserved null block)')
        self.num_blocks = num_blocks
        # pop() order: 1, 2, 3, ... — deterministic for tests/benches.
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref = [0] * num_blocks
        # Bumped on every alloc/incref/decref: lets the engine skip
        # re-running admission work for an HBM-blocked request until
        # pool state could actually have changed.
        self.version = 0

    @property
    def total_blocks(self) -> int:
        """Allocatable blocks (the null block is not)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def alloc(self) -> Optional[int]:
        """One block at refcount 1, or None when the pool is empty."""
        if not self._free:
            return None
        block = self._free.pop()
        self._ref[block] = 1
        self.version += 1
        return block

    def incref(self, block: int) -> None:
        if block == NULL_BLOCK or self._ref[block] <= 0:
            raise ValueError(f'incref of unallocated block {block}')
        self._ref[block] += 1
        self.version += 1

    def decref(self, block: int) -> None:
        if block == NULL_BLOCK or self._ref[block] <= 0:
            raise ValueError(f'double free of block {block}')
        self._ref[block] -= 1
        self.version += 1
        if self._ref[block] == 0:
            self._free.append(block)

    def refcount(self, block: int) -> int:
        return self._ref[block]


@dataclasses.dataclass
class _PrefixEntry:
    block: int
    parent: int           # parent digest (0 = chain root)
    tokens: Tuple[int, ...]


class PrefixCache:
    """Digest-chain keyed read-only block sharing.

    Keying: block i of a prompt is identified by a rolling digest
    ``hash((parent_digest, tokens[i*bs:(i+1)*bs]))``. Lookups walk the
    chain from the root and verify BOTH the stored token tuple and the
    parent link before trusting an entry, so hash collisions degrade to
    a cache miss, never to wrong KV. Entries are LRU-ordered; eviction
    drops the entry's block reference back to the pool.
    """

    def __init__(self, pool: BlockPool, block_size: int,
                 max_entries: int = 4096) -> None:
        self._pool = pool
        self._block_size = block_size
        self._max_entries = max_entries
        self._entries: 'OrderedDict[int, _PrefixEntry]' = OrderedDict()

    @property
    def cached_blocks(self) -> int:
        return len(self._entries)

    @staticmethod
    def _digest(parent: int, tokens: Tuple[int, ...]) -> int:
        return hash((parent, tokens))

    def lookup(self, ids: Sequence[int], limit_tokens: int,
               root: int = 0) -> List[int]:
        """Longest cached full-block prefix of ``ids`` covering at most
        ``limit_tokens`` tokens. Increfs and returns the matched block
        ids (caller owns the references). ``root`` seeds the chain
        (0 = base model; adapter-salted roots keep per-adapter KV
        chains disjoint)."""
        bs = self._block_size
        matched: List[int] = []
        parent = root
        for i in range(min(len(ids), limit_tokens) // bs):
            tokens = tuple(ids[i * bs:(i + 1) * bs])
            digest = self._digest(parent, tokens)
            entry = self._entries.get(digest)
            if (entry is None or entry.tokens != tokens or
                    entry.parent != parent):
                break
            self._entries.move_to_end(digest)
            self._pool.incref(entry.block)
            matched.append(entry.block)
            parent = digest
        return matched

    def resident_chain(self, ids: Sequence[int],
                       root: int = 0) -> List[int]:
        """Chain digests of the cached full-block prefix of ``ids`` —
        strictly read-only (no incref, no LRU touch), so the decode
        side of a KV migration can plan its delta manifest from
        OUTSIDE the serving loop. Residency can change before the
        import lands; the import transaction re-walks the chain and
        falls back to re-prefill on a shrink."""
        bs = self._block_size
        out: List[int] = []
        parent = root
        for i in range(len(ids) // bs):
            tokens = tuple(ids[i * bs:(i + 1) * bs])
            digest = self._digest(parent, tokens)
            entry = self._entries.get(digest)
            if (entry is None or entry.tokens != tokens or
                    entry.parent != parent):
                break
            out.append(digest)
            parent = digest
        return out

    def insert(self, ids: Sequence[int], blocks: Sequence[int],
               root: int = 0) -> None:
        """Register the full blocks of a freshly prefilled prompt.

        ``blocks`` is the slot's block list (shared prefix first, then
        private). Blocks already cached along the chain are skipped —
        the existing shared copy stays canonical. ``root`` must match
        the salt the prompt was prefilled under (see :meth:`lookup`)."""
        bs = self._block_size
        parent = root
        for i in range(len(ids) // bs):
            if i >= len(blocks):
                break
            tokens = tuple(ids[i * bs:(i + 1) * bs])
            digest = self._digest(parent, tokens)
            entry = self._entries.get(digest)
            if (entry is not None and entry.tokens == tokens and
                    entry.parent == parent):
                self._entries.move_to_end(digest)
                parent = digest
                continue
            if entry is not None:
                # Digest collision with a different chain: leave the
                # resident entry alone (collisions are misses, never
                # corruption) and stop extending this chain.
                break
            self._pool.incref(blocks[i])
            self._entries[digest] = _PrefixEntry(
                block=blocks[i], parent=parent, tokens=tokens)
            parent = digest
            while len(self._entries) > self._max_entries:
                self.evict_one()

    @property
    def reclaimable_blocks(self) -> int:
        """Entries whose block only the cache holds — evicting one of
        these actually frees a pool block (entries shared with live
        slots free nothing until the slots finish)."""
        return sum(1 for e in self._entries.values()
                   if self._pool.refcount(e.block) == 1)

    def evict_one(self) -> bool:
        """Drop the least-recently-used entry (and its block ref).
        Returns False when the cache is empty. Used for the entry-count
        cap; under POOL pressure use ``evict_reclaimable`` instead."""
        if not self._entries:
            return False
        _, entry = self._entries.popitem(last=False)
        self._pool.decref(entry.block)
        return True

    def evict_reclaimable(self) -> bool:
        """Evict the LRU entry whose block the cache alone holds, so
        the eviction actually returns a block to the free list.
        Returns False when no entry is reclaimable — evicting entries
        shared with active slots would wipe reusable prefix chains
        without freeing a single block."""
        for digest, entry in self._entries.items():  # LRU order
            if self._pool.refcount(entry.block) == 1:
                del self._entries[digest]
                self._pool.decref(entry.block)
                return True
        return False

    def clear(self) -> None:
        while self.evict_one():
            pass


# ---------------------------------------------------------------------
# KV-block migration bookkeeping (disaggregated prefill/decode serving)
# ---------------------------------------------------------------------


def chain_digests(ids: Sequence[int], block_size: int,
                  root: int = 0) -> List[int]:
    """Rolling chain digest of every FULL block of ``ids`` — the same
    keying :class:`PrefixCache` uses, exported for the KV-migration
    delta manifest: a block is resident on the decode side iff its
    chain digest (and token tuple, verified by the cache walk) already
    has an entry there, so only non-resident blocks ever move.
    ``root`` carries the adapter salt so migrated adapter KV never
    aliases base-model chains."""
    out: List[int] = []
    parent = root
    for i in range(len(ids) // block_size):
        tokens = tuple(ids[i * block_size:(i + 1) * block_size])
        parent = PrefixCache._digest(parent, tokens)  # noqa: SLF001
        out.append(parent)
    return out


class BlockImporter:
    """All-or-nothing block acquisition for a KV-block import.

    A migration import must be *refcount-exact*: if the transfer dies
    mid-flight (peer death, corrupt payload, timeout), the pool and
    prefix cache must be returned to EXACTLY their pre-import state —
    same refcounts, same cached entries — so the request can fall back
    to a local re-prefill with zero leaked blocks (the r13 speculative
    rollback discipline, applied to migration).

    Usage::

        importer = BlockImporter(pool, prefix)
        got = importer.begin(ids, needed_total, block_size=bs)
        if got is None:       # pool can't fit it right now; nothing held
            ...
        blocks, n_resident = got
        try:
            ... copy the non-resident block payloads in ...
            importer.commit()     # refs now owned by the caller's slot
        except Exception:
            importer.abort()      # exact pre-import state restored
            raise
    """

    def __init__(self, pool: BlockPool,
                 prefix: Optional[PrefixCache] = None) -> None:
        self._pool = pool
        self._prefix = prefix
        self._resident: List[int] = []
        self._allocated: List[int] = []
        self._active = False

    @property
    def active(self) -> bool:
        return self._active

    def begin(self, ids: Sequence[int], needed_total: int, *,
              block_size: int,
              alloc: Optional[Callable[[], Optional[int]]] = None,
              root: int = 0
              ) -> Optional[Tuple[List[int], int]]:
        """Acquire ``needed_total`` blocks for token sequence ``ids``:
        the cached full-block prefix first (shared — increfed through
        the prefix cache, these blocks' payloads never move), then
        freshly allocated private blocks for the remainder. Returns
        ``(blocks, n_resident)``, or ``None`` when the pool cannot
        supply the private blocks right now — in which case NOTHING is
        retained (the failed attempt is invisible to the pool beyond
        its version counter).

        ``alloc`` overrides the raw allocator (the engine passes its
        prefix-evicting ``_alloc_block``)."""
        if self._active:
            raise RuntimeError('BlockImporter already has an open import')
        if alloc is None:
            alloc = self._pool.alloc
        resident: List[int] = []
        if self._prefix is not None:
            limit = min(len(ids), needed_total * block_size)
            resident = self._prefix.lookup(ids, limit_tokens=limit,
                                           root=root)
        self._resident = resident
        self._allocated = []
        self._active = True
        while len(resident) + len(self._allocated) < needed_total:
            block = alloc()
            if block is None:
                self.abort()
                return None
            self._allocated.append(block)
        return list(resident) + list(self._allocated), len(resident)

    def commit(self) -> None:
        """The import landed: the caller's slot now owns every
        reference this importer took."""
        self._resident = []
        self._allocated = []
        self._active = False

    def abort(self) -> None:
        """Undo every reference this import took, newest first —
        refcounts and prefix-cache entries end exactly where they were
        before :meth:`begin`. Idempotent; a no-op after commit."""
        for block in reversed(self._allocated):
            self._pool.decref(block)
        for block in reversed(self._resident):
            self._pool.decref(block)
        self._resident = []
        self._allocated = []
        self._active = False


# ---------------------------------------------------------------------
# Multi-LoRA unified paging (adapter weight pages in the KV pool)
# ---------------------------------------------------------------------


def adapter_chain_root(adapter: Optional[str]) -> int:
    """Prefix-chain root salt for an adapter (0 = base model).

    LoRA v-projection deltas make cached V rows adapter-specific, so
    each adapter's prefix chains must be disjoint from the base chains
    and from every other adapter's. Never 0 for a named adapter."""
    if not adapter:
        return 0
    return hash(('skyt-lora-root', adapter)) or 1


@dataclasses.dataclass
class _AdapterResidency:
    page: int             # device page-slot index (1..n_pages)
    blocks: List[int]     # charge blocks held against the shared pool
    pins: int = 0         # live slots currently decoding this adapter


class AdapterPagePool:
    """Host-side policy for adapter weight pages in the shared pool.

    The device side is a fixed stack of adapter page slots
    (``models/lora.init_adapter_pages``; page 0 = base model, all
    zeros). This class decides which adapter owns which page slot and
    makes residency COST something: a resident adapter charges
    ``ceil(nbytes / block_bytes)`` blocks against the same
    :class:`BlockPool` the KV cache allocates from, held for as long
    as the adapter is resident. A cold adapter therefore costs a pull
    (host -> device upload into a page slot, possibly after LRU
    eviction of an idle adapter), never a dedicated fleet — and KV
    pressure and adapter pressure degrade each other gracefully
    instead of one budget silently starving the other.

    Refcount-exact by the same discipline as :class:`BlockImporter`:
    a failed admission leaves the pool untouched, and evicting every
    resident returns the pool to exactly its prior free count (the
    teardown accounting tests assert this).

    Pinning: a slot actively decoding with an adapter pins its
    residency — pinned adapters are never evicted, so a mid-request
    page can't be overwritten under the jitted step. Single-threaded
    by design (serving-loop only), like the rest of this module.
    """

    def __init__(self, pool: BlockPool, n_pages: int,
                 block_bytes: int) -> None:
        if n_pages < 1:
            raise ValueError('AdapterPagePool needs >= 1 page slot')
        if block_bytes < 1:
            raise ValueError('block_bytes must be >= 1')
        self._pool = pool
        self.n_pages = n_pages
        self.block_bytes = block_bytes
        # pop() order 1, 2, ... — deterministic, page 0 is the base.
        self._free_pages: List[int] = list(range(n_pages, 0, -1))
        self._resident: 'OrderedDict[str, _AdapterResidency]' = \
            OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def resident_pages(self) -> int:
        return len(self._resident)

    @property
    def blocks_charged(self) -> int:
        return sum(len(r.blocks) for r in self._resident.values())

    def blocks_for(self, nbytes: int) -> int:
        """Charge-block count for an adapter of ``nbytes`` weights."""
        return max(1, -(-int(nbytes) // self.block_bytes))

    def resident_names(self) -> List[str]:
        return list(self._resident)

    def page_of(self, name: str) -> Optional[int]:
        """Page index if resident (no LRU touch, no hit counting)."""
        entry = self._resident.get(name)
        return entry.page if entry is not None else None

    def lookup(self, name: str) -> Optional[int]:
        """Residency check on the request path: bumps LRU recency and
        the hit/miss counters."""
        entry = self._resident.get(name)
        if entry is None:
            self.misses += 1
            return None
        self._resident.move_to_end(name)
        self.hits += 1
        return entry.page

    def admit(self, name: str, nbytes: int, *,
              alloc: Optional[Callable[[], Optional[int]]] = None,
              on_evict: Optional[Callable[[str], None]] = None
              ) -> Optional[int]:
        """Make ``name`` resident: claim a page slot (LRU-evicting idle
        adapters if every slot is taken) and the charge blocks.
        Returns the page index, or None when it can't fit right now —
        every page pinned, or the pool can't supply the charge blocks
        even after evicting idle adapters. A None return retains
        nothing. Raises when the adapter can NEVER fit the pool.

        ``alloc`` overrides the raw allocator (the engine passes its
        prefix-evicting ``_alloc_block``); ``on_evict`` observes each
        LRU eviction (chaos hook + bookkeeping) BEFORE it mutates."""
        if name in self._resident:
            raise ValueError(f'adapter {name!r} is already resident')
        if alloc is None:
            alloc = self._pool.alloc
        need = self.blocks_for(nbytes)
        if need > self._pool.total_blocks:
            raise ValueError(
                f'adapter {name!r} needs {need} charge blocks; pool '
                f'has {self._pool.total_blocks} total')
        while not self._free_pages:
            if self.evict_lru(on_evict=on_evict) is None:
                return None
        blocks: List[int] = []
        try:
            while len(blocks) < need:
                block = alloc()
                if block is not None:
                    blocks.append(block)
                    continue
                if self.evict_lru(on_evict=on_evict) is None:
                    for held in reversed(blocks):
                        self._pool.decref(held)
                    return None
        except BaseException:
            # A raising alloc/on_evict (chaos hooks) must not leak the
            # charge blocks already held for this failed admission.
            for held in reversed(blocks):
                self._pool.decref(held)
            raise
        page = self._free_pages.pop()
        self._resident[name] = _AdapterResidency(page=page,
                                                 blocks=blocks)
        return page

    def evict_lru(self, on_evict: Optional[Callable[[str], None]] = None
                  ) -> Optional[str]:
        """Evict the least-recently-used UNPINNED resident back to the
        host store: page slot and charge blocks return to their free
        lists. Returns the evicted name, or None when every resident
        is pinned (nothing evictable)."""
        for name, entry in self._resident.items():   # LRU order
            if entry.pins:
                continue
            if on_evict is not None:
                on_evict(name)        # may raise; nothing mutated yet
            del self._resident[name]
            for block in reversed(entry.blocks):
                self._pool.decref(block)
            self._free_pages.append(entry.page)
            self.evictions += 1
            return name
        return None

    def pin(self, name: str) -> None:
        entry = self._resident.get(name)
        if entry is None:
            raise ValueError(f'pin of non-resident adapter {name!r}')
        entry.pins += 1
        # Pin state gates admissibility just like refcounts do: bump
        # the pool version so HBM-blocked admission retries re-run
        # when a pin drops.
        self._pool.version += 1

    def unpin(self, name: str) -> None:
        entry = self._resident.get(name)
        if entry is None or entry.pins <= 0:
            raise ValueError(f'unpin of unpinned adapter {name!r}')
        entry.pins -= 1
        self._pool.version += 1

    def pins(self, name: str) -> int:
        entry = self._resident.get(name)
        return entry.pins if entry is not None else 0

    def clear(self) -> None:
        """Evict every unpinned resident (teardown accounting)."""
        while self.evict_lru() is not None:
            pass
