"""Host-side bookkeeping for the paged KV cache: block pool + prefix cache.

The device side (``models/decode.py`` ``PagedKVCache``) is pure data —
fixed-size block pool arrays and per-slot block tables. The policy
lives here, on the serving-loop thread:

* ``BlockPool`` — refcounted free-list allocator over pool block ids.
  Block 0 is reserved as the null block (masked/inactive writes land
  there; unused table entries point at it), so it is never handed out.
* ``PrefixCache`` — digest-chain keyed, read-only, block-granular
  sharing of prompt prefixes (the vLLM/SGLang prefix-caching shape): a
  full block of prompt tokens is keyed by (parent digest, its token
  tuple), so a shared system prompt prefills once and later requests
  reference the same pool blocks copy-on-write style. Decode never
  writes into a shared block: only FULL prompt blocks are ever shared,
  and a slot's tail block is always private. Entries hold their own
  block reference; LRU eviction releases it back to the pool when HBM
  pressure needs the block.

Both structures are single-threaded by design — they are only touched
from the engine's serving loop.
"""
from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

NULL_BLOCK = 0


class BlockPool:
    """Refcounted allocator over pool block ids 1..num_blocks-1."""

    def __init__(self, num_blocks: int) -> None:
        if num_blocks < 2:
            raise ValueError('BlockPool needs >= 2 blocks '
                             '(block 0 is the reserved null block)')
        self.num_blocks = num_blocks
        # pop() order: 1, 2, 3, ... — deterministic for tests/benches.
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref = [0] * num_blocks
        # Bumped on every alloc/incref/decref: lets the engine skip
        # re-running admission work for an HBM-blocked request until
        # pool state could actually have changed.
        self.version = 0

    @property
    def total_blocks(self) -> int:
        """Allocatable blocks (the null block is not)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def alloc(self) -> Optional[int]:
        """One block at refcount 1, or None when the pool is empty."""
        if not self._free:
            return None
        block = self._free.pop()
        self._ref[block] = 1
        self.version += 1
        return block

    def incref(self, block: int) -> None:
        if block == NULL_BLOCK or self._ref[block] <= 0:
            raise ValueError(f'incref of unallocated block {block}')
        self._ref[block] += 1
        self.version += 1

    def decref(self, block: int) -> None:
        if block == NULL_BLOCK or self._ref[block] <= 0:
            raise ValueError(f'double free of block {block}')
        self._ref[block] -= 1
        self.version += 1
        if self._ref[block] == 0:
            self._free.append(block)

    def refcount(self, block: int) -> int:
        return self._ref[block]


@dataclasses.dataclass
class _PrefixEntry:
    block: int
    parent: int           # parent digest (0 = chain root)
    tokens: Tuple[int, ...]


class PrefixCache:
    """Digest-chain keyed read-only block sharing.

    Keying: block i of a prompt is identified by a rolling digest
    ``hash((parent_digest, tokens[i*bs:(i+1)*bs]))``. Lookups walk the
    chain from the root and verify BOTH the stored token tuple and the
    parent link before trusting an entry, so hash collisions degrade to
    a cache miss, never to wrong KV. Entries are LRU-ordered; eviction
    drops the entry's block reference back to the pool.
    """

    def __init__(self, pool: BlockPool, block_size: int,
                 max_entries: int = 4096) -> None:
        self._pool = pool
        self._block_size = block_size
        self._max_entries = max_entries
        self._entries: 'OrderedDict[int, _PrefixEntry]' = OrderedDict()

    @property
    def cached_blocks(self) -> int:
        return len(self._entries)

    @staticmethod
    def _digest(parent: int, tokens: Tuple[int, ...]) -> int:
        return hash((parent, tokens))

    def lookup(self, ids: Sequence[int], limit_tokens: int
               ) -> List[int]:
        """Longest cached full-block prefix of ``ids`` covering at most
        ``limit_tokens`` tokens. Increfs and returns the matched block
        ids (caller owns the references)."""
        bs = self._block_size
        matched: List[int] = []
        parent = 0
        for i in range(min(len(ids), limit_tokens) // bs):
            tokens = tuple(ids[i * bs:(i + 1) * bs])
            digest = self._digest(parent, tokens)
            entry = self._entries.get(digest)
            if (entry is None or entry.tokens != tokens or
                    entry.parent != parent):
                break
            self._entries.move_to_end(digest)
            self._pool.incref(entry.block)
            matched.append(entry.block)
            parent = digest
        return matched

    def insert(self, ids: Sequence[int], blocks: Sequence[int]) -> None:
        """Register the full blocks of a freshly prefilled prompt.

        ``blocks`` is the slot's block list (shared prefix first, then
        private). Blocks already cached along the chain are skipped —
        the existing shared copy stays canonical."""
        bs = self._block_size
        parent = 0
        for i in range(len(ids) // bs):
            if i >= len(blocks):
                break
            tokens = tuple(ids[i * bs:(i + 1) * bs])
            digest = self._digest(parent, tokens)
            entry = self._entries.get(digest)
            if (entry is not None and entry.tokens == tokens and
                    entry.parent == parent):
                self._entries.move_to_end(digest)
                parent = digest
                continue
            if entry is not None:
                # Digest collision with a different chain: leave the
                # resident entry alone (collisions are misses, never
                # corruption) and stop extending this chain.
                break
            self._pool.incref(blocks[i])
            self._entries[digest] = _PrefixEntry(
                block=blocks[i], parent=parent, tokens=tokens)
            parent = digest
            while len(self._entries) > self._max_entries:
                self.evict_one()

    @property
    def reclaimable_blocks(self) -> int:
        """Entries whose block only the cache holds — evicting one of
        these actually frees a pool block (entries shared with live
        slots free nothing until the slots finish)."""
        return sum(1 for e in self._entries.values()
                   if self._pool.refcount(e.block) == 1)

    def evict_one(self) -> bool:
        """Drop the least-recently-used entry (and its block ref).
        Returns False when the cache is empty. Used for the entry-count
        cap; under POOL pressure use ``evict_reclaimable`` instead."""
        if not self._entries:
            return False
        _, entry = self._entries.popitem(last=False)
        self._pool.decref(entry.block)
        return True

    def evict_reclaimable(self) -> bool:
        """Evict the LRU entry whose block the cache alone holds, so
        the eviction actually returns a block to the free list.
        Returns False when no entry is reclaimable — evicting entries
        shared with active slots would wipe reusable prefix chains
        without freeing a single block."""
        for digest, entry in self._entries.items():  # LRU order
            if self._pool.refcount(entry.block) == 1:
                del self._entries[digest]
                self._pool.decref(entry.block)
                return True
        return False

    def clear(self) -> None:
        while self.evict_one():
            pass
