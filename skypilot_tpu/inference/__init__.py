"""In-tree TPU serving payload: tokenizer, batched KV-cache engine, HTTP
server (the reference serves LLMs through external engines -- vLLM /
JetStream YAMLs under ``llm/`` and ``examples/tpu/v6e``; SURVEY.md §7
makes the TPU-native equivalent an in-tree deliverable)."""
from skypilot_tpu.inference.engine import InferenceEngine
from skypilot_tpu.inference.tokenizer import ByteTokenizer

__all__ = ['InferenceEngine', 'ByteTokenizer']
