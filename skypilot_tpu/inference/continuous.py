"""Continuous batching over a paged KV pool with chunked prefill.

The JetStream/vLLM serving core, TPU-first, three layers deep:

* **Paged KV pool** (vLLM PagedAttention shape): instead of one
  ``max_slots * max_len`` monolithic cache, KV lives in a fixed pool of
  ``block_size``-token blocks; each slot maps logical positions through
  a block table, so a sequence consumes HBM proportional to its actual
  length and ``max_slots`` can rise several-fold at the same HBM.
  Shapes stay static — the pool block count is fixed and the jitted
  step gathers/scatters by block index — so nothing recompiles as
  traffic changes.
* **Chunked prefill** (Sarathi-Serve shape): a prompt is absorbed in
  fixed-size chunks interleaved between decode steps instead of one
  inline whole-prompt prefill, so inter-token latency for active
  decoders is bounded by the chunk budget, not by arriving prompt
  length.
* **Prefix cache**: full prompt blocks are digest-keyed and shared
  read-only across requests (``inference/paged.py``) — a common system
  prompt prefills once; later requests reference the same blocks
  copy-on-write style and only compute their private suffix.

Decode is ONE jitted program stepping all slots together; the scheduler
thread admits requests into free slots as others finish. Public
surface (``generate_ids``/``stream_ids``/...) is unchanged from the
monolithic-cache engine.
"""
from __future__ import annotations

import functools
import math
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.inference.paged import BlockPool, PrefixCache
from skypilot_tpu.inference.tokenizer import get_tokenizer
from skypilot_tpu.models import decode as decode_lib
from skypilot_tpu.models import llama
from skypilot_tpu.models.config import ModelConfig, get_model_config
from skypilot_tpu.utils import log

logger = log.init_logger(__name__)

DEFAULT_BLOCK_SIZE = 16
DEFAULT_PREFILL_CHUNK = 64


# Module-level jitted steps with the (frozen, hashable) ModelConfig as
# a static arg: every engine with the same config + shapes shares one
# compiled program — repeated engine construction (tests, serving
# restarts) stops paying XLA compilation over and over.

@functools.partial(jax.jit, static_argnames=('cfg',))
def _decode_all_step(params, last_logits, cache, active, temps, rngs,
                     *, cfg):
    """One step for every slot: sample from last logits, advance."""
    keys = jax.vmap(jax.random.fold_in)(rngs, cache.lengths)
    greedy = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
    sampled = jax.vmap(
        lambda k, l, t: jax.random.categorical(
            k, l / jnp.maximum(t, 1e-6)))(keys, last_logits,
                                          temps).astype(jnp.int32)
    tokens = jnp.where(temps <= 0.0, greedy, sampled)
    logits, cache = decode_lib.paged_decode_step(
        params, tokens, cache, cfg, active=active)
    return tokens, logits, cache


@functools.partial(jax.jit, static_argnames=('cfg',))
def _prefill_chunk_step(params, tokens, start, n_new, slot, cache,
                        *, cfg):
    return decode_lib.prefill_chunk(params, tokens, start, n_new,
                                    slot, cache, cfg)


class _Request:
    def __init__(self, token_ids: List[int], max_new_tokens: int,
                 temperature: float, eos_id: Optional[int],
                 seed: int, trace_ctx=None) -> None:
        self.token_ids = token_ids
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.eos_id = eos_id
        self.seed = seed
        self.arrival = time.monotonic()
        self.arrival_wall = time.time()
        self.admitted = False  # queue-wait counted once, not per resume
        self.generated: List[int] = []
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        # Distributed tracing (armed deployments with an incoming
        # context only): the per-request engine span; queue-wait /
        # prefill-chunk / decode / preempt child spans hang off it.
        self.span = None
        self.decode_start_wall: Optional[float] = None
        self.decode_start_mono: Optional[float] = None


class _PrefillState:
    """A slot mid-prefill: ``pos`` = next index of ``ids`` to absorb.

    ``ids`` is the prompt PLUS any tokens generated before a
    preemption: a preempted request resumes by re-prefilling its whole
    visible sequence (chunked, possibly prefix-cache-accelerated) and
    continuing to decode — sampling folds the rng into the position,
    so the rng stream is exactly what it would have been. (The resume
    logits come through the chunk-prefill attention rather than the
    decode kernel; on backends where those reductions differ by ULPs,
    a near-tie at temperature>0 can still resolve differently.)"""

    def __init__(self, request: _Request, slot: int, pos: int,
                 ids: List[int]) -> None:
        self.request = request
        self.slot = slot
        self.pos = pos
        self.ids = ids


class ContinuousBatchingEngine:
    """generate() admits into the shared decode loop; thread-safe."""

    def __init__(self,
                 model: str = 'tiny',
                 *,
                 cfg: Optional[ModelConfig] = None,
                 params: Optional[Any] = None,
                 checkpoint_dir: Optional[str] = None,
                 hf_checkpoint: Optional[str] = None,
                 max_slots: int = 4,
                 max_len: Optional[int] = None,
                 block_size: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 seed: int = 0,
                 quantize: bool = False,
                 quantize_kv: bool = False,
                 mesh: Optional[Any] = None) -> None:
        # Real-weights path: see engine.py (models/hf_interop.py).
        if hf_checkpoint:
            from skypilot_tpu.models import hf_interop
            params, cfg = hf_interop.resolve_engine_inputs(
                hf_checkpoint, params, cfg)
        self.cfg = cfg or get_model_config(model)
        if quantize_kv:
            from skypilot_tpu.models.config import with_int8_kv_cache
            self.cfg = with_int8_kv_cache(self.cfg)
        self.tokenizer = get_tokenizer(hf_checkpoint,
                                       require=bool(hf_checkpoint))
        if self.tokenizer.vocab_size > self.cfg.vocab_size:
            raise ValueError(
                f'Model vocab {self.cfg.vocab_size} < tokenizer '
                f'vocab {self.tokenizer.vocab_size}')
        self.max_slots = max_slots
        self.max_len = min(max_len or self.cfg.max_seq_len,
                           self.cfg.max_seq_len)
        from skypilot_tpu.utils import env_registry
        self.block_size = (block_size or
                           env_registry.get_int('SKYT_INFER_BLOCK_SIZE',
                                                default=DEFAULT_BLOCK_SIZE))
        if self.block_size < 1:
            raise ValueError(f'block_size must be >= 1, got '
                             f'{self.block_size}')
        self.prefill_chunk = max(1, min(
            prefill_chunk or env_registry.get_int(
                'SKYT_INFER_PREFILL_CHUNK',
                default=DEFAULT_PREFILL_CHUNK),
            self.max_len))
        self.blocks_per_slot = math.ceil(self.max_len / self.block_size)
        # Default pool = the HBM the monolithic max_slots*max_len cache
        # used (+1 for the reserved null block). Block granularity +
        # prefix sharing is what lets max_slots rise at the same HBM.
        self.num_blocks = (num_blocks or
                           max_slots * self.blocks_per_slot + 1)
        if params is not None:
            self.params = params
        elif checkpoint_dir:
            from skypilot_tpu.train.checkpoint import restore_latest
            restored = restore_latest(
                checkpoint_dir,
                lambda: llama.init_params(jax.random.key(seed), self.cfg))
            self.params = (restored['params']
                           if isinstance(restored, dict) and
                           'params' in restored else restored)
        else:
            self.params = llama.init_params(jax.random.key(seed),
                                            self.cfg)
        # Mesh placement first, then quantization (see engine.py note).
        from skypilot_tpu.inference.sharding import (prepare_engine,
                                                     shard_paged_cache)
        self.params, self.cfg, self._mesh = prepare_engine(
            self.params, self.cfg, mesh)
        from skypilot_tpu.models.quant import maybe_quantize
        self.params = maybe_quantize(self.params, quantize)
        self.cache = shard_paged_cache(
            decode_lib.init_paged_cache(self.cfg, self.num_blocks,
                                        self.block_size, max_slots,
                                        self.blocks_per_slot),
            self._mesh, self.cfg)
        # Host-side bookkeeping (serving-loop thread only).
        self._pool = BlockPool(self.num_blocks)
        self._prefix: Optional[PrefixCache] = (
            PrefixCache(self._pool, self.block_size)
            if prefix_cache and self.block_size <= self.max_len else None)
        self._host_bt = np.zeros((max_slots, self.blocks_per_slot),
                                 np.int32)
        self._host_len = np.zeros((max_slots,), np.int64)
        self._slot_blocks: List[List[int]] = [[] for _ in
                                              range(max_slots)]
        self._bt_dirty = False
        self._slots: List[Optional[_Request]] = [None] * max_slots
        self._decoding = [False] * max_slots
        self._admit_order = [0] * max_slots  # preemption victim pick
        self._admit_seq = 0
        self._prefilling: List[_PrefillState] = []
        self._waiting: List[_Request] = []  # admitted FIFO, blocked on HBM
        # Pool version at the last admission attempt that failed on
        # HBM pressure: until it changes, retrying is pure waste
        # (prefix re-hash + reclaimable scan on the serving loop).
        self._blocked_at_version: Optional[int] = None
        self._rngs = [jax.random.key(seed + 1 + i)
                      for i in range(max_slots)]
        self._last_logits = jnp.zeros((max_slots, self.cfg.vocab_size),
                                      jnp.float32)
        self._pending: 'queue.Queue[_Request]' = queue.Queue()
        # Counters (monotonic; surfaced as Prometheus counters).
        self._requests_total = 0
        self._completions_total = 0
        self._errors_total = 0
        self._prefill_errors_total = 0
        self._prefill_chunks_total = 0
        self._tokens_total = 0
        self._decode_seconds_total = 0.0
        self._queue_wait_seconds_total = 0.0
        self._prefix_hits_total = 0
        self._prefix_misses_total = 0
        self._prefix_tokens_reused_total = 0
        self._preemptions_total = 0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name='continuous-batching',
                                        daemon=True)
        self._decode_fn = functools.partial(_decode_all_step,
                                            cfg=self.cfg)
        self._prefill_fn = functools.partial(_prefill_chunk_step,
                                             cfg=self.cfg)
        self._thread.start()

    # -- block-table plumbing -------------------------------------------

    def _sync_tables(self) -> None:
        """Push host block-table/length edits to the device cache."""
        if not self._bt_dirty:
            return
        import dataclasses
        self.cache = dataclasses.replace(
            self.cache,
            block_tables=jnp.asarray(self._host_bt),
            lengths=jnp.asarray(self._host_len, np.int32))
        self._bt_dirty = False

    def _alloc_block(self) -> Optional[int]:
        """Pool alloc with prefix-cache LRU eviction under pressure.
        Only reclaimable entries are evicted — dropping entries whose
        blocks live slots still share frees nothing and would wipe the
        reusable prefix chains exactly when the pool is busiest."""
        block = self._pool.alloc()
        while block is None and self._prefix is not None:
            if not self._prefix.evict_reclaimable():
                break
            block = self._pool.alloc()
        return block

    def _release_slot(self, slot: int) -> None:
        for block in self._slot_blocks[slot]:
            self._pool.decref(block)
        self._slot_blocks[slot] = []
        self._host_bt[slot, :] = 0
        self._host_len[slot] = 0
        self._slots[slot] = None
        self._decoding[slot] = False
        self._bt_dirty = True

    def _finish(self, request: _Request,
                error: Optional[BaseException] = None) -> None:
        """Single exit point: keeps requests == completions + errors +
        in-flight, whatever path a request dies on."""
        if error is not None:
            request.error = error
            self._errors_total += 1
        else:
            self._completions_total += 1
        if request.span is not None:
            self._record_decode_segment(request)
            request.span.finish(error=error,
                                tokens=len(request.generated))
            request.span = None
        request.done.set()

    @staticmethod
    def _record_decode_segment(request: _Request) -> None:
        """Close the current infer.decode segment (finish OR preempt).
        Segments end at preemption — otherwise one span would absorb
        the requeue wait and re-prefill, billing them as decode on the
        critical-path breakdown."""
        if request.span is None or request.decode_start_wall is None \
                or request.decode_start_mono is None:
            return
        from skypilot_tpu.utils import tracing
        tracing.record_span(
            'infer.decode', request.span.context,
            request.decode_start_wall,
            max(0.0, time.monotonic() - request.decode_start_mono),
            service='inference', tokens=len(request.generated))
        request.decode_start_wall = None
        request.decode_start_mono = None

    def _fail_slot(self, slot: int, error: BaseException,
                   prefill: bool = False) -> None:
        request = self._slots[slot]
        self._release_slot(slot)
        if prefill:
            self._prefill_errors_total += 1
        if request is not None:
            self._finish(request, error)

    # -- admission + chunked prefill ------------------------------------

    def _admit(self) -> None:
        """Bookkeeping-only admission: assign a free slot, reference
        cached prefix blocks, allocate private blocks for the prompt.
        The compute (chunked prefill) happens in ``_prefill_tick``,
        interleaved with decode steps — never inline here."""
        while True:
            try:
                self._waiting.append(self._pending.get_nowait())
            except queue.Empty:
                break
        while self._waiting:
            slot = next((s for s in range(self.max_slots)
                         if self._slots[s] is None), None)
            if slot is None:
                return
            if self._blocked_at_version == self._pool.version:
                return  # still HBM-blocked; nothing changed since
            request = self._waiting[0]
            try:
                if not self._begin_prefill(request, slot):
                    # HBM pressure: keep FIFO order; retry only once
                    # the pool's alloc/ref state has moved.
                    self._blocked_at_version = self._pool.version
                    return
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('prefill admission failed')
                self._waiting.pop(0)
                self._prefill_errors_total += 1
                self._finish(request, e)
                continue
            self._blocked_at_version = None
            self._waiting.pop(0)

    def _begin_prefill(self, request: _Request, slot: int) -> bool:
        """Returns False when the pool can't fit the prompt right now
        (request stays queued); raises when it never can.

        A preempted request carries its already-generated tokens: they
        re-prefill as part of the visible sequence and decode resumes
        where it left off."""
        ids = request.token_ids + request.generated
        plen = len(ids)
        needed_total = math.ceil(plen / self.block_size)
        if needed_total > self._pool.total_blocks:
            raise RuntimeError(
                f'prompt needs {needed_total} KV blocks; pool has '
                f'{self._pool.total_blocks} (raise num_blocks or '
                f'SKYT_INFER_BLOCK_SIZE granularity)')
        shared: List[int] = []
        if self._prefix is not None:
            # Leave >= 1 prompt token to compute: the last token's
            # logits seed sampling and are never cached. Hit/miss
            # counters are bumped only once admission COMMITS below —
            # a blocked retry must not re-count reuse that never
            # happened.
            shared = self._prefix.lookup(ids, limit_tokens=plen - 1)
        blocks = list(shared)
        # Admission watermark: keep one tail block of headroom per
        # active decoder so admitting this prompt can't immediately
        # force a preemption storm. Only RECLAIMABLE prefix entries
        # count as available (this request's own shared refs and
        # blocks live slots share free nothing when evicted).
        need_private = needed_total - len(shared)
        avail = self._pool.free_blocks + (
            self._prefix.reclaimable_blocks if self._prefix is not None
            else 0)
        if avail < need_private + sum(self._decoding):
            for block in blocks:
                self._pool.decref(block)
            return False
        ok = True
        while len(blocks) < needed_total:
            block = self._alloc_block()
            if block is None:
                ok = False
                break
            blocks.append(block)
        if not ok:
            for block in blocks:
                self._pool.decref(block)
            return False
        start = len(shared) * self.block_size
        if self._prefix is not None:
            if shared:
                self._prefix_hits_total += 1
                self._prefix_tokens_reused_total += start
            else:
                self._prefix_misses_total += 1
        if not request.admitted:
            request.admitted = True
            wait_s = max(0.0, time.monotonic() - request.arrival)
            self._queue_wait_seconds_total += wait_s
            if request.span is not None:
                from skypilot_tpu.utils import tracing
                tracing.record_span('infer.queue_wait',
                                    request.span.context,
                                    request.arrival_wall, wait_s,
                                    service='inference')
        self._slot_blocks[slot] = blocks
        self._host_bt[slot, :] = 0
        self._host_bt[slot, :len(blocks)] = blocks
        self._host_len[slot] = start
        self._bt_dirty = True
        self._slots[slot] = request
        self._decoding[slot] = False
        self._admit_seq += 1
        self._admit_order[slot] = self._admit_seq
        self._prefilling.append(_PrefillState(request, slot, start, ids))
        return True

    def _prefill_tick(self) -> None:
        """Absorb ONE chunk of ONE prefilling prompt (FIFO). Called
        up to twice per loop iteration (once before the decode step,
        once overlapped with its host readback), so active decoders
        stall for at most TWO chunks of prefill compute per generated
        token — still bounded by the chunk budget, never by arriving
        prompt length."""
        if not self._prefilling:
            return
        state = self._prefilling[0]
        request, slot = state.request, state.slot
        ids = state.ids
        chunk = ids[state.pos:state.pos + self.prefill_chunk]
        tokens = np.zeros((1, self.prefill_chunk), np.int32)
        tokens[0, :len(chunk)] = chunk
        self._sync_tables()
        chunk_wall = time.time()
        chunk_mono = time.monotonic()
        try:
            last, cache = self._prefill_fn(
                self.params, jnp.asarray(tokens),
                jnp.int32(state.pos), jnp.int32(len(chunk)),
                jnp.int32(slot), self.cache)
        except Exception as e:  # pylint: disable=broad-except
            logger.exception('chunked prefill failed')
            self._prefilling.pop(0)
            self._fail_slot(slot, e, prefill=True)
            return
        self.cache = cache
        state.pos += len(chunk)
        self._host_len[slot] = state.pos
        self._prefill_chunks_total += 1
        if request.span is not None:
            from skypilot_tpu.utils import tracing
            tracing.record_span(
                'infer.prefill_chunk', request.span.context, chunk_wall,
                max(0.0, time.monotonic() - chunk_mono),
                service='inference', tokens=len(chunk), slot=slot,
                pos=state.pos)
        if state.pos >= len(ids):
            self._prefilling.pop(0)
            self._last_logits = self._last_logits.at[slot].set(
                last[0].astype(jnp.float32))
            self._rngs[slot] = jax.random.key(request.seed)
            self._decoding[slot] = True
            if request.decode_start_wall is None:
                request.decode_start_wall = time.time()
                request.decode_start_mono = time.monotonic()
            if self._prefix is not None:
                self._prefix.insert(ids, self._slot_blocks[slot])

    def _preempt(self, slot: int, active_mask: np.ndarray) -> None:
        """Release a slot's blocks (decoding OR mid-prefill) and
        requeue its request at the FRONT of the admission queue (it
        resumes by re-prefilling prompt + generated-so-far; fold-in-
        position sampling keeps the rng stream identical — see the
        _PrefillState note on kernel-level logits equivalence). The
        HBM-pressure valve: oversubscribed pools degrade to queueing,
        never to corrupt or dead requests."""
        request = self._slots[slot]
        self._prefilling = [s for s in self._prefilling
                            if s.slot != slot]
        self._release_slot(slot)
        active_mask[slot] = False
        self._preemptions_total += 1
        if request is not None:
            if request.span is not None:
                from skypilot_tpu.utils import tracing
                # Close the decode segment HERE: the requeue wait and
                # the resume's re-prefill must not be billed as decode.
                self._record_decode_segment(request)
                tracing.record_span(
                    'infer.preempt', request.span.context, time.time(),
                    0.0, service='inference', slot=slot,
                    generated=len(request.generated))
            self._waiting.insert(0, request)
            self._wake.set()

    def _ensure_decode_blocks(self, active_mask: np.ndarray) -> None:
        """A slot crossing a block boundary needs its next tail block
        BEFORE the step writes position ``length``. When the pool is
        exhausted even after prefix-cache eviction, the most recently
        admitted decoding request is preempted (vLLM policy: the oldest
        request always progresses, so the system drains)."""
        for slot in range(self.max_slots):
            if not active_mask[slot]:
                continue
            length = int(self._host_len[slot])
            if length % self.block_size != 0:
                continue
            index = length // self.block_size
            if index >= self.blocks_per_slot:
                continue  # finish check retires it this step
            if self._host_bt[slot, index] != 0:
                continue
            while True:
                block = self._alloc_block()
                if block is not None:
                    self._slot_blocks[slot].append(block)
                    self._host_bt[slot, index] = block
                    self._bt_dirty = True
                    break
                # Victims: any OTHER slot holding blocks — decoding or
                # mid-prefill (a pool drained into prefills must not
                # strand the decoders).
                victims = [s for s in range(self.max_slots)
                           if s != slot and self._slots[s] is not None]
                if not victims:
                    # Nothing left to steal from: this request alone
                    # outgrew the pool — fail it loudly.
                    active_mask[slot] = False
                    self._fail_slot(slot, RuntimeError(
                        'KV block pool exhausted mid-decode (raise '
                        'num_blocks or lower max_slots)'))
                    break
                victim = max(victims,
                             key=lambda s: self._admit_order[s])
                self._preempt(victim, active_mask)

    # -- serving loop ---------------------------------------------------

    def _loop(self) -> None:
        from skypilot_tpu.inference.sharding import mesh_context
        with mesh_context(self._mesh):
            self._loop_body()

    def _loop_body(self) -> None:
        while not self._stop.is_set():
            self._admit()
            self._prefill_tick()
            active_mask = np.array(self._decoding, bool)
            if not active_mask.any():
                if self._prefilling or self._waiting or \
                        not self._pending.empty():
                    continue  # keep absorbing prefill chunks
                self._wake.wait(0.01)
                self._wake.clear()
                continue
            self._ensure_decode_blocks(active_mask)
            if not active_mask.any():
                continue
            self._sync_tables()
            temps = np.array([r.temperature if r else 0.0
                              for r in self._slots], np.float32)
            step_t0 = time.perf_counter()
            try:
                tokens, logits, cache = self._decode_fn(
                    self.params, self._last_logits, self.cache,
                    jnp.asarray(active_mask), jnp.asarray(temps),
                    jnp.stack(self._rngs))
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('continuous decode step failed')
                for slot in range(self.max_slots):
                    if active_mask[slot] and self._slots[slot] is not None:
                        self._fail_slot(slot, e)
                continue
            self.cache = cache
            self._last_logits = logits
            # The step advanced every active slot by one position
            # (deterministic) — mirror it on the host now so overlap
            # work below sees consistent lengths.
            self._host_len[active_mask] += 1
            # Overlap the host readback with useful work: start the
            # async device->host copy, then dispatch the next prefill
            # chunk / admission bookkeeping while the step (and the
            # copy) complete — no hard sync in the middle of the loop.
            try:
                tokens.copy_to_host_async()
            except AttributeError:
                pass
            overlap_t0 = time.perf_counter()
            self._admit()
            self._prefill_tick()
            # decode_seconds feeds tokens/s derivations: exclude the
            # host-side admission/prefill bookkeeping done in the
            # overlap window from the decode-step accounting.
            overlap_cost = time.perf_counter() - overlap_t0
            host_tokens = np.asarray(tokens)
            self._decode_seconds_total += (time.perf_counter() -
                                           step_t0 - overlap_cost)
            for slot in range(self.max_slots):
                request = self._slots[slot]
                if request is None or not active_mask[slot]:
                    continue
                token = int(host_tokens[slot])
                self._tokens_total += 1
                request.generated.append(token)
                finished = (
                    (request.eos_id is not None and
                     token == request.eos_id) or
                    len(request.generated) >= request.max_new_tokens or
                    self._host_len[slot] >= self.max_len)
                if finished:
                    self._finish(request)
                    self._release_slot(slot)  # blocks back to the pool

    # -- public API -----------------------------------------------------

    def _submit(self, token_ids: List[int], max_new_tokens: int,
                temperature: float, eos_id: Optional[int],
                seed: int, trace_ctx=None) -> _Request:
        """Shared admission path: validate + enqueue (both the blocking
        and streaming entries; the policy must not drift between them).

        ``trace_ctx`` (a tracing.SpanContext, e.g. parsed from the
        serving request's traceparent) opens a per-request engine span
        with queue-wait / prefill-chunk / decode / preempt children."""
        if len(token_ids) >= self.max_len:
            # Reject loudly: silently truncating a prompt answers a
            # question the caller never asked.
            raise ValueError(
                f'prompt is {len(token_ids)} tokens; engine max_len is '
                f'{self.max_len} (prompt + generation must fit)')
        request = _Request(token_ids, max_new_tokens, temperature,
                           eos_id, seed, trace_ctx=trace_ctx)
        if trace_ctx is not None:
            from skypilot_tpu.utils import tracing
            request.span = tracing.start_span(
                'infer.request', parent=trace_ctx, service='inference',
                prompt_tokens=len(token_ids),
                max_new_tokens=max_new_tokens)
        self._requests_total += 1
        self._pending.put(request)
        self._wake.set()
        return request

    def generate_ids(self, token_ids: List[int], *,
                     max_new_tokens: int = 32,
                     temperature: float = 0.0,
                     eos_id: Optional[int] = None,
                     seed: int = 0,
                     timeout: float = 300.0,
                     trace_ctx=None) -> List[int]:
        request = self._submit(token_ids, max_new_tokens, temperature,
                               eos_id, seed, trace_ctx=trace_ctx)
        if not request.done.wait(timeout):
            raise TimeoutError('generation timed out')
        if request.error is not None:
            raise request.error
        generated = request.generated
        if eos_id is not None and eos_id in generated:
            generated = generated[:generated.index(eos_id)]
        return generated

    def generate_text(self, prompt: str, **kwargs: Any) -> str:
        ids = self.tokenizer.encode(prompt)
        out = self.generate_ids(ids, eos_id=self.tokenizer.eos_id,
                                **kwargs)
        return self.tokenizer.decode(out)

    def stream_ids(self, token_ids: List[int], *,
                   max_new_tokens: int = 32,
                   temperature: float = 0.0,
                   eos_id: Optional[int] = None,
                   seed: int = 0,
                   timeout: float = 300.0,
                   trace_ctx=None):
        """Yield generated token ids AS THEY LAND in the slot loop
        (the decode thread appends to request.generated; this iterator
        tails it) — the vLLM/JetStream streaming serving shape.

        Validation/admission happens EAGERLY (same as generate_ids: an
        over-long prompt raises here, not at first iteration)."""
        request = self._submit(token_ids, max_new_tokens, temperature,
                               eos_id, seed, trace_ctx=trace_ctx)

        def tail():
            emitted = 0
            deadline = time.monotonic() + timeout
            while True:
                finished = request.done.is_set()
                generated = request.generated
                while emitted < len(generated):
                    token = generated[emitted]
                    emitted += 1
                    if eos_id is not None and token == eos_id:
                        return
                    yield token
                if finished:
                    if request.error is not None:
                        raise request.error
                    return
                if time.monotonic() > deadline:
                    raise TimeoutError('generation timed out')
                time.sleep(0.005)

        return tail()

    def stream_text(self, prompt: str, **kwargs: Any):
        """Yield text DELTAS: ids decode cumulatively (single BPE
        tokens may be partial UTF-8; the running decode keeps deltas
        well-formed)."""
        ids = self.tokenizer.encode(prompt)
        out_ids: List[int] = []
        text_so_far = ''
        for token in self.stream_ids(ids, eos_id=self.tokenizer.eos_id,
                                     **kwargs):
            out_ids.append(token)
            text = self.tokenizer.decode(out_ids)
            delta, text_so_far = text[len(text_so_far):], text
            if delta:
                yield delta

    def generate_texts(self, prompts: List[str],
                       **kwargs: Any) -> List[str]:
        """Concurrent multi-prompt entry (the HTTP payload's batch API):
        each prompt is its own slot request, so they genuinely overlap."""
        import concurrent.futures
        # Bounded pool: a huge prompt list must not fan out into
        # thousands of OS threads — beyond ~2x the slot count extra
        # callers would only queue anyway.
        workers = max(1, min(len(prompts), 2 * self.max_slots))
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=workers) as pool:
            futures = [pool.submit(self.generate_text, p, **kwargs)
                       for p in prompts]
            return [f.result() for f in futures]

    def stats(self) -> Dict[str, float]:
        total = self._pool.total_blocks
        free = self._pool.free_blocks
        return {
            'slots': self.max_slots,
            'active': sum(r is not None for r in self._slots),
            'pending': self._pending.qsize() + len(self._waiting),
            # Monotonic counters (Prometheus counter type on /metrics).
            'requests': self._requests_total,
            'completions': self._completions_total,
            'request_errors': self._errors_total,
            'prefill_errors': self._prefill_errors_total,
            'prefill_chunks': self._prefill_chunks_total,
            'tokens_generated': self._tokens_total,
            'decode_seconds': round(self._decode_seconds_total, 4),
            'queue_wait_seconds': round(self._queue_wait_seconds_total,
                                        4),
            'prefix_cache_hits': self._prefix_hits_total,
            'prefix_cache_misses': self._prefix_misses_total,
            'prefix_tokens_reused': self._prefix_tokens_reused_total,
            'preemptions': self._preemptions_total,
            # Point-in-time gauges: paged-pool pressure.
            'block_size': self.block_size,
            'blocks_total': total,
            'blocks_free': free,
            'blocks_cached': (self._prefix.cached_blocks
                              if self._prefix is not None else 0),
            'block_occupancy': round((total - free) / total, 4)
            if total else 0.0,
        }

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)
