"""Continuous batching over a paged KV pool with chunked prefill.

The JetStream/vLLM serving core, TPU-first, three layers deep:

* **Paged KV pool** (vLLM PagedAttention shape): instead of one
  ``max_slots * max_len`` monolithic cache, KV lives in a fixed pool of
  ``block_size``-token blocks; each slot maps logical positions through
  a block table, so a sequence consumes HBM proportional to its actual
  length and ``max_slots`` can rise several-fold at the same HBM.
  Shapes stay static — the pool block count is fixed and the jitted
  step gathers/scatters by block index — so nothing recompiles as
  traffic changes.
* **Chunked prefill** (Sarathi-Serve shape): a prompt is absorbed in
  fixed-size chunks interleaved between decode steps instead of one
  inline whole-prompt prefill, so inter-token latency for active
  decoders is bounded by the chunk budget, not by arriving prompt
  length.
* **Prefix cache**: full prompt blocks are digest-keyed and shared
  read-only across requests (``inference/paged.py``) — a common system
  prompt prefills once; later requests reference the same blocks
  copy-on-write style and only compute their private suffix.

Decode is ONE jitted program stepping all slots together; the scheduler
thread admits requests into free slots as others finish. Public
surface (``generate_ids``/``stream_ids``/...) is unchanged from the
monolithic-cache engine.

Two r13 layers on top:

* **Fused paged attention**: the decode/verify programs read the pool
  through the block table inside the attention kernel
  (``ops/pallas/paged_attention.py``) — no materialized logical-view
  copy per layer per step.
* **Speculative decoding** (``SKYT_SPEC_DECODE`` / ``spec_decode=``): a
  host-side draft (n-gram prompt-lookup by default,
  ``inference/speculative.py``) proposes up to ``draft_k`` tokens after
  the pending one; ONE fused verify program scores the whole window;
  the engine emits the longest prefix matching what the target model
  samples at each position. Sampling folds the rng into the POSITION,
  so the accepted stream is token-for-token identical to the
  non-speculative engine (greedy exactly; temperature>0 up to kernel
  ULP near-ties, same caveat as preemption resume). Rejected suffixes
  roll back: lengths truncate and over-allocated tail blocks decref
  back to the pool. Verify steps schedule exactly like decode steps —
  chunked prefill still interleaves, preemption semantics unchanged —
  so inter-token p99 stays bounded by the chunk budget.
"""
from __future__ import annotations

import dataclasses
import functools
import math
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.inference import kv_migrate
from skypilot_tpu.inference.paged import (AdapterPagePool, BlockImporter,
                                          BlockPool, PrefixCache,
                                          adapter_chain_root,
                                          chain_digests)
from skypilot_tpu.inference.tokenizer import get_tokenizer
from skypilot_tpu.models import decode as decode_lib
from skypilot_tpu.models import llama
from skypilot_tpu.models import lora as lora_lib
from skypilot_tpu.models.config import ModelConfig, get_model_config
from skypilot_tpu.utils import fault_injection, log

logger = log.init_logger(__name__)

DEFAULT_BLOCK_SIZE = 16
DEFAULT_PREFILL_CHUNK = 64

# Chaos sites (utils/fault_injection): the two host-side edges of
# adapter residency — pulling a cold adapter into a device page, and
# LRU-evicting an idle one to make room.
LORA_FETCH_SITE = 'infer.lora.fetch'
LORA_EVICT_SITE = 'infer.lora.evict'
WEIGHT_REFRESH_SITE = 'infer.weights.refresh'


def flatten_param_paths(params) -> Dict[str, Any]:
    """Stable ``'/'``-joined path -> leaf map for a params pytree.

    The RL pipeline's PolicyStore names checkpoint shards by these
    paths and the engine's refresh hook resolves them back; both sides
    MUST use this one function or delta refresh silently misses
    shards. Dicts walk in sorted key order so the mapping (and the
    manifest built from it) is independent of insertion order."""
    flat: Dict[str, Any] = {}

    def walk(node, prefix):
        if isinstance(node, dict):
            for key in sorted(node):
                walk(node[key], prefix + (str(key),))
        elif isinstance(node, (list, tuple)):
            for i, value in enumerate(node):
                walk(value, prefix + (str(i),))
        else:
            flat['/'.join(prefix)] = node

    walk(params, ())
    return flat


class _WeightRefresh:
    """A queued live weight swap; the serving loop applies it at a
    step boundary and then sets ``done`` (``error`` on failure)."""

    def __init__(self, updates, params, version, mode) -> None:
        self.updates = updates
        self.params = params
        self.version = version
        self.mode = mode
        self.applied_shards = 0
        self.done = threading.Event()
        self.error: Optional[BaseException] = None


# Module-level jitted steps with the (frozen, hashable) ModelConfig as
# a static arg: every engine with the same config + shapes shares one
# compiled program — repeated engine construction (tests, serving
# restarts) stops paying XLA compilation over and over.

def _sample_tokens(logits, rngs, positions, temps):
    """Fold-in-POSITION sampling, the single definition every sampling
    site shares (plain decode, speculative targets, pending-token
    re-seed): the token at position p is a pure function of
    (seed, p, logits), so a speculative or resumed stream reproduces
    the plain stream exactly."""
    keys = jax.vmap(jax.random.fold_in)(rngs, positions)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    sampled = jax.vmap(
        lambda k, l, t: jax.random.categorical(
            k, l / jnp.maximum(t, 1e-6)))(keys, logits,
                                          temps).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled)


@functools.partial(jax.jit, static_argnames=('cfg',))
def _decode_all_step(params, last_logits, cache, active, temps, rngs,
                     lora_pages=None, adapter_ids=None, *, cfg):
    """One step for every slot: sample from last logits, advance.

    ``lora_pages``/``adapter_ids`` are None on a non-LoRA engine —
    None is part of the pytree structure, so the disabled trace is
    EXACTLY the pre-multi-LoRA program (bitwise-base guarantee)."""
    tokens = _sample_tokens(last_logits, rngs, cache.lengths, temps)
    logits, cache = decode_lib.paged_decode_step(
        params, tokens, cache, cfg, active=active,
        lora_pages=lora_pages, adapter_ids=adapter_ids)
    return tokens, logits, cache


@jax.jit
def _sample_pending_step(logits_row, rng, length, temp):
    """The pending token a slot enters speculative mode with: what the
    plain engine would sample next (same fold-in-position math, batch
    of one)."""
    return _sample_tokens(logits_row[None], rng[None], length[None],
                          temp[None])[0]


@functools.partial(jax.jit, static_argnames=('cfg', 'q_len'))
def _spec_verify_all_step(params, cache, inputs, n_input, active, temps,
                          rngs, lora_pages=None, adapter_ids=None,
                          *, cfg, q_len):
    """One speculative verify step for every slot.

    ``inputs`` [B, Q]: the pending token then the draft proposals
    (slot rows beyond ``n_input`` are padding). The fused verify
    program writes all window rows and returns per-position logits;
    the target token for each position is then sampled with the SAME
    fold-in-position keys the plain step would use, and a draft is
    accepted while it equals its target. ``n_emit`` = 1 + accepted
    prefix (the pending token always lands); ``pending`` = the first
    non-matching target (or the bonus token after a fully-accepted
    window) — exactly the next plain-engine token, carried to the next
    step instead of being emitted twice. Lengths advance by ``n_emit``
    on-device; the host rolls back further (eos, caps) by truncating
    its mirror.
    """
    lengths0 = cache.lengths
    logits, cache = decode_lib.paged_verify_step(
        params, inputs, cache, cfg, active=active, n_input=n_input,
        lora_pages=lora_pages, adapter_ids=adapter_ids)
    targets = [
        _sample_tokens(logits[:, j], rngs, lengths0 + 1 + j, temps)
        for j in range(q_len)]
    g = jnp.stack(targets, axis=1)                           # [B, Q]
    if q_len > 1:
        match = (g[:, :-1] == inputs[:, 1:])
        match &= jnp.arange(1, q_len)[None, :] < n_input[:, None]
        accepted = jnp.cumprod(match.astype(jnp.int32), axis=1)
        n_emit = 1 + jnp.sum(accepted, axis=1)
    else:
        n_emit = jnp.ones_like(lengths0)
    n_emit = jnp.where(active, jnp.minimum(n_emit, n_input),
                       0).astype(jnp.int32)
    pending = jnp.take_along_axis(
        g, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
    cache = dataclasses.replace(cache, lengths=lengths0 + n_emit)
    return n_emit, pending, cache


@functools.partial(jax.jit, static_argnames=('cfg',))
def _prefill_chunk_step(params, tokens, start, n_new, slot, cache,
                        lora_pages=None, adapter_id=None, *, cfg):
    return decode_lib.prefill_chunk(params, tokens, start, n_new,
                                    slot, cache, cfg,
                                    lora_pages=lora_pages,
                                    adapter_id=adapter_id)


class _Request:
    def __init__(self, token_ids: List[int], max_new_tokens: int,
                 temperature: float, eos_id: Optional[int],
                 seed: int, trace_ctx=None,
                 adapter: Optional[str] = None) -> None:
        self.token_ids = token_ids
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.eos_id = eos_id
        self.seed = seed
        self.adapter = adapter  # registered LoRA adapter name or None
        self.arrival = time.monotonic()
        self.arrival_wall = time.time()
        self.admitted = False  # queue-wait counted once, not per resume
        self.generated: List[int] = []
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        # Distributed tracing (armed deployments with an incoming
        # context only): the per-request engine span; queue-wait /
        # prefill-chunk / decode / preempt child spans hang off it.
        self.span = None
        self.decode_start_wall: Optional[float] = None
        self.decode_start_mono: Optional[float] = None
        # Disaggregated serving: the engine-assigned id a prefill-role
        # export is keyed by; ``migration`` holds the decode side's
        # pulled KV (kv_migrate.PulledKv) until it imports or falls
        # back to a local re-prefill.
        self.request_id = ''
        self.migration = None
        self.handoff_start: Optional[float] = None
        # Policy version of the weights that generated this request's
        # tokens — stamped at submit; the RL rollout path reads it to
        # compute off-policy staleness per batch.
        self.policy_version = 0


class _DrrQueue:
    """Deficit-round-robin admission queue keyed by adapter.

    Mirrors what ``serve/requests_db.claim_next`` does for the control
    plane, one layer down: each adapter (base traffic = key ``''``)
    owns a FIFO lane; lanes are served round-robin with a per-visit
    ``quantum`` of deficit measured in KV BLOCKS (the resource a
    prompt actually consumes), and a lane may admit while its deficit
    covers the head request's block cost. One 100x-hot adapter
    therefore gets one quantum per round like everyone else — it
    queues behind itself, not in front of the other 999. With a single
    lane (no adapters in play) the order degenerates to exact FIFO, so
    a base-only engine schedules precisely as before.
    """

    def __init__(self, block_size: int, quantum_blocks: int) -> None:
        import collections
        self._block_size = max(1, block_size)
        self._quantum = max(1, quantum_blocks)
        self._queues: Dict[str, Any] = {}
        self._rotation = collections.deque()  # lane visit order
        self._deficit: Dict[str, int] = {}
        self._total = 0
        self._deque = collections.deque

    def __len__(self) -> int:
        return self._total

    @staticmethod
    def _key(request: '_Request') -> str:
        return request.adapter or ''

    def _cost(self, request: '_Request') -> int:
        tokens = len(request.token_ids) + len(request.generated)
        return max(1, -(-tokens // self._block_size))

    def push(self, request: '_Request') -> None:
        key = self._key(request)
        lane = self._queues.get(key)
        if lane is None:
            lane = self._queues[key] = self._deque()
            self._rotation.append(key)
            self._deficit.setdefault(key, 0)
        lane.append(request)
        self._total += 1

    def push_front(self, request: '_Request') -> None:
        """Head-of-lane requeue (preemption / HBM-blocked retry): the
        request resumes first in ITS lane, and its pop's deficit
        charge is refunded so the retry isn't double-billed."""
        key = self._key(request)
        lane = self._queues.get(key)
        if lane is None:
            lane = self._queues[key] = self._deque()
            self._rotation.appendleft(key)
            self._deficit.setdefault(key, 0)
        lane.appendleft(request)
        self._deficit[key] = self._deficit.get(key, 0) + \
            self._cost(request)
        self._total += 1

    def pop(self, blocked=None) -> Optional['_Request']:
        """Next request by DRR order, or None when the queue is empty
        or every lane's head is ``blocked`` (per-adapter quota)."""
        while self._total:
            progressed = False
            for _ in range(len(self._rotation)):
                key = self._rotation[0]
                lane = self._queues.get(key)
                if not lane:
                    self._rotation.popleft()
                    self._queues.pop(key, None)
                    self._deficit.pop(key, None)
                    continue
                head = lane[0]
                if blocked is not None and blocked(head):
                    self._rotation.rotate(-1)
                    continue
                cost = self._cost(head)
                if self._deficit.get(key, 0) >= cost:
                    lane.popleft()
                    self._deficit[key] -= cost
                    self._total -= 1
                    if not lane:
                        # An emptied lane forfeits leftover deficit —
                        # it must not bank credit while idle.
                        self._rotation.popleft()
                        self._queues.pop(key, None)
                        self._deficit.pop(key, None)
                    return head
                self._deficit[key] = self._deficit.get(key, 0) + \
                    self._quantum
                self._rotation.rotate(-1)
                progressed = True
            if not progressed:
                return None  # every lane head quota-blocked
        return None


class _PrefillState:
    """A slot mid-prefill: ``pos`` = next index of ``ids`` to absorb.

    ``ids`` is the prompt PLUS any tokens generated before a
    preemption: a preempted request resumes by re-prefilling its whole
    visible sequence (chunked, possibly prefix-cache-accelerated) and
    continuing to decode — sampling folds the rng into the position,
    so the rng stream is exactly what it would have been. (The resume
    logits come through the chunk-prefill attention rather than the
    decode kernel; on backends where those reductions differ by ULPs,
    a near-tie at temperature>0 can still resolve differently.)"""

    def __init__(self, request: _Request, slot: int, pos: int,
                 ids: List[int]) -> None:
        self.request = request
        self.slot = slot
        self.pos = pos
        self.ids = ids


class ContinuousBatchingEngine:
    """generate() admits into the shared decode loop; thread-safe."""

    def __init__(self,
                 model: str = 'tiny',
                 *,
                 cfg: Optional[ModelConfig] = None,
                 params: Optional[Any] = None,
                 checkpoint_dir: Optional[str] = None,
                 hf_checkpoint: Optional[str] = None,
                 max_slots: int = 4,
                 max_len: Optional[int] = None,
                 block_size: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 seed: int = 0,
                 quantize: bool = False,
                 quantize_kv: bool = False,
                 mesh: Optional[Any] = None,
                 spec_decode: Optional[bool] = None,
                 draft_k: Optional[int] = None,
                 draft: Optional[Any] = None,
                 role: Optional[str] = None,
                 lora_pages: Optional[int] = None,
                 lora_max_rank: Optional[int] = None,
                 lora_max_active: Optional[int] = None,
                 base_digest: Optional[str] = None) -> None:
        # Real-weights path: see engine.py (models/hf_interop.py).
        if hf_checkpoint:
            from skypilot_tpu.models import hf_interop
            params, cfg = hf_interop.resolve_engine_inputs(
                hf_checkpoint, params, cfg)
        self.cfg = cfg or get_model_config(model)
        if quantize_kv:
            from skypilot_tpu.models.config import with_int8_kv_cache
            self.cfg = with_int8_kv_cache(self.cfg)
        self.tokenizer = get_tokenizer(hf_checkpoint,
                                       require=bool(hf_checkpoint))
        # A model whose vocab can't cover the tokenizer can still
        # serve the id-level APIs (the RL rollout path samples raw
        # token ids on purpose-built small vocabs) — only the TEXT
        # entry points are poisoned, checked at call time below.
        self._tokenizer_fits = (self.tokenizer.vocab_size <=
                                self.cfg.vocab_size)
        if hf_checkpoint and not self._tokenizer_fits:
            raise ValueError(
                f'Model vocab {self.cfg.vocab_size} < tokenizer '
                f'vocab {self.tokenizer.vocab_size}')
        self.max_slots = max_slots
        self.max_len = min(max_len or self.cfg.max_seq_len,
                           self.cfg.max_seq_len)
        from skypilot_tpu.utils import env_registry
        paged_block_k = env_registry.get_int('SKYT_PAGED_BLOCK_K',
                                             default=0)
        if paged_block_k and not self.cfg.paged_block_k:
            self.cfg = dataclasses.replace(self.cfg,
                                           paged_block_k=paged_block_k)
        self.block_size = (block_size or
                           env_registry.get_int('SKYT_INFER_BLOCK_SIZE',
                                                default=DEFAULT_BLOCK_SIZE))
        if self.block_size < 1:
            raise ValueError(f'block_size must be >= 1, got '
                             f'{self.block_size}')
        self.prefill_chunk = max(1, min(
            prefill_chunk or env_registry.get_int(
                'SKYT_INFER_PREFILL_CHUNK',
                default=DEFAULT_PREFILL_CHUNK),
            self.max_len))
        self.blocks_per_slot = math.ceil(self.max_len / self.block_size)
        # Default pool = the HBM the monolithic max_slots*max_len cache
        # used (+1 for the reserved null block). Block granularity +
        # prefix sharing is what lets max_slots rise at the same HBM.
        self.num_blocks = (num_blocks or
                           max_slots * self.blocks_per_slot + 1)
        if params is not None:
            self.params = params
        elif checkpoint_dir:
            from skypilot_tpu.train.checkpoint import restore_latest
            restored = restore_latest(
                checkpoint_dir,
                lambda: llama.init_params(jax.random.key(seed), self.cfg))
            self.params = (restored['params']
                           if isinstance(restored, dict) and
                           'params' in restored else restored)
        else:
            self.params = llama.init_params(jax.random.key(seed),
                                            self.cfg)
        # Mesh placement first, then quantization (see engine.py note).
        from skypilot_tpu.inference.sharding import (prepare_engine,
                                                     shard_paged_cache)
        self.params, self.cfg, self._mesh = prepare_engine(
            self.params, self.cfg, mesh)
        from skypilot_tpu.models.quant import maybe_quantize
        self.params = maybe_quantize(self.params, quantize)
        self.cache = shard_paged_cache(
            decode_lib.init_paged_cache(self.cfg, self.num_blocks,
                                        self.block_size, max_slots,
                                        self.blocks_per_slot),
            self._mesh, self.cfg)
        # Host-side bookkeeping (serving-loop thread only).
        self._pool = BlockPool(self.num_blocks)
        self._prefix: Optional[PrefixCache] = (
            PrefixCache(self._pool, self.block_size)
            if prefix_cache and self.block_size <= self.max_len else None)
        self._host_bt = np.zeros((max_slots, self.blocks_per_slot),
                                 np.int32)
        self._host_len = np.zeros((max_slots,), np.int64)
        self._slot_blocks: List[List[int]] = [[] for _ in
                                              range(max_slots)]
        self._bt_dirty = False
        self._slots: List[Optional[_Request]] = [None] * max_slots
        self._decoding = [False] * max_slots
        self._admit_order = [0] * max_slots  # preemption victim pick
        self._admit_seq = 0
        self._prefilling: List[_PrefillState] = []
        # Admission queue: DRR-fair across adapters (exact FIFO when
        # only base traffic flows — a single lane degenerates to the
        # pre-multi-LoRA order).
        self._waiting = _DrrQueue(
            self.block_size,
            env_registry.get_int('SKYT_LORA_DRR_QUANTUM', default=4))
        # Pool version at the last admission attempt that failed on
        # HBM pressure: until it changes, retrying is pure waste
        # (prefix re-hash + reclaimable scan on the serving loop).
        self._blocked_at_version: Optional[int] = None
        # Speculative decoding: verify window = pending token + up to
        # draft_k proposals. Off (window 1) unless asked for via arg or
        # SKYT_SPEC_DECODE; the draft is pluggable (speculative.py),
        # n-gram prompt-lookup by default.
        if spec_decode is None:
            spec_decode = env_registry.get_bool('SKYT_SPEC_DECODE')
        k_draft = (draft_k if draft_k is not None
                   else env_registry.get_int('SKYT_SPEC_DRAFT_K'))
        self._spec_window = 1 + max(0, min(int(k_draft),
                                           self.max_len - 1)) \
            if spec_decode else 1
        if spec_decode and self._spec_window > 1:
            from skypilot_tpu.inference.speculative import NGramDraft
            self._draft = draft or NGramDraft(
                env_registry.get_int('SKYT_SPEC_NGRAM_MAX'),
                corpus_entries=8192)
        else:
            self._draft = None
        self.spec_decode = self._draft is not None
        # Multi-LoRA serving (docs/multi_lora_serving.md): a fixed
        # stack of device adapter pages fed from a host registry, with
        # residency charged against the KV block pool (S-LoRA unified
        # paging) and per-slot page indices gathered inside the jitted
        # steps (Punica BGMV). 0 pages = disabled: the jitted programs
        # and scheduler order are exactly the pre-LoRA engine.
        n_lora = (lora_pages if lora_pages is not None
                  else env_registry.get_int('SKYT_LORA_PAGES',
                                            default=0))
        self._lora_max_rank = max(1, (
            lora_max_rank if lora_max_rank is not None
            else env_registry.get_int('SKYT_LORA_MAX_RANK', default=8)))
        self._lora_max_active = (
            lora_max_active if lora_max_active is not None
            else env_registry.get_int('SKYT_LORA_MAX_ACTIVE',
                                      default=0))
        self.base_digest = base_digest or ''
        self._adapters: Dict[str, Dict[str, Any]] = {}
        self._adapter_lock = threading.Lock()
        self._adapter_demand: Dict[str, Dict[str, float]] = {}
        self._slot_adapter = np.zeros((max_slots,), np.int32)
        self._slot_adapter_name: List[Optional[str]] = \
            [None] * max_slots
        self._in_adapter_admit = False
        if n_lora > 0:
            kv_itemsize = self.cache.k.dtype.itemsize
            block_bytes = (2 * self.cfg.n_layers * self.block_size *
                           self.cfg.n_kv_heads *
                           self.cfg.resolved_head_dim * kv_itemsize)
            if self.cache.quantized:
                block_bytes += (2 * self.cfg.n_layers *
                                self.block_size * 4)
            self._adapter_pool: Optional[AdapterPagePool] = \
                AdapterPagePool(self._pool, n_lora, block_bytes)
            self._lora_store = lora_lib.init_adapter_pages(
                self.cfg, n_lora, self._lora_max_rank,
                dtype=self.cfg.compute_dtype)
        else:
            self._adapter_pool = None
            self._lora_store = None
        # Disaggregated serving role (docs/disaggregated_serving.md):
        # '' = colocated, 'prefill' = chunked prefill only, finished KV
        # parked in the exporter for the decode fleet to pull;
        # 'decode' = imports migrated KV and batch-decodes (prefill
        # only as the re-prefill fallback).
        if role is None:
            role = env_registry.get_str('SKYT_DISAGG_ROLE') or ''
        if role not in ('', 'prefill', 'decode'):
            raise ValueError(
                f"SKYT_DISAGG_ROLE must be '', 'prefill' or 'decode', "
                f'got {role!r}')
        self.role = role
        self.exporter = (kv_migrate.KvExporter()
                         if role == 'prefill' else None)
        self._request_seq = 0
        self._kv_exports_total = 0
        self._kv_imports_total = 0
        self._kv_import_fallbacks_total = 0
        self._pending_tok = np.zeros((max_slots,), np.int64)
        self._rngs = [jax.random.key(seed + 1 + i)
                      for i in range(max_slots)]
        self._last_logits = jnp.zeros((max_slots, self.cfg.vocab_size),
                                      jnp.float32)
        self._pending: 'queue.Queue[_Request]' = queue.Queue()
        # Counters (monotonic; surfaced as Prometheus counters).
        self._requests_total = 0
        self._completions_total = 0
        self._errors_total = 0
        self._prefill_errors_total = 0
        self._prefill_chunks_total = 0
        self._tokens_total = 0
        self._decode_seconds_total = 0.0
        self._queue_wait_seconds_total = 0.0
        self._prefix_hits_total = 0
        self._prefix_misses_total = 0
        self._prefix_tokens_reused_total = 0
        self._preemptions_total = 0
        self._draft_tokens_total = 0
        self._accepted_tokens_total = 0
        self._verify_steps_total = 0
        # Live in-place weight refresh (docs/rl_pipeline.md): tickets
        # queue here and the serving loop swaps params at the TOP of a
        # loop iteration — a step boundary, so the paged KV written by
        # the old policy stays valid (cache entries describe past
        # positions; only future positions see the new weights, which
        # is exactly the off-policy staleness GRPO's group baseline
        # absorbs). ``drain`` mode additionally holds admission and
        # waits for in-flight requests — the per-replica
        # stop-the-world baseline bench_rl.py compares against.
        self.policy_version = 0
        self._refresh_queue: 'queue.Queue[_WeightRefresh]' = \
            queue.Queue()
        self._refresh_hold = False
        self._weight_refreshes_total = 0
        self._refresh_shards_total = 0
        self._refresh_seconds_total = 0.0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name='continuous-batching',
                                        daemon=True)
        self._decode_fn = functools.partial(_decode_all_step,
                                            cfg=self.cfg)
        self._prefill_fn = functools.partial(_prefill_chunk_step,
                                             cfg=self.cfg)
        self._spec_fn = functools.partial(_spec_verify_all_step,
                                          cfg=self.cfg,
                                          q_len=self._spec_window)
        self._thread.start()

    # -- block-table plumbing -------------------------------------------

    def _sync_tables(self) -> None:
        """Push host block-table/length edits to the device cache."""
        if not self._bt_dirty:
            return
        import dataclasses
        self.cache = dataclasses.replace(
            self.cache,
            block_tables=jnp.asarray(self._host_bt),
            lengths=jnp.asarray(self._host_len, np.int32))
        self._bt_dirty = False

    def _alloc_block(self) -> Optional[int]:
        """Pool alloc with prefix-cache LRU eviction under pressure.
        Only reclaimable entries are evicted — dropping entries whose
        blocks live slots still share frees nothing and would wipe the
        reusable prefix chains exactly when the pool is busiest."""
        block = self._pool.alloc()
        while block is None and self._prefix is not None:
            if not self._prefix.evict_reclaimable():
                break
            block = self._pool.alloc()
        # Last resort before preemption: reclaim idle adapter pages
        # (KV pressure and adapter residency share one budget). The
        # reentrancy guard keeps an in-flight admission's own eviction
        # loop authoritative.
        while (block is None and self._adapter_pool is not None and
               not self._in_adapter_admit):
            if self._adapter_pool.evict_lru(
                    on_evict=self._note_adapter_evict) is None:
                break
            block = self._pool.alloc()
        return block

    def _release_slot(self, slot: int) -> None:
        for block in self._slot_blocks[slot]:
            self._pool.decref(block)
        self._slot_blocks[slot] = []
        self._host_bt[slot, :] = 0
        self._host_len[slot] = 0
        self._slots[slot] = None
        self._decoding[slot] = False
        self._pending_tok[slot] = 0
        name = self._slot_adapter_name[slot]
        if name is not None:
            self._slot_adapter_name[slot] = None
            self._slot_adapter[slot] = 0
            if self._adapter_pool is not None and \
                    self._adapter_pool.page_of(name) is not None:
                self._adapter_pool.unpin(name)
        self._bt_dirty = True

    def _finish(self, request: _Request,
                error: Optional[BaseException] = None) -> None:
        """Single exit point: keeps requests == completions + errors +
        in-flight, whatever path a request dies on."""
        if error is not None:
            request.error = error
            self._errors_total += 1
        else:
            self._completions_total += 1
        if request.span is not None:
            self._record_decode_segment(request)
            request.span.finish(error=error,
                                tokens=len(request.generated))
            request.span = None
        request.done.set()

    @staticmethod
    def _record_decode_segment(request: _Request) -> None:
        """Close the current infer.decode segment (finish OR preempt).
        Segments end at preemption — otherwise one span would absorb
        the requeue wait and re-prefill, billing them as decode on the
        critical-path breakdown."""
        if request.span is None or request.decode_start_wall is None \
                or request.decode_start_mono is None:
            return
        from skypilot_tpu.utils import tracing
        tracing.record_span(
            'infer.decode', request.span.context,
            request.decode_start_wall,
            max(0.0, time.monotonic() - request.decode_start_mono),
            service='inference', tokens=len(request.generated))
        request.decode_start_wall = None
        request.decode_start_mono = None

    def _fail_slot(self, slot: int, error: BaseException,
                   prefill: bool = False) -> None:
        request = self._slots[slot]
        self._release_slot(slot)
        if prefill:
            self._prefill_errors_total += 1
        if request is not None:
            self._finish(request, error)

    # -- multi-LoRA adapters --------------------------------------------

    def register_adapter(self, name: str, lora: Any, *,
                         alpha: float = lora_lib.DEFAULT_ALPHA,
                         base_digest: Optional[str] = None) -> None:
        """Make adapter ``name`` servable: host-side weights go into
        the registry; the device page is populated lazily on first
        request (prefetch-on-admission). ``lora`` is an
        ``init_lora_params``-shaped pytree. ``base_digest`` (when both
        sides declare one) must match the engine's base checkpoint —
        an adapter trained against a different base is rejected here,
        not discovered as garbage tokens in production."""
        if not name:
            raise ValueError('adapter name must be non-empty')
        if self._adapter_pool is None:
            raise RuntimeError(
                'engine has no adapter pages (construct with '
                'lora_pages=N or set SKYT_LORA_PAGES)')
        rank = int(lora['wq_a'].shape[-1])
        if rank > self._lora_max_rank:
            raise ValueError(
                f'adapter {name!r} rank {rank} exceeds the engine '
                f'page max_rank {self._lora_max_rank} '
                f'(SKYT_LORA_MAX_RANK)')
        if lora['wq_a'].shape[0] != self.cfg.n_layers or \
                lora['wq_a'].shape[1] != self.cfg.d_model:
            raise ValueError(
                f'adapter {name!r} shape {lora["wq_a"].shape} does '
                f'not match the base model '
                f'[{self.cfg.n_layers}, {self.cfg.d_model}, r]')
        if base_digest and self.base_digest and \
                base_digest != self.base_digest:
            raise ValueError(
                f'adapter {name!r} was trained against base '
                f'{base_digest[:12]}...; this engine serves '
                f'{self.base_digest[:12]}...')
        host = {key: np.asarray(value) for key, value in lora.items()}
        with self._adapter_lock:
            self._adapters[name] = {
                'lora': host,
                'alpha': float(alpha),
                'rank': rank,
                'base_digest': base_digest or '',
                'nbytes': lora_lib.adapter_nbytes(self.cfg, rank),
            }
            self._adapter_demand.setdefault(
                name, {'requests': 0, 'last_request': 0.0,
                       'last_evicted': 0.0})

    def adapters(self) -> List[str]:
        with self._adapter_lock:
            return sorted(self._adapters)

    def adapter_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-adapter demand/residency snapshot (serve status)."""
        resident = (set(self._adapter_pool.resident_names())
                    if self._adapter_pool is not None else set())
        out: Dict[str, Dict[str, float]] = {}
        with self._adapter_lock:
            for name, entry in self._adapters.items():
                demand = self._adapter_demand.get(name, {})
                out[name] = {
                    'rank': entry['rank'],
                    'resident': float(name in resident),
                    'active_slots': float(sum(
                        1 for n in self._slot_adapter_name
                        if n == name)),
                    'requests': float(demand.get('requests', 0)),
                    'last_request': float(demand.get('last_request',
                                                     0.0)),
                    'last_evicted': float(demand.get('last_evicted',
                                                     0.0)),
                }
        return out

    def _note_adapter_evict(self, name: str) -> None:
        """Observes every adapter-page eviction (chaos site + demand
        bookkeeping) BEFORE the pool mutates."""
        fault_injection.inject(LORA_EVICT_SITE)
        demand = self._adapter_demand.setdefault(
            name, {'requests': 0, 'last_request': 0.0,
                   'last_evicted': 0.0})
        demand['last_evicted'] = time.time()

    def _ensure_adapter_resident(self, name: str) -> Optional[int]:
        """Device page for ``name``, admitting (host -> device upload)
        on a miss. None = can't fit right now (HBM pressure — request
        stays queued, nothing retained). Raises on unknown adapters or
        injected fetch faults."""
        with self._adapter_lock:
            entry = self._adapters.get(name)
        if entry is None:
            raise KeyError(f'adapter {name!r} is not registered')
        page = self._adapter_pool.lookup(name)
        if page is not None:
            return page
        fault_injection.inject(LORA_FETCH_SITE)
        self._in_adapter_admit = True
        try:
            page = self._adapter_pool.admit(
                name, entry['nbytes'], alloc=self._alloc_block,
                on_evict=self._note_adapter_evict)
        finally:
            self._in_adapter_admit = False
        if page is None:
            return None
        self._lora_store = lora_lib.write_adapter_page(
            self._lora_store, page,
            {key: jnp.asarray(value)
             for key, value in entry['lora'].items()},
            alpha=entry['alpha'])
        return page

    def _quota_blocked(self, request: _Request) -> bool:
        """Per-adapter concurrency quota (SKYT_LORA_MAX_ACTIVE): an
        adapter at its cap waits in ITS lane; other lanes admit."""
        if not request.adapter or self._lora_max_active <= 0:
            return False
        active = sum(1 for name in self._slot_adapter_name
                     if name == request.adapter)
        return active >= self._lora_max_active

    def _lora_step_args(self):
        """(lora_pages, adapter_ids) for the jitted steps — (None,
        None) on a non-LoRA engine OR an all-base batch (no slot holds
        an adapter page), keeping those traces bitwise-identical to
        the pre-LoRA program: base-only traffic on a LoRA-enabled
        engine skips the gather einsums entirely."""
        if self._adapter_pool is None or \
                not self._slot_adapter.any():
            return (None, None)
        return (self._lora_store, jnp.asarray(self._slot_adapter))

    # -- admission + chunked prefill ------------------------------------

    def _admit(self) -> None:
        """Bookkeeping-only admission: assign a free slot, reference
        cached prefix blocks, allocate private blocks for the prompt.
        The compute (chunked prefill) happens in ``_prefill_tick``,
        interleaved with decode steps — never inline here."""
        if self._refresh_hold:
            return  # drain-mode refresh pending: admission held
        while True:
            try:
                self._waiting.push(self._pending.get_nowait())
            except queue.Empty:
                break
        while self._waiting:
            slot = next((s for s in range(self.max_slots)
                         if self._slots[s] is None), None)
            if slot is None:
                return
            if self._blocked_at_version == self._pool.version:
                return  # still HBM-blocked; nothing changed since
            request = self._waiting.pop(blocked=self._quota_blocked)
            if request is None:
                return  # every lane head is quota-blocked
            try:
                if not self._begin_prefill(request, slot):
                    # HBM pressure: the request resumes first in its
                    # lane (deficit refunded); retry only once the
                    # pool's alloc/ref/pin state has moved.
                    self._waiting.push_front(request)
                    self._blocked_at_version = self._pool.version
                    return
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('prefill admission failed')
                self._prefill_errors_total += 1
                self._finish(request, e)
                continue
            self._blocked_at_version = None

    def _begin_prefill(self, request: _Request, slot: int) -> bool:
        """Returns False when the pool can't fit the prompt right now
        (request stays queued); raises when it never can.

        A preempted request carries its already-generated tokens: they
        re-prefill as part of the visible sequence and decode resumes
        where it left off."""
        if request.migration is not None:
            try:
                return self._import_migrated(request, slot,
                                             request.migration)
            except Exception:  # pylint: disable=broad-except
                # Refcount-exact abort already ran: fall back to a
                # local re-prefill of the same tokens — fold-in-
                # position sampling keeps the stream identical.
                logger.exception(
                    'KV import failed; falling back to local '
                    're-prefill')
                self._kv_import_fallbacks_total += 1
                request.migration = None
                request.handoff_start = None
        ids = request.token_ids + request.generated
        plen = len(ids)
        needed_total = math.ceil(plen / self.block_size)
        if needed_total > self._pool.total_blocks:
            raise RuntimeError(
                f'prompt needs {needed_total} KV blocks; pool has '
                f'{self._pool.total_blocks} (raise num_blocks or '
                f'SKYT_INFER_BLOCK_SIZE granularity)')
        if request.adapter and self._adapter_pool is None:
            raise RuntimeError(
                f'request names adapter {request.adapter!r} but the '
                'engine has no adapter pages (lora_pages=0)')
        # LoRA v-deltas make cached V adapter-specific: each adapter
        # hashes its prefix chains under its own root salt, so base
        # and per-adapter chains share the pool but never collide.
        root = adapter_chain_root(request.adapter)
        shared: List[int] = []
        if self._prefix is not None:
            # Leave >= 1 prompt token to compute: the last token's
            # logits seed sampling and are never cached. Hit/miss
            # counters are bumped only once admission COMMITS below —
            # a blocked retry must not re-count reuse that never
            # happened.
            shared = self._prefix.lookup(ids, limit_tokens=plen - 1,
                                         root=root)
        blocks = list(shared)
        # Admission watermark: keep one tail block of headroom per
        # active decoder so admitting this prompt can't immediately
        # force a preemption storm. Only RECLAIMABLE prefix entries
        # count as available (this request's own shared refs and
        # blocks live slots share free nothing when evicted).
        need_private = needed_total - len(shared)
        avail = self._pool.free_blocks + (
            self._prefix.reclaimable_blocks if self._prefix is not None
            else 0)
        if avail < need_private + sum(self._decoding):
            for block in blocks:
                self._pool.decref(block)
            return False
        ok = True
        while len(blocks) < needed_total:
            block = self._alloc_block()
            if block is None:
                ok = False
                break
            blocks.append(block)
        if not ok:
            for block in blocks:
                self._pool.decref(block)
            return False
        adapter_page = 0
        if request.adapter:
            # After KV allocation (so adapter admission's own evictions
            # can't race the blocks above — they're ref'd), before the
            # commit. A raise here (unknown adapter, injected fetch
            # fault) fails the request; None (HBM-blocked) requeues it
            # with the pool exactly as it was.
            try:
                page = self._ensure_adapter_resident(request.adapter)
            except BaseException:
                for block in blocks:
                    self._pool.decref(block)
                raise
            if page is None:
                for block in blocks:
                    self._pool.decref(block)
                return False
            adapter_page = page
            self._adapter_pool.pin(request.adapter)
        start = len(shared) * self.block_size
        if self._prefix is not None:
            if shared:
                self._prefix_hits_total += 1
                self._prefix_tokens_reused_total += start
            else:
                self._prefix_misses_total += 1
        if not request.admitted:
            request.admitted = True
            wait_s = max(0.0, time.monotonic() - request.arrival)
            self._queue_wait_seconds_total += wait_s
            if request.span is not None:
                from skypilot_tpu.utils import tracing
                tracing.record_span('infer.queue_wait',
                                    request.span.context,
                                    request.arrival_wall, wait_s,
                                    service='inference')
        self._slot_blocks[slot] = blocks
        self._host_bt[slot, :] = 0
        self._host_bt[slot, :len(blocks)] = blocks
        self._host_len[slot] = start
        self._bt_dirty = True
        self._slots[slot] = request
        self._decoding[slot] = False
        self._slot_adapter[slot] = adapter_page
        self._slot_adapter_name[slot] = request.adapter or None
        self._admit_seq += 1
        self._admit_order[slot] = self._admit_seq
        self._prefilling.append(_PrefillState(request, slot, start, ids))
        return True

    def _prefill_tick(self) -> None:
        """Absorb ONE chunk of ONE prefilling prompt (FIFO). Called
        up to twice per loop iteration (once before the decode step,
        once overlapped with its host readback), so active decoders
        stall for at most TWO chunks of prefill compute per generated
        token — still bounded by the chunk budget, never by arriving
        prompt length."""
        if not self._prefilling:
            return
        state = self._prefilling[0]
        request, slot = state.request, state.slot
        ids = state.ids
        chunk = ids[state.pos:state.pos + self.prefill_chunk]
        tokens = np.zeros((1, self.prefill_chunk), np.int32)
        tokens[0, :len(chunk)] = chunk
        self._sync_tables()
        chunk_wall = time.time()
        chunk_mono = time.monotonic()
        lora_pages, _ = self._lora_step_args()
        adapter_id = (jnp.int32(int(self._slot_adapter[slot]))
                      if lora_pages is not None else None)
        try:
            last, cache = self._prefill_fn(
                self.params, jnp.asarray(tokens),
                jnp.int32(state.pos), jnp.int32(len(chunk)),
                jnp.int32(slot), self.cache, lora_pages, adapter_id)
        except Exception as e:  # pylint: disable=broad-except
            logger.exception('chunked prefill failed')
            self._prefilling.pop(0)
            self._fail_slot(slot, e, prefill=True)
            return
        self.cache = cache
        state.pos += len(chunk)
        self._host_len[slot] = state.pos
        self._prefill_chunks_total += 1
        if request.span is not None:
            from skypilot_tpu.utils import tracing
            tracing.record_span(
                'infer.prefill_chunk', request.span.context, chunk_wall,
                max(0.0, time.monotonic() - chunk_mono),
                service='inference', tokens=len(chunk), slot=slot,
                pos=state.pos)
        if state.pos >= len(ids):
            self._prefilling.pop(0)
            if self.role == 'prefill':
                # Prefill fleet: never decode — serialize the slot's
                # KV + last logits, park them for the decode side's
                # pull, give the blocks straight back to the pool
                # (the export holds host-memory copies).
                self._export_prefill(request, slot, ids, last[0])
                return
            self._last_logits = self._last_logits.at[slot].set(
                last[0].astype(jnp.float32))
            self._rngs[slot] = jax.random.key(request.seed)
            if self._draft is not None:
                # Seed the speculative pending token: exactly what the
                # plain step would sample next (same key, same logits).
                # One scalar readback per prefill completion.
                self._pending_tok[slot] = int(_sample_pending_step(
                    last[0].astype(jnp.float32), self._rngs[slot],
                    jnp.int32(state.pos),
                    jnp.float32(request.temperature)))
            self._decoding[slot] = True
            if request.decode_start_wall is None:
                request.decode_start_wall = time.time()
                request.decode_start_mono = time.monotonic()
            if self._prefix is not None:
                self._prefix.insert(ids, self._slot_blocks[slot],
                                    root=adapter_chain_root(
                                        request.adapter))

    # -- disaggregated prefill/decode (docs/disaggregated_serving.md) ---

    def _read_block_arrays(self, block_ids: List[int]
                           ) -> List[Dict[str, np.ndarray]]:
        """Host copies of the pool KV at ``block_ids`` (one batched
        device read), one name->array dict per block."""
        if not block_ids:
            return []
        idx = jnp.asarray(block_ids, jnp.int32)
        k = np.asarray(self.cache.k[:, idx])
        v = np.asarray(self.cache.v[:, idx])
        k_scale = (np.asarray(self.cache.k_scale[:, idx])
                   if self.cache.k_scale is not None else None)
        v_scale = (np.asarray(self.cache.v_scale[:, idx])
                   if self.cache.v_scale is not None else None)
        out = []
        for i in range(len(block_ids)):
            arrays = {'k': k[:, i], 'v': v[:, i]}
            if k_scale is not None:
                arrays['k_scale'] = k_scale[:, i]
                arrays['v_scale'] = v_scale[:, i]
            out.append(arrays)
        return out

    def _write_block_arrays(self, writes: List[tuple]) -> None:
        """Scatter ``(block_id, arrays)`` payloads into the pool (one
        batched device write per field)."""
        if not writes:
            return
        idx = jnp.asarray([b for b, _ in writes], jnp.int32)

        def stacked(name, dtype):
            return jnp.asarray(
                np.stack([a[name] for _, a in writes], axis=1), dtype)

        cache = self.cache
        cache = dataclasses.replace(
            cache,
            k=cache.k.at[:, idx].set(stacked('k', cache.k.dtype)),
            v=cache.v.at[:, idx].set(stacked('v', cache.v.dtype)))
        if cache.k_scale is not None:
            cache = dataclasses.replace(
                cache,
                k_scale=cache.k_scale.at[:, idx].set(
                    stacked('k_scale', cache.k_scale.dtype)),
                v_scale=cache.v_scale.at[:, idx].set(
                    stacked('v_scale', cache.v_scale.dtype)))
        self.cache = cache

    def _export_prefill(self, request: _Request, slot: int,
                        ids: List[int], last_row) -> None:
        """Prefill-role completion: serialize the slot's KV (full
        blocks individually — the migration delta unit — plus the
        partial tail block and last-logits row as the opaque tail),
        park it in the exporter, finish the request with zero
        generated tokens, and release the slot."""
        plen = len(ids)
        n_full = plen // self.block_size
        blocks = self._slot_blocks[slot]
        host = self._read_block_arrays(blocks)
        payloads = [kv_migrate.pack_arrays(host[i])
                    for i in range(n_full)]
        tail_arrays = {'logits': np.asarray(last_row, np.float32)}
        if plen % self.block_size:
            for name, array in host[n_full].items():
                tail_arrays[f'tail_{name}'] = array
        root = adapter_chain_root(request.adapter)
        export = kv_migrate.KvExport(
            request_id=request.request_id, ids=list(ids),
            block_size=self.block_size,
            digests=chain_digests(ids, self.block_size, root=root),
            blocks=payloads, tail=kv_migrate.pack_arrays(tail_arrays),
            meta={'seed': request.seed, 'n_tokens': plen,
                  'adapter': request.adapter or ''},
            created=time.monotonic())
        self.exporter.put(export)
        self._kv_exports_total += 1
        if self._prefix is not None:
            # Future prompts sharing this prefix prefill only their
            # suffix — and their exports list the shared blocks with
            # the same chain digests.
            self._prefix.insert(ids, blocks, root=root)
        self._finish(request)
        self._release_slot(slot)

    def _import_migrated(self, request: _Request, slot: int,
                         pulled) -> bool:
        """Decode-role admission of a migrated prefill: acquire blocks
        through a refcount-exact import transaction (resident prefix
        re-used in place, payloads written only into freshly allocated
        blocks), seed the sampling state, and enter decode directly —
        no prefill compute. Returns False when HBM can't fit it right
        now (request stays queued); raises on any integrity problem —
        the caller falls back to a local re-prefill with the pool and
        prefix cache exactly as they were."""
        ids = request.token_ids + request.generated
        plen = len(ids)
        manifest = pulled.manifest
        if (manifest['n_tokens'] != plen or
                manifest['block_size'] != self.block_size):
            raise RuntimeError(
                f'migration manifest mismatch: {manifest["n_tokens"]} '
                f'tokens/bs={manifest["block_size"]} vs local '
                f'{plen}/bs={self.block_size}')
        root = adapter_chain_root(request.adapter)
        digests = chain_digests(ids, self.block_size, root=root)
        if [row['digest'] for row in manifest['blocks']] != digests:
            raise RuntimeError('migration chain digests diverge from '
                               'the local token stream')
        n_full = plen // self.block_size
        needed_total = math.ceil(plen / self.block_size)
        if needed_total > self._pool.total_blocks:
            raise RuntimeError(
                f'migrated prompt needs {needed_total} KV blocks; '
                f'pool has {self._pool.total_blocks}')
        # Same admission watermark as _begin_prefill: keep one tail
        # block of headroom per active decoder.
        resident_now = (self._prefix.resident_chain(ids, root=root)
                        if self._prefix is not None else [])
        need_private = needed_total - len(resident_now)
        avail = self._pool.free_blocks + (
            self._prefix.reclaimable_blocks if self._prefix is not None
            else 0)
        if avail < need_private + sum(self._decoding):
            return False
        adapter_page = 0
        if request.adapter:
            page = self._ensure_adapter_resident(request.adapter)
            if page is None:
                return False
            adapter_page = page
            # Pin NOW: the import's own allocations below route
            # through _alloc_block, whose adapter-eviction fallback
            # must not reclaim the page this import depends on.
            self._adapter_pool.pin(request.adapter)
        importer = BlockImporter(self._pool, self._prefix)
        got = importer.begin(ids, needed_total,
                             block_size=self.block_size,
                             alloc=self._alloc_block, root=root)
        if got is None:
            if request.adapter:
                self._adapter_pool.unpin(request.adapter)
            return False
        blocks, n_resident = got
        try:
            writes = []
            for i in range(n_full):
                if i < n_resident:
                    continue  # resident: the cached copy is canonical
                payload = pulled.payloads[i]
                if payload is None:
                    # The pull's residency probe was optimistic and the
                    # entry was evicted since: the payload never moved.
                    raise RuntimeError(
                        f'block {i} evicted mid-migration and its '
                        'payload was not pulled')
                writes.append((blocks[i],
                               kv_migrate.unpack_arrays(payload)))
            tail = kv_migrate.unpack_arrays(pulled.tail)
            if plen % self.block_size:
                tail_block = {
                    name[len('tail_'):]: array
                    for name, array in tail.items()
                    if name.startswith('tail_')}
                if not tail_block:
                    raise RuntimeError('migration tail payload is '
                                       'missing the partial block')
                writes.append((blocks[n_full], tail_block))
            self._write_block_arrays(writes)
            self._last_logits = self._last_logits.at[slot].set(
                jnp.asarray(tail['logits'], jnp.float32))
        except Exception:
            importer.abort()
            if request.adapter:
                self._adapter_pool.unpin(request.adapter)
            raise
        importer.commit()
        if not request.admitted:
            request.admitted = True
            self._queue_wait_seconds_total += max(
                0.0, time.monotonic() - request.arrival)
        self._slot_blocks[slot] = list(blocks)
        self._host_bt[slot, :] = 0
        self._host_bt[slot, :len(blocks)] = blocks
        self._host_len[slot] = plen
        self._bt_dirty = True
        self._slots[slot] = request
        self._slot_adapter[slot] = adapter_page
        self._slot_adapter_name[slot] = request.adapter or None
        self._admit_seq += 1
        self._admit_order[slot] = self._admit_seq
        self._rngs[slot] = jax.random.key(request.seed)
        if self._draft is not None:
            self._pending_tok[slot] = int(_sample_pending_step(
                jnp.asarray(tail['logits'], jnp.float32),
                self._rngs[slot], jnp.int32(plen),
                jnp.float32(request.temperature)))
        self._decoding[slot] = True
        if request.decode_start_wall is None:
            request.decode_start_wall = time.time()
            request.decode_start_mono = time.monotonic()
        if self._prefix is not None:
            self._prefix.insert(ids, blocks, root=root)
        request.migration = None  # a later preemption re-prefills
        self._kv_imports_total += 1
        if request.handoff_start is not None:
            from skypilot_tpu.server import metrics
            metrics.DISAGG_HANDOFF.observe(
                max(0.0, time.monotonic() - request.handoff_start))
            request.handoff_start = None
        return True

    def _preempt(self, slot: int, active_mask: np.ndarray) -> None:
        """Release a slot's blocks (decoding OR mid-prefill) and
        requeue its request at the FRONT of the admission queue (it
        resumes by re-prefilling prompt + generated-so-far; fold-in-
        position sampling keeps the rng stream identical — see the
        _PrefillState note on kernel-level logits equivalence). The
        HBM-pressure valve: oversubscribed pools degrade to queueing,
        never to corrupt or dead requests."""
        request = self._slots[slot]
        self._prefilling = [s for s in self._prefilling
                            if s.slot != slot]
        self._release_slot(slot)
        active_mask[slot] = False
        self._preemptions_total += 1
        if request is not None:
            if request.span is not None:
                from skypilot_tpu.utils import tracing
                # Close the decode segment HERE: the requeue wait and
                # the resume's re-prefill must not be billed as decode.
                self._record_decode_segment(request)
                tracing.record_span(
                    'infer.preempt', request.span.context, time.time(),
                    0.0, service='inference', slot=slot,
                    generated=len(request.generated))
            self._waiting.push_front(request)
            self._wake.set()

    def _ensure_decode_blocks(self, active_mask: np.ndarray,
                              needed: Optional[np.ndarray] = None
                              ) -> None:
        """A slot about to write positions ``length .. length+n-1``
        needs tail blocks covering them BEFORE the step (``needed``
        per-slot token counts; default 1 — the plain decode boundary
        case). When the pool is exhausted even after prefix-cache
        eviction, the most recently admitted decoding request is
        preempted (vLLM policy: the oldest request always progresses,
        so the system drains)."""
        for slot in range(self.max_slots):
            if not active_mask[slot]:
                continue
            length = int(self._host_len[slot])
            n_new = 1 if needed is None else int(needed[slot])
            if n_new <= 0:
                continue
            last_pos = min(length + n_new, self.max_len) - 1
            failed = False
            for index in range(length // self.block_size,
                               last_pos // self.block_size + 1):
                if index >= self.blocks_per_slot:
                    break  # finish check retires it this step
                if self._host_bt[slot, index] != 0:
                    continue
                while True:
                    block = self._alloc_block()
                    if block is not None:
                        self._slot_blocks[slot].append(block)
                        self._host_bt[slot, index] = block
                        self._bt_dirty = True
                        break
                    # Victims: any OTHER slot holding blocks — decoding
                    # or mid-prefill (a pool drained into prefills must
                    # not strand the decoders).
                    victims = [s for s in range(self.max_slots)
                               if s != slot and
                               self._slots[s] is not None]
                    if not victims:
                        # Nothing left to steal from: this request
                        # alone outgrew the pool — fail it loudly.
                        active_mask[slot] = False
                        self._fail_slot(slot, RuntimeError(
                            'KV block pool exhausted mid-decode (raise '
                            'num_blocks or lower max_slots)'))
                        failed = True
                        break
                    victim = max(victims,
                                 key=lambda s: self._admit_order[s])
                    self._preempt(victim, active_mask)
                if failed:
                    break

    def _trim_slot_blocks(self, slot: int, new_len: int) -> None:
        """Speculative rollback: positions >= ``new_len`` are dead
        (rejected drafts / post-eos rows) — decref any tail blocks past
        the last live one back to the pool, exactly the block state a
        non-speculative run at ``new_len`` would hold. Shared prefix
        blocks always cover positions < the prefill length <= new_len,
        so only private verify-window blocks are ever trimmed."""
        keep = -(-new_len // self.block_size)
        blocks = self._slot_blocks[slot]
        if len(blocks) <= keep:
            return
        for index in range(keep, len(blocks)):
            self._pool.decref(blocks[index])
            self._host_bt[slot, index] = 0
        self._slot_blocks[slot] = blocks[:keep]
        self._bt_dirty = True

    # -- speculative verify step ----------------------------------------

    def _spec_step(self, active_mask: np.ndarray) -> None:
        """Draft → batched verify → accept/rollback for every decoding
        slot; scheduled exactly like a decode step (the caller's
        prefill interleave and preemption paths are unchanged)."""
        q_len = self._spec_window
        inputs = np.zeros((self.max_slots, q_len), np.int32)
        n_input = np.ones((self.max_slots,), np.int32)
        for slot in range(self.max_slots):
            if not active_mask[slot]:
                continue
            request = self._slots[slot]
            length = int(self._host_len[slot])
            # Never draft past what the request can still emit or the
            # slot can still hold: a shrunken window degrades to a
            # plain decode step, not a stall.
            cap = max(1, min(q_len,
                             request.max_new_tokens -
                             len(request.generated),
                             self.max_len - length))
            pending = int(self._pending_tok[slot])
            inputs[slot, 0] = pending
            n = 1
            if cap > 1:
                history = (request.token_ids + request.generated +
                           [pending])
                for tok in self._draft.propose(history, cap - 1):
                    inputs[slot, n] = int(tok)
                    n += 1
            n_input[slot] = n
        self._ensure_decode_blocks(active_mask, needed=n_input)
        if not active_mask.any():
            return
        self._draft_tokens_total += int(
            (n_input[active_mask] - 1).sum())
        self._sync_tables()
        temps = np.array([r.temperature if r else 0.0
                          for r in self._slots], np.float32)
        lora_pages, adapter_ids = self._lora_step_args()
        step_t0 = time.perf_counter()
        try:
            n_emit, pending_next, cache = self._spec_fn(
                self.params, self.cache, jnp.asarray(inputs),
                jnp.asarray(n_input), jnp.asarray(active_mask),
                jnp.asarray(temps), jnp.stack(self._rngs),
                lora_pages, adapter_ids)
        except Exception as e:  # pylint: disable=broad-except
            logger.exception('speculative verify step failed')
            for slot in range(self.max_slots):
                if active_mask[slot] and self._slots[slot] is not None:
                    self._fail_slot(slot, e)
            return
        self.cache = cache
        self._verify_steps_total += 1
        # Overlap the readback with admission BOOKKEEPING only: a
        # prefill tick would _sync_tables, and the host length mirror
        # for verified slots is stale until n_emit lands (the plain
        # step can mirror +1 eagerly; the verify advance is
        # data-dependent).
        try:
            n_emit.copy_to_host_async()
            pending_next.copy_to_host_async()
        except AttributeError:
            pass
        overlap_t0 = time.perf_counter()
        self._admit()
        overlap_cost = time.perf_counter() - overlap_t0
        host_emit = np.asarray(n_emit)
        host_pending = np.asarray(pending_next)
        self._decode_seconds_total += (time.perf_counter() - step_t0 -
                                       overlap_cost)
        for slot in range(self.max_slots):
            request = self._slots[slot]
            if request is None or not active_mask[slot]:
                continue
            m = int(host_emit[slot])
            if m <= 0:
                continue
            emitted = []
            for tok in inputs[slot, :m]:
                emitted.append(int(tok))
                if (request.eos_id is not None and
                        int(tok) == request.eos_id):
                    break  # post-eos acceptances are dead rows
            # Count acceptance AFTER truncation: device-accepted drafts
            # discarded as post-eos dead rows were never delivered, and
            # the derivable acceptance rate must track delivered tokens.
            self._accepted_tokens_total += len(emitted) - 1
            request.generated.extend(emitted)
            self._tokens_total += len(emitted)
            length0 = int(self._host_len[slot])
            new_len = length0 + len(emitted)
            self._host_len[slot] = new_len
            if len(emitted) < m:
                # Device advanced by m: re-sync the truncated mirror.
                self._bt_dirty = True
            self._trim_slot_blocks(slot, new_len)
            self._pending_tok[slot] = int(host_pending[slot])
            finished = (
                (request.eos_id is not None and
                 emitted[-1] == request.eos_id) or
                len(request.generated) >= request.max_new_tokens or
                new_len >= self.max_len)
            if finished:
                observe = getattr(self._draft, 'observe', None)
                if observe is not None:
                    # Feed the completion corpus: repeated/near-repeated
                    # queries draft their answer from the last one.
                    observe(request.token_ids + request.generated)
                self._finish(request)
                self._release_slot(slot)  # blocks back to the pool

    # -- serving loop ---------------------------------------------------

    def _loop(self) -> None:
        from skypilot_tpu.inference.sharding import mesh_context
        with mesh_context(self._mesh):
            self._loop_body()

    # -- live weight refresh --------------------------------------------

    def request_refresh(self, updates=None, *, params=None,
                        version: Optional[int] = None,
                        mode: str = 'step') -> _WeightRefresh:
        """Queue a live weight refresh; returns the ticket (wait on
        ``.done``, then check ``.error``).

        Exactly one of ``updates`` (a ``flatten_param_paths``-keyed
        dict of new shard values — the delta path) or ``params`` (a
        full replacement pytree matching the engine's param structure)
        must be given. ``mode='step'`` (default) swaps at the next
        step boundary with generation still running; ``mode='drain'``
        holds admission and waits for in-flight requests first."""
        if (updates is None) == (params is None):
            raise ValueError(
                'pass exactly one of updates= (delta shards) or '
                'params= (full tree)')
        if mode not in ('step', 'drain'):
            raise ValueError(
                f"refresh mode must be 'step' or 'drain', got {mode!r}")
        ticket = _WeightRefresh(updates, params, version, mode)
        self._refresh_queue.put(ticket)
        self._wake.set()
        return ticket

    def refresh_weights(self, updates=None, *, params=None,
                        version: Optional[int] = None,
                        mode: str = 'step',
                        timeout: float = 120.0) -> int:
        """Blocking :meth:`request_refresh`; returns the new
        ``policy_version``."""
        ticket = self.request_refresh(updates, params=params,
                                      version=version, mode=mode)
        if not ticket.done.wait(timeout):
            raise TimeoutError('weight refresh timed out')
        if ticket.error is not None:
            raise ticket.error
        return self.policy_version

    def _device_put_like(self, new, old):
        """Place a refreshed shard exactly where the old one lives:
        under a mesh the old leaf's NamedSharding transfers, so
        refresh is per-shard along the GSPMD layout — no host
        re-gather, no resharding traffic."""
        new = jnp.asarray(new, getattr(old, 'dtype', None))
        sharding = getattr(old, 'sharding', None)
        if self._mesh is not None and sharding is not None:
            return jax.device_put(new, sharding)
        return new

    def _apply_updates(self, params, updates):
        applied = set()

        def walk(node, prefix):
            if isinstance(node, dict):
                return {k: walk(v, prefix + (str(k),))
                        for k, v in node.items()}
            if isinstance(node, (list, tuple)):
                return type(node)(walk(v, prefix + (str(i),))
                                  for i, v in enumerate(node))
            path = '/'.join(prefix)
            if path in updates:
                applied.add(path)
                return self._device_put_like(updates[path], node)
            return node

        new_params = walk(params, ())
        unknown = sorted(set(updates) - applied)
        if unknown:
            raise KeyError(
                f'refresh updates name {len(unknown)} unknown param '
                f'shards (first: {unknown[:3]}); learner and engine '
                f'param trees have diverged')
        return new_params, len(applied)

    def _refresh_tick(self) -> None:
        """Serving-loop-only: apply queued weight refreshes at the
        step boundary (the caller invokes this at the top of a loop
        iteration, before admission/prefill/decode touch params)."""
        if self._refresh_queue.empty():
            return
        ticket = self._refresh_queue.queue[0]  # peek; sole consumer
        if ticket.mode == 'drain':
            self._refresh_hold = True
            if any(r is not None for r in self._slots) or \
                    self._prefilling:
                return  # in-flight work finishes on the OLD policy
        self._refresh_queue.get_nowait()
        t0 = time.perf_counter()
        try:
            fault_injection.inject(WEIGHT_REFRESH_SITE)
            if ticket.params is not None:
                self.params = jax.tree_util.tree_map(
                    lambda o, n: self._device_put_like(n, o),
                    self.params, ticket.params)
                n_shards = len(jax.tree_util.tree_leaves(self.params))
            else:
                self.params, n_shards = self._apply_updates(
                    self.params, ticket.updates)
            self.policy_version = (int(ticket.version)
                                   if ticket.version is not None
                                   else self.policy_version + 1)
            ticket.applied_shards = n_shards
            self._weight_refreshes_total += 1
            self._refresh_shards_total += n_shards
        except BaseException as e:  # pylint: disable=broad-except
            ticket.error = e
            logger.exception('live weight refresh failed')
        finally:
            self._refresh_seconds_total += time.perf_counter() - t0
            queued = self._refresh_queue.queue
            self._refresh_hold = bool(queued) and \
                queued[0].mode == 'drain'
            ticket.done.set()

    def _loop_body(self) -> None:
        while not self._stop.is_set():
            self._refresh_tick()
            self._admit()
            self._prefill_tick()
            active_mask = np.array(self._decoding, bool)
            if not active_mask.any():
                if self._prefilling or self._waiting or \
                        not self._pending.empty():
                    continue  # keep absorbing prefill chunks
                self._wake.wait(0.01)
                self._wake.clear()
                continue
            if self._draft is not None:
                self._spec_step(active_mask)
                continue
            self._ensure_decode_blocks(active_mask)
            if not active_mask.any():
                continue
            self._sync_tables()
            temps = np.array([r.temperature if r else 0.0
                              for r in self._slots], np.float32)
            lora_pages, adapter_ids = self._lora_step_args()
            step_t0 = time.perf_counter()
            try:
                tokens, logits, cache = self._decode_fn(
                    self.params, self._last_logits, self.cache,
                    jnp.asarray(active_mask), jnp.asarray(temps),
                    jnp.stack(self._rngs), lora_pages, adapter_ids)
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('continuous decode step failed')
                for slot in range(self.max_slots):
                    if active_mask[slot] and self._slots[slot] is not None:
                        self._fail_slot(slot, e)
                continue
            self.cache = cache
            self._last_logits = logits
            # The step advanced every active slot by one position
            # (deterministic) — mirror it on the host now so overlap
            # work below sees consistent lengths.
            self._host_len[active_mask] += 1
            # Overlap the host readback with useful work: start the
            # async device->host copy, then dispatch the next prefill
            # chunk / admission bookkeeping while the step (and the
            # copy) complete — no hard sync in the middle of the loop.
            try:
                tokens.copy_to_host_async()
            except AttributeError:
                pass
            overlap_t0 = time.perf_counter()
            self._admit()
            self._prefill_tick()
            # decode_seconds feeds tokens/s derivations: exclude the
            # host-side admission/prefill bookkeeping done in the
            # overlap window from the decode-step accounting.
            overlap_cost = time.perf_counter() - overlap_t0
            host_tokens = np.asarray(tokens)
            self._decode_seconds_total += (time.perf_counter() -
                                           step_t0 - overlap_cost)
            for slot in range(self.max_slots):
                request = self._slots[slot]
                if request is None or not active_mask[slot]:
                    continue
                token = int(host_tokens[slot])
                self._tokens_total += 1
                request.generated.append(token)
                finished = (
                    (request.eos_id is not None and
                     token == request.eos_id) or
                    len(request.generated) >= request.max_new_tokens or
                    self._host_len[slot] >= self.max_len)
                if finished:
                    self._finish(request)
                    self._release_slot(slot)  # blocks back to the pool

    # -- public API -----------------------------------------------------

    def _submit(self, token_ids: List[int], max_new_tokens: int,
                temperature: float, eos_id: Optional[int],
                seed: int, trace_ctx=None, migration=None,
                handoff_start: Optional[float] = None,
                adapter: Optional[str] = None) -> _Request:
        """Shared admission path: validate + enqueue (both the blocking
        and streaming entries; the policy must not drift between them).

        ``trace_ctx`` (a tracing.SpanContext, e.g. parsed from the
        serving request's traceparent) opens a per-request engine span
        with queue-wait / prefill-chunk / decode / preempt children."""
        if len(token_ids) >= self.max_len:
            # Reject loudly: silently truncating a prompt answers a
            # question the caller never asked.
            raise ValueError(
                f'prompt is {len(token_ids)} tokens; engine max_len is '
                f'{self.max_len} (prompt + generation must fit)')
        if self.role == 'prefill' and max_new_tokens > 0:
            raise RuntimeError(
                'a prefill-role engine never decodes; use '
                'prefill_and_export (or clear SKYT_DISAGG_ROLE)')
        if adapter:
            # Reject unknown adapters EAGERLY (callers get a clean
            # error, not an async prefill failure) and count demand.
            with self._adapter_lock:
                if adapter not in self._adapters:
                    raise ValueError(
                        f'adapter {adapter!r} is not registered '
                        f'(register_adapter first)')
                demand = self._adapter_demand.setdefault(
                    adapter, {'requests': 0, 'last_request': 0.0,
                              'last_evicted': 0.0})
                demand['requests'] += 1
                demand['last_request'] = time.time()
        request = _Request(token_ids, max_new_tokens, temperature,
                           eos_id, seed, trace_ctx=trace_ctx,
                           adapter=adapter or None)
        request.policy_version = self.policy_version
        self._request_seq += 1
        request.request_id = f'r{self._request_seq}'
        request.migration = migration
        request.handoff_start = handoff_start
        if trace_ctx is not None:
            from skypilot_tpu.utils import tracing
            request.span = tracing.start_span(
                'infer.request', parent=trace_ctx, service='inference',
                prompt_tokens=len(token_ids),
                max_new_tokens=max_new_tokens)
        self._requests_total += 1
        self._pending.put(request)
        self._wake.set()
        return request

    # -- disaggregated-serving public surface ---------------------------

    def prefill_and_export(self, token_ids: List[int], *,
                           temperature: float = 0.0,
                           eos_id: Optional[int] = None,
                           seed: int = 0,
                           timeout: float = 300.0,
                           trace_ctx=None) -> str:
        """Prefill-role entry: absorb the prompt (chunked, prefix-
        cache-accelerated) and park the serialized KV in
        ``self.exporter``. Returns the request id the export is keyed
        by — the decode side pulls ``/kv/manifest/<id>`` etc. from
        this replica's migration surface."""
        if self.role != 'prefill':
            raise RuntimeError(
                "prefill_and_export needs role='prefill' "
                '(SKYT_DISAGG_ROLE)')
        request = self._submit(token_ids, 0, temperature, eos_id, seed,
                               trace_ctx=trace_ctx)
        if not request.done.wait(timeout):
            raise TimeoutError('prefill timed out')
        if request.error is not None:
            raise request.error
        return request.request_id

    def probe_resident(self, token_ids: List[int],
                       adapter: Optional[str] = None) -> List[int]:
        """Chain digests of the full-block prefix already resident in
        this engine's PrefixCache — read-only and thread-safe, the
        decode side's input to the migration delta manifest (those
        blocks are skipped by the pull). Adapter chains live under
        their own root salt, so probe with the same adapter the
        request will decode with."""
        if self._prefix is None:
            return []
        return self._prefix.resident_chain(
            token_ids, root=adapter_chain_root(adapter))

    def submit_migrated(self, token_ids: List[int], pulled, *,
                        max_new_tokens: int = 32,
                        temperature: float = 0.0,
                        eos_id: Optional[int] = None,
                        seed: int = 0,
                        trace_ctx=None,
                        handoff_start: Optional[float] = None
                        ) -> _Request:
        """Decode-role entry: admit a pulled migration
        (``kv_migrate.PulledKv``) — the serving loop imports the
        blocks refcount-exactly and starts decoding WITHOUT a prefill
        pass; any import failure falls back to a local re-prefill of
        ``token_ids``, so the request always completes. Returns the
        request handle; stream with :meth:`tail_tokens` or block on
        ``request.done``. ``handoff_start`` (time.monotonic) stamps
        ``skyt_disagg_handoff_seconds`` when the import lands."""
        if self.role == 'prefill':
            raise RuntimeError("a prefill-role engine never decodes; "
                               "submit_migrated needs role='decode' "
                               "(or colocated)")
        return self._submit(token_ids, max_new_tokens, temperature,
                            eos_id, seed, trace_ctx=trace_ctx,
                            migration=pulled,
                            handoff_start=handoff_start)

    def tail_tokens(self, request: _Request, *,
                    eos_id: Optional[int] = None,
                    timeout: float = 300.0):
        """Yield a submitted request's tokens as they land (the
        streaming tail ``stream_ids`` is built on)."""
        emitted = 0
        deadline = time.monotonic() + timeout
        while True:
            finished = request.done.is_set()
            generated = request.generated
            while emitted < len(generated):
                token = generated[emitted]
                emitted += 1
                if eos_id is not None and token == eos_id:
                    return
                yield token
            if finished:
                if request.error is not None:
                    raise request.error
                return
            if time.monotonic() > deadline:
                raise TimeoutError('generation timed out')
            time.sleep(0.005)

    def submit_ids(self, token_ids: List[int], *,
                   max_new_tokens: int = 32,
                   temperature: float = 0.0,
                   eos_id: Optional[int] = None,
                   seed: int = 0,
                   adapter: Optional[str] = None) -> _Request:
        """Non-blocking admission for batch producers (the RL rollout
        path submits a whole prompt group, then harvests): returns
        the request handle — wait on ``handle.done``, then read
        ``handle.generated`` / ``handle.error``. The handle's
        ``policy_version`` records which weights admitted it."""
        return self._submit(token_ids, max_new_tokens, temperature,
                            eos_id, seed, adapter=adapter)

    def generate_ids(self, token_ids: List[int], *,
                     max_new_tokens: int = 32,
                     temperature: float = 0.0,
                     eos_id: Optional[int] = None,
                     seed: int = 0,
                     timeout: float = 300.0,
                     trace_ctx=None,
                     adapter: Optional[str] = None) -> List[int]:
        request = self._submit(token_ids, max_new_tokens, temperature,
                               eos_id, seed, trace_ctx=trace_ctx,
                               adapter=adapter)
        if not request.done.wait(timeout):
            raise TimeoutError('generation timed out')
        if request.error is not None:
            raise request.error
        generated = request.generated
        if eos_id is not None and eos_id in generated:
            generated = generated[:generated.index(eos_id)]
        return generated

    def _require_tokenizer(self) -> None:
        if not self._tokenizer_fits:
            raise ValueError(
                f'Model vocab {self.cfg.vocab_size} < tokenizer '
                f'vocab {self.tokenizer.vocab_size}; text APIs are '
                f'unavailable (use the *_ids entry points)')

    def generate_text(self, prompt: str, **kwargs: Any) -> str:
        self._require_tokenizer()
        ids = self.tokenizer.encode(prompt)
        out = self.generate_ids(ids, eos_id=self.tokenizer.eos_id,
                                **kwargs)
        return self.tokenizer.decode(out)

    def stream_ids(self, token_ids: List[int], *,
                   max_new_tokens: int = 32,
                   temperature: float = 0.0,
                   eos_id: Optional[int] = None,
                   seed: int = 0,
                   timeout: float = 300.0,
                   trace_ctx=None,
                   adapter: Optional[str] = None):
        """Yield generated token ids AS THEY LAND in the slot loop
        (the decode thread appends to request.generated; this iterator
        tails it) — the vLLM/JetStream streaming serving shape.

        Validation/admission happens EAGERLY (same as generate_ids: an
        over-long prompt raises here, not at first iteration)."""
        request = self._submit(token_ids, max_new_tokens, temperature,
                               eos_id, seed, trace_ctx=trace_ctx,
                               adapter=adapter)
        return self.tail_tokens(request, eos_id=eos_id, timeout=timeout)

    def stream_text(self, prompt: str, **kwargs: Any):
        """Yield text DELTAS: ids decode cumulatively (single BPE
        tokens may be partial UTF-8; the running decode keeps deltas
        well-formed)."""
        self._require_tokenizer()
        ids = self.tokenizer.encode(prompt)
        out_ids: List[int] = []
        text_so_far = ''
        for token in self.stream_ids(ids, eos_id=self.tokenizer.eos_id,
                                     **kwargs):
            out_ids.append(token)
            text = self.tokenizer.decode(out_ids)
            delta, text_so_far = text[len(text_so_far):], text
            if delta:
                yield delta

    def generate_texts(self, prompts: List[str],
                       **kwargs: Any) -> List[str]:
        """Concurrent multi-prompt entry (the HTTP payload's batch API):
        each prompt is its own slot request, so they genuinely overlap."""
        import concurrent.futures
        # Bounded pool: a huge prompt list must not fan out into
        # thousands of OS threads — beyond ~2x the slot count extra
        # callers would only queue anyway.
        workers = max(1, min(len(prompts), 2 * self.max_slots))
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=workers) as pool:
            futures = [pool.submit(self.generate_text, p, **kwargs)
                       for p in prompts]
            return [f.result() for f in futures]

    def stats(self) -> Dict[str, float]:
        total = self._pool.total_blocks
        free = self._pool.free_blocks
        return {
            'slots': self.max_slots,
            'active': sum(r is not None for r in self._slots),
            'pending': self._pending.qsize() + len(self._waiting),
            # Monotonic counters (Prometheus counter type on /metrics).
            'requests': self._requests_total,
            'completions': self._completions_total,
            'request_errors': self._errors_total,
            'prefill_errors': self._prefill_errors_total,
            'prefill_chunks': self._prefill_chunks_total,
            'tokens_generated': self._tokens_total,
            'decode_seconds': round(self._decode_seconds_total, 4),
            'queue_wait_seconds': round(self._queue_wait_seconds_total,
                                        4),
            'prefix_cache_hits': self._prefix_hits_total,
            'prefix_cache_misses': self._prefix_misses_total,
            'prefix_tokens_reused': self._prefix_tokens_reused_total,
            'preemptions': self._preemptions_total,
            # Disaggregated serving (zero in colocated engines).
            'kv_exports': self._kv_exports_total,
            'kv_imports': self._kv_imports_total,
            'kv_import_fallbacks': self._kv_import_fallbacks_total,
            'kv_exports_pending': (len(self.exporter)
                                   if self.exporter is not None else 0),
            # Speculative decoding: acceptance rate is derivable as
            # accepted_tokens / draft_tokens (both counters, so it
            # rate()s correctly over any window).
            'draft_tokens': self._draft_tokens_total,
            'accepted_tokens': self._accepted_tokens_total,
            'verify_steps': self._verify_steps_total,
            'spec_window': self._spec_window,
            # Live weight refresh (RL rollout serving; zero on engines
            # that never refresh). policy_version is a gauge.
            'policy_version': self.policy_version,
            'weight_refreshes': self._weight_refreshes_total,
            'refresh_shards': self._refresh_shards_total,
            'refresh_seconds': round(self._refresh_seconds_total, 4),
            # Multi-LoRA (zero on engines with no adapter pages).
            'lora_hits': (self._adapter_pool.hits
                          if self._adapter_pool is not None else 0),
            'lora_misses': (self._adapter_pool.misses
                            if self._adapter_pool is not None else 0),
            'lora_evictions': (self._adapter_pool.evictions
                               if self._adapter_pool is not None
                               else 0),
            'lora_pages_total': (self._adapter_pool.n_pages
                                 if self._adapter_pool is not None
                                 else 0),
            'lora_pages_resident': (self._adapter_pool.resident_pages
                                    if self._adapter_pool is not None
                                    else 0),
            'lora_blocks_charged': (self._adapter_pool.blocks_charged
                                    if self._adapter_pool is not None
                                    else 0),
            'lora_adapters_registered': len(self._adapters),
            # Point-in-time gauges: paged-pool pressure.
            'block_size': self.block_size,
            'blocks_total': total,
            'blocks_free': free,
            'blocks_cached': (self._prefix.cached_blocks
                              if self._prefix is not None else 0),
            'block_occupancy': round((total - free) / total, 4)
            if total else 0.0,
        }

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)
