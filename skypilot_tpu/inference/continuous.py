"""Continuous batching: slot-based serving over a fixed decode program.

The JetStream/vLLM serving core, TPU-first: the KV cache is allocated
ONCE for ``max_slots`` sequences, decode is ONE jitted program stepping
all slots together (static shapes — nothing recompiles as traffic
changes), and a scheduler thread admits requests into free slots as
others finish. Unlike the batch-synchronous ``InferenceEngine`` (a new
request waits for the whole batch), a finished sequence's slot is
refilled immediately — the latency/throughput profile that makes
serving economical on TPU.

Prefill is per-request (its own bucketed program) and its KV rows are
spliced into the shared cache at the slot index; decode masks inactive
slots (models/decode.py decode_step(active=...)).
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.inference.tokenizer import get_tokenizer
from skypilot_tpu.models import decode as decode_lib
from skypilot_tpu.models import llama
from skypilot_tpu.models.config import ModelConfig, get_model_config
from skypilot_tpu.utils import log

logger = log.init_logger(__name__)


def _bucket(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class _Request:
    def __init__(self, token_ids: List[int], max_new_tokens: int,
                 temperature: float, eos_id: Optional[int],
                 seed: int) -> None:
        self.token_ids = token_ids
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.eos_id = eos_id
        self.seed = seed
        self.generated: List[int] = []
        self.done = threading.Event()
        self.error: Optional[BaseException] = None


class ContinuousBatchingEngine:
    """generate() admits into the shared decode loop; thread-safe."""

    def __init__(self,
                 model: str = 'tiny',
                 *,
                 cfg: Optional[ModelConfig] = None,
                 params: Optional[Any] = None,
                 checkpoint_dir: Optional[str] = None,
                 hf_checkpoint: Optional[str] = None,
                 max_slots: int = 4,
                 max_len: Optional[int] = None,
                 seed: int = 0,
                 quantize: bool = False,
                 quantize_kv: bool = False,
                 mesh: Optional[Any] = None) -> None:
        # Real-weights path: see engine.py (models/hf_interop.py).
        if hf_checkpoint:
            from skypilot_tpu.models import hf_interop
            params, cfg = hf_interop.resolve_engine_inputs(
                hf_checkpoint, params, cfg)
        self.cfg = cfg or get_model_config(model)
        if quantize_kv:
            from skypilot_tpu.models.config import with_int8_kv_cache
            self.cfg = with_int8_kv_cache(self.cfg)
        self.tokenizer = get_tokenizer(hf_checkpoint,
                                       require=bool(hf_checkpoint))
        if self.tokenizer.vocab_size > self.cfg.vocab_size:
            raise ValueError(
                f'Model vocab {self.cfg.vocab_size} < tokenizer '
                f'vocab {self.tokenizer.vocab_size}')
        self.max_slots = max_slots
        # Cache length defaults to the model's full context (the cache
        # is allocated once: max_slots * max_len rows).
        self.max_len = min(max_len or self.cfg.max_seq_len,
                           self.cfg.max_seq_len)
        if params is not None:
            self.params = params
        elif checkpoint_dir:
            from skypilot_tpu.train.checkpoint import restore_latest
            restored = restore_latest(
                checkpoint_dir,
                lambda: llama.init_params(jax.random.key(seed), self.cfg))
            self.params = (restored['params']
                           if isinstance(restored, dict) and
                           'params' in restored else restored)
        else:
            self.params = llama.init_params(jax.random.key(seed),
                                            self.cfg)
        # Mesh placement first, then quantization (see engine.py note).
        from skypilot_tpu.inference.sharding import prepare_engine
        self.params, self.cfg, self._mesh = prepare_engine(
            self.params, self.cfg, mesh)
        from skypilot_tpu.models.quant import maybe_quantize
        self.params = maybe_quantize(self.params, quantize)
        self.cache = decode_lib.init_cache(self.cfg, max_slots,
                                           self.max_len)
        self._slots: List[Optional[_Request]] = [None] * max_slots
        self._rngs = [jax.random.key(seed + 1 + i)
                      for i in range(max_slots)]
        self._last_logits = jnp.zeros((max_slots, self.cfg.vocab_size),
                                      jnp.float32)
        self._pending: 'queue.Queue[_Request]' = queue.Queue()
        self._requests_total = 0
        self._tokens_total = 0
        self._decode_seconds_total = 0.0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name='continuous-batching',
                                        daemon=True)
        self._decode_fn = jax.jit(self._decode_all)
        self._thread.start()

    # -- jitted pieces --------------------------------------------------

    def _decode_all(self, params, last_logits, cache, active, temps,
                    rngs):
        """One step for every slot: sample from last logits, advance."""
        keys = jax.vmap(jax.random.fold_in)(rngs, cache.lengths)
        greedy = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
        sampled = jax.vmap(
            lambda k, l, t: jax.random.categorical(
                k, l / jnp.maximum(t, 1e-6)))(keys, last_logits,
                                              temps).astype(jnp.int32)
        tokens = jnp.where(temps <= 0.0, greedy, sampled)
        logits, cache = decode_lib.decode_step(params, tokens, cache,
                                               self.cfg, active=active)
        return tokens, logits, cache

    def _prefill_slot(self, request: _Request, slot: int) -> None:
        ids = request.token_ids
        bucket = min(_bucket(len(ids)), self.max_len)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :len(ids)] = ids
        lengths = jnp.array([len(ids)], jnp.int32)
        logits, small = decode_lib.prefill(self.params,
                                           jnp.asarray(tokens), lengths,
                                           self.cfg, self.max_len)
        # Splice the single-sequence cache into the shared one at `slot`.
        def splice(big, one):
            return jax.lax.dynamic_update_slice_in_dim(big, one, slot,
                                                       axis=1)
        self.cache = decode_lib.KVCache(
            k=splice(self.cache.k, small.k),
            v=splice(self.cache.v, small.v),
            lengths=self.cache.lengths.at[slot].set(lengths[0]),
            k_scale=(splice(self.cache.k_scale, small.k_scale)
                     if self.cache.quantized else None),
            v_scale=(splice(self.cache.v_scale, small.v_scale)
                     if self.cache.quantized else None))
        self._last_logits = self._last_logits.at[slot].set(
            logits[0].astype(jnp.float32))
        self._rngs[slot] = jax.random.key(request.seed)
        self._slots[slot] = request

    # -- serving loop ---------------------------------------------------

    def _loop(self) -> None:
        from skypilot_tpu.inference.sharding import mesh_context
        with mesh_context(self._mesh):
            self._loop_body()

    def _loop_body(self) -> None:
        while not self._stop.is_set():
            self._admit()
            active_mask = np.array([r is not None for r in self._slots])
            if not active_mask.any():
                self._wake.wait(0.01)
                self._wake.clear()
                continue
            temps = np.array([r.temperature if r else 0.0
                              for r in self._slots], np.float32)
            import time as time_lib
            step_t0 = time_lib.perf_counter()
            try:
                tokens, logits, cache = self._decode_fn(
                    self.params, self._last_logits, self.cache,
                    jnp.asarray(active_mask), jnp.asarray(temps),
                    jnp.stack(self._rngs))
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('continuous decode step failed')
                for slot, request in enumerate(self._slots):
                    if request is not None:
                        request.error = e
                        request.done.set()
                        self._slots[slot] = None
                continue
            self.cache = cache
            self._last_logits = logits
            host_tokens = np.asarray(tokens)
            lengths = np.asarray(cache.lengths)
            self._decode_seconds_total += (time_lib.perf_counter() -
                                           step_t0)
            for slot, request in enumerate(self._slots):
                if request is None:
                    continue
                token = int(host_tokens[slot])
                self._tokens_total += 1
                request.generated.append(token)
                finished = (
                    (request.eos_id is not None and
                     token == request.eos_id) or
                    len(request.generated) >= request.max_new_tokens or
                    lengths[slot] >= self.max_len)
                if finished:
                    request.done.set()
                    self._slots[slot] = None  # slot free for admission

    def _admit(self) -> None:
        for slot in range(self.max_slots):
            if self._slots[slot] is not None:
                continue
            try:
                request = self._pending.get_nowait()
            except queue.Empty:
                break
            try:
                self._prefill_slot(request, slot)
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('prefill failed')
                request.error = e
                request.done.set()

    # -- public API -----------------------------------------------------

    def _submit(self, token_ids: List[int], max_new_tokens: int,
                temperature: float, eos_id: Optional[int],
                seed: int) -> _Request:
        """Shared admission path: validate + enqueue (both the blocking
        and streaming entries; the policy must not drift between them)."""
        if len(token_ids) >= self.max_len:
            # Reject loudly: silently truncating a prompt answers a
            # question the caller never asked.
            raise ValueError(
                f'prompt is {len(token_ids)} tokens; engine max_len is '
                f'{self.max_len} (prompt + generation must fit)')
        request = _Request(token_ids, max_new_tokens, temperature,
                           eos_id, seed)
        self._requests_total += 1
        self._pending.put(request)
        self._wake.set()
        return request

    def generate_ids(self, token_ids: List[int], *,
                     max_new_tokens: int = 32,
                     temperature: float = 0.0,
                     eos_id: Optional[int] = None,
                     seed: int = 0,
                     timeout: float = 300.0) -> List[int]:
        request = self._submit(token_ids, max_new_tokens, temperature,
                               eos_id, seed)
        if not request.done.wait(timeout):
            raise TimeoutError('generation timed out')
        if request.error is not None:
            raise request.error
        generated = request.generated
        if eos_id is not None and eos_id in generated:
            generated = generated[:generated.index(eos_id)]
        return generated

    def generate_text(self, prompt: str, **kwargs: Any) -> str:
        ids = self.tokenizer.encode(prompt)
        out = self.generate_ids(ids, eos_id=self.tokenizer.eos_id,
                                **kwargs)
        return self.tokenizer.decode(out)

    def stream_ids(self, token_ids: List[int], *,
                   max_new_tokens: int = 32,
                   temperature: float = 0.0,
                   eos_id: Optional[int] = None,
                   seed: int = 0,
                   timeout: float = 300.0):
        """Yield generated token ids AS THEY LAND in the slot loop
        (the decode thread appends to request.generated; this iterator
        tails it) — the vLLM/JetStream streaming serving shape.

        Validation/admission happens EAGERLY (same as generate_ids: an
        over-long prompt raises here, not at first iteration)."""
        import time as time_lib
        request = self._submit(token_ids, max_new_tokens, temperature,
                               eos_id, seed)

        def tail():
            emitted = 0
            deadline = time_lib.time() + timeout
            while True:
                finished = request.done.is_set()
                generated = request.generated
                while emitted < len(generated):
                    token = generated[emitted]
                    emitted += 1
                    if eos_id is not None and token == eos_id:
                        return
                    yield token
                if finished:
                    if request.error is not None:
                        raise request.error
                    return
                if time_lib.time() > deadline:
                    raise TimeoutError('generation timed out')
                time_lib.sleep(0.005)

        return tail()

    def stream_text(self, prompt: str, **kwargs: Any):
        """Yield text DELTAS: ids decode cumulatively (single BPE
        tokens may be partial UTF-8; the running decode keeps deltas
        well-formed)."""
        ids = self.tokenizer.encode(prompt)
        out_ids: List[int] = []
        text_so_far = ''
        for token in self.stream_ids(ids, eos_id=self.tokenizer.eos_id,
                                     **kwargs):
            out_ids.append(token)
            text = self.tokenizer.decode(out_ids)
            delta, text_so_far = text[len(text_so_far):], text
            if delta:
                yield delta

    def generate_texts(self, prompts: List[str],
                       **kwargs: Any) -> List[str]:
        """Concurrent multi-prompt entry (the HTTP payload's batch API):
        each prompt is its own slot request, so they genuinely overlap."""
        import concurrent.futures
        # Bounded pool: a huge prompt list must not fan out into
        # thousands of OS threads — beyond ~2x the slot count extra
        # callers would only queue anyway.
        workers = max(1, min(len(prompts), 2 * self.max_slots))
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=workers) as pool:
            futures = [pool.submit(self.generate_text, p, **kwargs)
                       for p in prompts]
            return [f.result() for f in futures]

    def stats(self) -> Dict[str, float]:
        return {
            'slots': self.max_slots,
            'active': sum(r is not None for r in self._slots),
            'pending': self._pending.qsize(),
            # Monotonic counters (Prometheus counter type on /metrics).
            'requests': self._requests_total,
            'tokens_generated': self._tokens_total,
            'decode_seconds': round(self._decode_seconds_total, 4),
        }

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)
