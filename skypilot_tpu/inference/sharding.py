"""Multi-chip (tensor-parallel) serving: shard engine params over a mesh.

An 8B model in bf16 (~16 GB) does not fit one v5e chip — serving it
needs the slice, the way the reference's engines do tensor parallelism
(vLLM/sglang ``tensor_parallel_size``, SURVEY §2.9 TP row). Here the
engines reuse the training stack's logical-axis rules: heads/ffn/expert
dims shard over the ``tensor`` axis, everything else replicates, and
GSPMD propagates those shardings through the prefill/decode programs
(per-head attention partitions cleanly; activations stay sharded on the
head axis between the qkv and output projections).

Note: under a multi-device mesh the decode path uses the XLA attention
reference — the Pallas decode kernel is an opaque primitive to the
GSPMD partitioner and would force cache all-gathers until it is wrapped
in shard_map (future work; the kernel stays the single-chip fast path).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

import jax
from jax.sharding import Mesh

from skypilot_tpu.models.config import ModelConfig

Params = Dict[str, Any]


def build_inference_mesh(spec: Union[str, Mesh]) -> Mesh:
    """'tensor=4' / 'tensor=4,data=2'-style spec (or a ready Mesh).

    Unspecified axes stay at 1 and the mesh takes exactly the devices
    the spec multiplies out to — unlike training, leftover chips must
    NOT be absorbed into fsdp (weight-gathering per matmul is the wrong
    default for a latency-bound decode loop)."""
    if isinstance(spec, Mesh):
        return spec
    if not spec or not spec.strip():
        raise ValueError(
            "empty mesh spec: pass e.g. 'tensor=4' (or omit the mesh "
            'argument for single-device serving)')
    import math
    from skypilot_tpu.parallel.mesh import MeshConfig, build_mesh
    from skypilot_tpu.train.pretrain import parse_mesh
    axes = {'data': 1, 'stage': 1, 'fsdp': 1, 'seq': 1, 'expert': 1,
            'tensor': 1}
    parsed = parse_mesh(spec)
    unknown = set(parsed) - set(axes)
    if unknown:
        raise ValueError(
            f'unknown mesh axis {sorted(unknown)} in {spec!r}; valid '
            f'axes: {sorted(axes)}')
    axes.update(parsed)
    wildcards = [a for a, v in axes.items() if v == -1]
    if wildcards:  # 'tensor=-1': absorb every local chip
        if len(wildcards) > 1:
            raise ValueError(f'mesh {spec!r}: only one axis may be -1')
        fixed = math.prod(v for v in axes.values() if v != -1)
        axes[wildcards[0]] = max(len(jax.devices()) // fixed, 1)
    n = math.prod(axes.values())
    if n < 1 or n > len(jax.devices()):
        raise ValueError(
            f'mesh {spec!r} needs {n} devices, have {len(jax.devices())}')
    return build_mesh(MeshConfig(**axes), devices=jax.devices()[:n])


def shard_inference_params(params: Params, mesh: Mesh,
                           cfg: ModelConfig) -> Params:
    """Place params on the mesh under the model's logical-axes rules."""
    from skypilot_tpu.models import llama
    from skypilot_tpu.parallel.sharding import shard_params_pytree
    shardings = shard_params_pytree(mesh, llama.param_logical_axes(cfg))
    return jax.device_put(params, shardings)


def prepare_engine(params: Params, cfg: ModelConfig,
                   mesh: Optional[Union[str, Mesh]]):
    """(params, cfg) ready for the engine: sharded + XLA attention under
    a multi-device mesh, unchanged otherwise."""
    if mesh is None:
        return params, cfg
    mesh = build_inference_mesh(mesh)
    if mesh.size > 1:
        import dataclasses
        cfg = dataclasses.replace(cfg, attention_impl='xla')
    return shard_inference_params(params, mesh, cfg), cfg
