"""Multi-chip (tensor-parallel) serving: shard engine params over a mesh.

An 8B model in bf16 (~16 GB) does not fit one v5e chip — serving it
needs the slice, the way the reference's engines do tensor parallelism
(vLLM/sglang ``tensor_parallel_size``, SURVEY §2.9 TP row). Here the
engines reuse the training stack's logical-axis rules: heads/ffn/expert
dims shard over the ``tensor`` axis, everything else replicates, and
GSPMD propagates those shardings through the prefill/decode programs
(per-head attention partitions cleanly; activations stay sharded on the
head axis between the qkv and output projections).

Attention under a multi-device mesh: both phases keep their kernels by
shard_mapping over the tensor axis (heads are embarrassingly parallel)
— prefill splits the flash kernel per head shard
(``models/decode._prefill_attention``), decode splits the length-aware
cache kernel per kv-head shard. The engines enable this by wrapping
their compute calls in ``jax.sharding.set_mesh`` (see
``mesh_context``); non-dividing head counts fall back to the
GSPMD-partitionable XLA reference.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

import jax
from jax.sharding import Mesh

from skypilot_tpu.models.config import ModelConfig

Params = Dict[str, Any]


def build_inference_mesh(spec: Union[str, Mesh]) -> Mesh:
    """'tensor=4' / 'tensor=4,data=2'-style spec (or a ready Mesh).

    Unspecified axes stay at 1 and the mesh takes exactly the devices
    the spec multiplies out to — unlike training, leftover chips must
    NOT be absorbed into fsdp (weight-gathering per matmul is the wrong
    default for a latency-bound decode loop)."""
    if isinstance(spec, Mesh):
        return spec
    if not spec or not spec.strip():
        raise ValueError(
            "empty mesh spec: pass e.g. 'tensor=4' (or omit the mesh "
            'argument for single-device serving)')
    import math
    from skypilot_tpu.parallel.mesh import MeshConfig, build_mesh
    from skypilot_tpu.train.pretrain import parse_mesh
    axes = {'data': 1, 'stage': 1, 'fsdp': 1, 'seq': 1, 'expert': 1,
            'tensor': 1}
    parsed = parse_mesh(spec)
    unknown = set(parsed) - set(axes)
    if unknown:
        raise ValueError(
            f'unknown mesh axis {sorted(unknown)} in {spec!r}; valid '
            f'axes: {sorted(axes)}')
    axes.update(parsed)
    wildcards = [a for a, v in axes.items() if v == -1]
    if wildcards:  # 'tensor=-1': absorb every local chip
        if len(wildcards) > 1:
            raise ValueError(f'mesh {spec!r}: only one axis may be -1')
        fixed = math.prod(v for v in axes.values() if v != -1)
        axes[wildcards[0]] = max(len(jax.devices()) // fixed, 1)
    n = math.prod(axes.values())
    if n < 1 or n > len(jax.devices()):
        raise ValueError(
            f'mesh {spec!r} needs {n} devices, have {len(jax.devices())}')
    return build_mesh(MeshConfig(**axes), devices=jax.devices()[:n])


def shard_inference_params(params: Params, mesh: Mesh,
                           cfg: ModelConfig) -> Params:
    """Place params on the mesh under the model's logical-axes rules."""
    from skypilot_tpu.models import llama
    from skypilot_tpu.parallel.sharding import shard_params_pytree
    shardings = shard_params_pytree(mesh, llama.param_logical_axes(cfg))
    return jax.device_put(params, shardings)


def prepare_engine(params: Params, cfg: ModelConfig,
                   mesh: Optional[Union[str, Mesh]]):
    """(params, cfg, mesh) ready for the engine.

    Under a multi-device mesh: params shard, and decode attention
    defaults to 'auto' — both the prefill flash kernel and the decode
    cache kernel run per-head-shard via shard_map when the engine wraps
    its calls in ``jax.sharding.set_mesh(mesh)``."""
    if mesh is None:
        return params, cfg, None
    mesh = build_inference_mesh(mesh)
    if mesh.size > 1:
        import dataclasses
        # Both phases keep their kernels under TP: prefill shard_maps
        # flash over the head axis (models/decode.py
        # _prefill_attention), decode shard_maps the length-aware
        # kernel. An explicit user decode setting (e.g. 'xla' to rule
        # the kernel out while debugging) wins over the TP default.
        cfg = dataclasses.replace(
            cfg,
            decode_attention_impl=cfg.decode_attention_impl or 'auto')
    return shard_inference_params(params, mesh, cfg), cfg, mesh


def shard_paged_cache(cache, mesh: Optional[Mesh], cfg: ModelConfig):
    """Place a ``PagedKVCache`` pool on the mesh: k/v (and int8 scales)
    shard along the kv-head axis over ``tensor`` — the same axis the
    decode kernel shard_maps over — while block tables and lengths
    replicate. Gather/scatter by block index only touches the
    pool/position axes, so GSPMD keeps the head sharding through the
    jitted step. Non-dividing head counts replicate (the XLA fallback
    path partitions itself)."""
    if mesh is None or mesh.size == 1:
        return cache
    tp = dict(mesh.shape).get('tensor', 1)
    if tp <= 1 or cfg.n_kv_heads % tp:
        return cache
    import dataclasses
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    kv_spec = P(None, None, None, 'tensor', None)
    scale_spec = P(None, None, None, 'tensor')
    return dataclasses.replace(
        cache,
        k=put(cache.k, kv_spec), v=put(cache.v, kv_spec),
        lengths=put(cache.lengths, P()),
        block_tables=put(cache.block_tables, P()),
        k_scale=(put(cache.k_scale, scale_spec)
                 if cache.k_scale is not None else None),
        v_scale=(put(cache.v_scale, scale_spec)
                 if cache.v_scale is not None else None))


def mesh_context(mesh: Optional[Mesh]):
    """``set_mesh(mesh)`` (or a no-op) for wrapping engine compute calls.

    Puts the mesh in thread-local context so the decode path can see it
    (``get_abstract_mesh`` inside jit) and route the attention kernel
    through shard_map."""
    import contextlib
    if mesh is None:
        return contextlib.nullcontext()
    return jax.sharding.set_mesh(mesh)
