"""KV-block migration: prefill->decode handoff for disaggregated serving.

A prefill replica (``SKYT_DISAGG_ROLE=prefill``) finishes a prompt at
full-chip arithmetic intensity and parks the result here as a
:class:`KvExport` — the slot's KV blocks serialized per-block, keyed by
the same rolling chain digests the :class:`PrefixCache` uses
(``inference/paged.py`` ``chain_digests``). The decode replica pulls it
over a ranged, content-addressed HTTP surface modeled on the r17 weight
fan-out (``data/fanout.py``):

* **Delta manifests make shared-prefix migration nearly free.** The
  manifest carries one ``(chain digest, sha256, nbytes)`` row per full
  block; the decode side skips every block whose chain digest its own
  ``PrefixCache`` already holds (``outcome=resident``) — only
  non-resident blocks move. Because the decode engine increfs resident
  blocks through ``BlockImporter.begin`` BEFORE the pull starts, they
  cannot be evicted mid-migration.
* **Every payload is digest-verified, a corrupt block is re-pulled,
  never decoded.** sha256 over the wire bytes; mismatch discards the
  payload and restarts that block from offset 0
  (``outcome=corrupt_retry``), bounded by SKYT_KV_MIGRATE_RETRIES.
* **Transfers resume mid-block.** A fetch that dies mid-stream keeps
  its partial buffer; the retry sends ``Range: bytes=<got>-`` so only
  the remainder crosses the wire again.
* **The source's backpressure is honored.** A 429/503 with Retry-After
  floors the retry delay (the transfer-engine discipline), so a
  prefill replica shedding load shapes the pull rate instead of being
  hammered.

Chaos sites: ``infer.kv_migrate.push`` (the prefill side serving a
manifest/block: dies, sheds with Retry-After) and
``infer.kv_migrate.pull`` (the decode side's fetch: dies / hangs /
corrupt bytes). Failure matrix: docs/disaggregated_serving.md.
"""
from __future__ import annotations

import dataclasses
import hashlib
import http.server
import json
import threading
import time
import urllib.error
import urllib.request
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence, Tuple)

import numpy as np

from skypilot_tpu.utils import env_registry, fault_injection, log
from skypilot_tpu.utils import resilience

logger = log.init_logger(__name__)

PUSH_SITE = 'infer.kv_migrate.push'
PULL_SITE = 'infer.kv_migrate.pull'

_CHUNK = 256 * 1024
_SHA_HEADER = 'X-Skyt-Kv-Sha256'


class MigrationUnavailable(Exception):
    """Source dead / timed out / shedding — retryable; carries the
    server's Retry-After floor when it sent one."""

    def __init__(self, msg: str, retry_after: float = 0.0) -> None:
        super().__init__(msg)
        self.retry_after = retry_after


class BlockCorrupt(Exception):
    """A payload failed its digest after every re-pull attempt — the
    decode side falls back to a local re-prefill; the bytes are never
    written into the KV pool."""


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# -- payload packing (engine KV arrays <-> wire bytes) -----------------


def pack_arrays(arrays: Dict[str, np.ndarray]) -> bytes:
    """A name->array dict as one self-describing payload: a length-
    prefixed JSON header (name -> dtype, shape) + the raw bytes in
    sorted-name order. No pickle on the wire; non-standard dtypes
    (bfloat16) resolve through ml_dtypes on unpack."""
    names = sorted(arrays)
    header = json.dumps(
        {n: {'dtype': str(arrays[n].dtype),
             'shape': list(arrays[n].shape)} for n in names},
        sort_keys=True).encode()
    parts = [len(header).to_bytes(4, 'big'), header]
    for name in names:
        parts.append(np.ascontiguousarray(arrays[name]).tobytes())
    return b''.join(parts)


def unpack_arrays(data: bytes) -> Dict[str, np.ndarray]:
    hlen = int.from_bytes(data[:4], 'big')
    header = json.loads(data[4:4 + hlen])
    out: Dict[str, np.ndarray] = {}
    offset = 4 + hlen
    for name in sorted(header):
        spec = header[name]
        dtype = _np_dtype(spec['dtype'])
        count = 1
        for dim in spec['shape']:
            count *= int(dim)
        nbytes = dtype.itemsize * count
        out[name] = np.frombuffer(
            data[offset:offset + nbytes],
            dtype=dtype).reshape(spec['shape'])
        offset += nbytes
    if offset != len(data):
        raise ValueError(
            f'payload is {len(data)}B but header describes {offset}B')
    return out


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


# -- the export (prefill side) -----------------------------------------


@dataclasses.dataclass
class KvExport:
    """One finished prefill parked for migration. ``blocks`` holds the
    serialized payload of every FULL block (chain order, aligned with
    ``digests``); ``tail`` is the engine's opaque tail state (partial
    block KV + last-logits row + resume metadata), ``meta`` the
    JSON-safe scalars the decode engine needs to resume the stream
    deterministically (seed, lengths)."""
    request_id: str
    ids: List[int]
    block_size: int
    digests: List[int]
    blocks: List[bytes]
    tail: bytes
    meta: Dict[str, Any]
    created: float = 0.0

    def __post_init__(self) -> None:
        if len(self.digests) != len(self.blocks):
            raise ValueError(
                f'{len(self.digests)} digests for '
                f'{len(self.blocks)} block payloads')
        self.block_sha = [_sha256(b) for b in self.blocks]
        self.tail_sha = _sha256(self.tail)

    def manifest(self) -> Dict[str, Any]:
        """The delta manifest: everything the decode side needs to
        plan the pull — no payload bytes."""
        return {
            'request_id': self.request_id,
            'block_size': self.block_size,
            'n_tokens': len(self.ids),
            'blocks': [
                {'digest': d, 'sha256': s, 'nbytes': len(b)}
                for d, s, b in zip(self.digests, self.block_sha,
                                   self.blocks)],
            'tail': {'sha256': self.tail_sha, 'nbytes': len(self.tail)},
            'meta': self.meta,
        }


class KvExporter:
    """The prefill replica's parking lot: finished prefills awaiting
    their decode-side pull, keyed by request id. Thread-safe (the
    serving loop puts, the HTTP thread reads, the handoff ack pops)."""

    def __init__(self) -> None:
        self._exports: Dict[str, KvExport] = {}
        self._lock = threading.Lock()

    def put(self, export: KvExport) -> None:
        with self._lock:
            self._exports[export.request_id] = export

    def get(self, request_id: str) -> KvExport:
        with self._lock:
            export = self._exports.get(request_id)
        if export is None:
            raise KeyError(request_id)
        return export

    def pop(self, request_id: str) -> Optional[KvExport]:
        """Release a completed (or abandoned) export. Idempotent."""
        with self._lock:
            return self._exports.pop(request_id, None)

    def request_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._exports)

    def __len__(self) -> int:
        with self._lock:
            return len(self._exports)


# -- the HTTP surface (mounted by the prefill replica) -----------------


def handle_kv_get(path: str, exporter: KvExporter,
                  range_header: Optional[str] = None
                  ) -> Tuple[int, Dict[str, str], bytes]:
    """Shared GET handler for the prefill side's migration surface:

    * ``/kv/manifest/<request_id>`` — the delta manifest (JSON);
    * ``/kv/block/<request_id>/<digest>`` — one block's payload bytes
      (Range-resumable, sha256 in ``X-Skyt-Kv-Sha256``);
    * ``/kv/tail/<request_id>`` — the opaque tail payload.

    Returns ``(status, headers, body)``; mounted by the payload server
    and :class:`KvServer`. An injected push fault surfaces as a 503
    with ``Retry-After`` — the realistic shape of a prefill replica
    shedding load — so chaos drills exercise the puller's
    backpressure floor, not a synthetic stack trace."""
    from skypilot_tpu.server import metrics
    try:
        fault_injection.inject(PUSH_SITE)
    except Exception as e:  # noqa: BLE001 — any injected fault sheds
        return (503, {'Retry-After': '0'},
                json.dumps({'error': f'shedding: {e}'}).encode())
    parts = path.strip('/').split('/')
    if len(parts) < 3 or parts[0] != 'kv':
        return 404, {}, b'{"error": "not found"}'
    kind, request_id = parts[1], parts[2]
    try:
        export = exporter.get(request_id)
    except KeyError:
        return 404, {}, b'{"error": "unknown request"}'
    if kind == 'manifest' and len(parts) == 3:
        body = json.dumps(export.manifest(), sort_keys=True).encode()
        return 200, {'Content-Type': 'application/json'}, body
    if kind == 'tail' and len(parts) == 3:
        payload, sha = export.tail, export.tail_sha
    elif kind == 'block' and len(parts) == 4:
        try:
            index = export.digests.index(int(parts[3]))
        except ValueError:
            return 404, {}, b'{"error": "unknown block digest"}'
        payload, sha = export.blocks[index], export.block_sha[index]
    else:
        return 404, {}, b'{"error": "not found"}'
    size = len(payload)
    offset = _parse_range(range_header)
    if offset > size:
        offset = 0
    body = payload[offset:]
    metrics.KV_MIGRATE_BYTES.inc(len(body), direction='push')
    headers = {'Content-Type': 'application/octet-stream',
               _SHA_HEADER: sha}
    if offset:
        headers['Content-Range'] = f'bytes {offset}-{size - 1}/{size}'
        return 206, headers, body
    return 200, headers, body


def handle_kv_release(path: str, exporter: KvExporter
                      ) -> Tuple[int, Dict[str, str], bytes]:
    """POST ``/kv/release/<request_id>`` — the decode side committed
    its import; the prefill side frees the parked export (and, in the
    engine, the slot's blocks). Idempotent: releasing an unknown id is
    200 (the pull may race a prefill-side timeout sweep)."""
    parts = path.strip('/').split('/')
    if len(parts) != 3 or parts[:2] != ['kv', 'release']:
        return 404, {}, b'{"error": "not found"}'
    exporter.pop(parts[2])
    return 200, {'Content-Type': 'application/json'}, b'{"ok": true}'


def _parse_range(header: Optional[str]) -> int:
    """Start offset of a ``bytes=N-`` header (the only form pullers
    send); anything else reads as 0 — the puller's digest check still
    holds."""
    if not header or not header.startswith('bytes='):
        return 0
    spec = header[len('bytes='):].split(',')[0].strip()
    try:
        return max(0, int(spec.split('-')[0]))
    except ValueError:
        return 0


class KvServer:
    """Standalone migration HTTP server over one exporter — what tests
    and benches stand up in place of a full prefill replica (the real
    replica mounts the same handlers on its inference server)."""

    def __init__(self, exporter: KvExporter) -> None:
        self.exporter = exporter
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _reply(self, status, headers, body):
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib casing)
                self._reply(*handle_kv_get(
                    self.path, outer.exporter, self.headers.get('Range')))

            def do_POST(self):  # noqa: N802 (stdlib casing)
                self._reply(*handle_kv_release(self.path, outer.exporter))

            def log_message(self, *args):  # noqa: D102
                pass

        self._server = http.server.ThreadingHTTPServer(
            ('127.0.0.1', 0), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    @property
    def endpoint(self) -> str:
        host, port = self._server.server_address[:2]
        return f'http://{host}:{port}'

    def __enter__(self) -> 'KvServer':
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


# -- pull sources (decode side) ----------------------------------------


class HTTPKvSource:
    """Fetches manifests and payloads from a prefill replica's ``/kv``
    surface. Connection errors and timeouts surface as
    :class:`MigrationUnavailable`; 429/503 additionally carry the
    server's Retry-After floor."""

    def __init__(self, endpoint: str,
                 timeout: Optional[float] = None) -> None:
        self.endpoint = endpoint.rstrip('/')
        if timeout is None:
            timeout = env_registry.get_float('SKYT_KV_MIGRATE_TIMEOUT')
        self.timeout = timeout
        self.name = f'kv:{self.endpoint}'

    def fetch_manifest(self, request_id: str) -> Dict[str, Any]:
        body = b''.join(self._get(f'/kv/manifest/{request_id}', 0))
        try:
            return json.loads(body)
        except ValueError as e:
            raise MigrationUnavailable(
                f'{self.name}: bad manifest: {e}') from None

    def fetch_block(self, request_id: str, digest: int,
                    offset: int) -> Iterator[bytes]:
        return self._get(f'/kv/block/{request_id}/{digest}', offset)

    def fetch_tail(self, request_id: str,
                   offset: int) -> Iterator[bytes]:
        return self._get(f'/kv/tail/{request_id}', offset)

    def release(self, request_id: str) -> None:
        """Best-effort handoff ack — the prefill side also sweeps
        abandoned exports, so a lost ack leaks nothing permanent."""
        req = urllib.request.Request(
            f'{self.endpoint}/kv/release/{request_id}', data=b'',
            method='POST')
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass
        except (urllib.error.URLError, TimeoutError, OSError,
                ConnectionError):
            pass

    def _get(self, path: str, offset: int) -> Iterator[bytes]:
        fault_injection.inject(PULL_SITE)
        req = urllib.request.Request(self.endpoint + path)
        if offset:
            req.add_header('Range', f'bytes={offset}-')
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                if resp.status not in (200, 206):
                    raise MigrationUnavailable(
                        f'{self.name}: HTTP {resp.status}')
                if resp.status == 200 and offset:
                    # Source ignored Range: discard the prefix so the
                    # resume offset stays truthful.
                    resp.read(offset)
                while True:
                    chunk = resp.read(_CHUNK)
                    if not chunk:
                        return
                    yield chunk
        except urllib.error.HTTPError as e:
            raise MigrationUnavailable(
                f'{self.name}: HTTP {e.code}',
                retry_after=_retry_after(e)) from None
        except (urllib.error.URLError, TimeoutError, OSError,
                ConnectionError) as e:
            raise MigrationUnavailable(f'{self.name}: {e}') from None


def _retry_after(error: urllib.error.HTTPError) -> float:
    if error.code not in (429, 503):
        return 0.0
    value = (error.headers.get('Retry-After') or '').strip()
    try:
        return max(0.0, float(value))
    except ValueError:
        return 0.0


class LocalKvSource:
    """Test/bench seam: serves straight from an in-process exporter.
    ``mutate(kind, key, data) -> bytes`` models a corrupt source
    (kind is ``'block'``/``'tail'``, key the digest or request id)."""

    def __init__(self, exporter: KvExporter,
                 mutate: Optional[Callable[[str, Any, bytes],
                                           bytes]] = None) -> None:
        self._exporter = exporter
        self._mutate = mutate
        self.name = 'kv:local'

    def _lookup(self, request_id: str) -> KvExport:
        try:
            return self._exporter.get(request_id)
        except KeyError:
            raise MigrationUnavailable(
                f'{self.name}: unknown request {request_id}') from None

    def fetch_manifest(self, request_id: str) -> Dict[str, Any]:
        fault_injection.inject(PULL_SITE)
        return self._lookup(request_id).manifest()

    def fetch_block(self, request_id: str, digest: int,
                    offset: int) -> Iterator[bytes]:
        fault_injection.inject(PULL_SITE)
        export = self._lookup(request_id)
        try:
            data = export.blocks[export.digests.index(digest)]
        except ValueError:
            raise MigrationUnavailable(
                f'{self.name}: unknown block {digest}') from None
        if self._mutate is not None:
            data = self._mutate('block', digest, data)
        yield from _chunks(data[offset:])

    def fetch_tail(self, request_id: str,
                   offset: int) -> Iterator[bytes]:
        fault_injection.inject(PULL_SITE)
        data = self._lookup(request_id).tail
        if self._mutate is not None:
            data = self._mutate('tail', request_id, data)
        yield from _chunks(data[offset:])

    def release(self, request_id: str) -> None:
        self._exporter.pop(request_id)


def _chunks(data: bytes) -> Iterator[bytes]:
    for i in range(0, len(data), _CHUNK):
        yield data[i:i + _CHUNK]


# -- the puller (decode side) ------------------------------------------


@dataclasses.dataclass
class PulledKv:
    """A verified migration: ``payloads`` aligns with
    ``manifest['blocks']`` — ``None`` where the block was resident on
    the decode side (nothing moved; the importer's prefix-cache hit
    already owns it)."""
    manifest: Dict[str, Any]
    payloads: List[Optional[bytes]]
    tail: bytes

    @property
    def moved(self) -> int:
        return sum(1 for p in self.payloads if p is not None)

    @property
    def resident(self) -> int:
        return sum(1 for p in self.payloads if p is None)


class KvPuller:
    """Pulls one export from a source, skipping blocks already
    resident on the decode side, verifying every payload, and
    honoring the source's backpressure. Raises
    :class:`MigrationUnavailable` when the source stays dead past the
    retry budget and :class:`BlockCorrupt` when a payload never
    passes its digest — both mapped to the re-prefill fallback by the
    decode engine, with the import transaction rolled back."""

    def __init__(self, source: Any, *, retries: Optional[int] = None,
                 sleep: Optional[Callable[[float], None]] = None) -> None:
        if retries is None:
            retries = env_registry.get_int('SKYT_KV_MIGRATE_RETRIES')
        if sleep is None:
            sleep = time.sleep
        self._source = source
        self._retries = max(0, int(retries))
        self._sleep = sleep
        # Observability for tests/benches.
        self.corrupt_retries = 0
        self.unavailable_retries = 0

    def pull(self, request_id: str,
             resident_digests: Sequence[int] = ()) -> PulledKv:
        """Fetch the manifest, then every non-resident payload.
        ``resident_digests`` is the chain-digest prefix the decode
        engine matched (and increfed) in its own PrefixCache."""
        from skypilot_tpu.server import metrics
        manifest = self._retrying(
            f'manifest:{request_id}',
            lambda: self._source.fetch_manifest(request_id))
        resident = set(resident_digests)
        payloads: List[Optional[bytes]] = []
        for row in manifest['blocks']:
            if row['digest'] in resident:
                metrics.KV_MIGRATE_BLOCKS.inc(outcome='resident')
                payloads.append(None)
                continue
            payloads.append(self._pull_payload(
                f'block:{row["digest"]}', row['sha256'], row['nbytes'],
                lambda offset, d=row['digest']: self._source.fetch_block(
                    request_id, d, offset)))
            metrics.KV_MIGRATE_BLOCKS.inc(outcome='moved')
        tail = self._pull_payload(
            'tail', manifest['tail']['sha256'],
            manifest['tail']['nbytes'],
            lambda offset: self._source.fetch_tail(request_id, offset))
        return PulledKv(manifest=manifest, payloads=payloads, tail=tail)

    # -- internals -----------------------------------------------------

    def _retrying(self, what: str, fn: Callable[[], Any]) -> Any:
        delays = resilience.backoff_delays(base=0.05, cap=2.0)
        attempts = 0
        while True:
            try:
                return fn()
            except (MigrationUnavailable, TimeoutError,
                    ConnectionError, OSError) as e:
                attempts += 1
                self.unavailable_retries += 1
                if attempts > self._retries:
                    raise
                delay = max(next(delays),
                            getattr(e, 'retry_after', 0.0))
                logger.warning(
                    'kv_migrate: %s unavailable (%s); retry %d/%d '
                    'in %.2fs', what, e, attempts, self._retries, delay)
                self._sleep(delay)

    def _pull_payload(self, what: str, sha256: str, nbytes: int,
                      fetch: Callable[[int], Iterator[bytes]]) -> bytes:
        """One payload, digest-verified: mid-stream death resumes at
        the byte reached; a digest mismatch discards everything (the
        partial prefix could be the corrupt part) and re-pulls from
        offset 0. A corrupt payload is never returned."""
        from skypilot_tpu.server import metrics
        delays = resilience.backoff_delays(base=0.05, cap=2.0)
        attempts = 0
        buf = b''
        while True:
            try:
                for chunk in fetch(len(buf)):
                    buf += chunk
            except (MigrationUnavailable, TimeoutError,
                    ConnectionError, OSError) as e:
                attempts += 1
                self.unavailable_retries += 1
                if attempts > self._retries:
                    raise
                delay = max(next(delays),
                            getattr(e, 'retry_after', 0.0))
                logger.warning(
                    'kv_migrate: %s unavailable (%s); retry %d/%d '
                    'in %.2fs', what, e, attempts, self._retries, delay)
                self._sleep(delay)
                continue
            if len(buf) == nbytes and _sha256(buf) == sha256:
                metrics.KV_MIGRATE_BYTES.inc(len(buf), direction='pull')
                return buf
            attempts += 1
            self.corrupt_retries += 1
            metrics.KV_MIGRATE_BLOCKS.inc(outcome='corrupt_retry')
            if attempts > self._retries:
                raise BlockCorrupt(
                    f'{what}: got {_sha256(buf)[:12]}/{len(buf)}B, '
                    f'want {sha256[:12]}/{nbytes}B after '
                    f'{attempts} attempt(s)')
            logger.warning(
                'kv_migrate: %s failed digest; re-pulling from 0 '
                '(%d/%d)', what, attempts, self._retries)
            buf = b''
            self._sleep(next(delays))
