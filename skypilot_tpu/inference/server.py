"""Inference HTTP server: the in-tree serving payload.

Runs behind the serve stack (``serve/``): the replica manager launches
this per replica, the readiness probe hits /health, the load balancer
proxies /generate. stdlib HTTP (threaded) -- the data plane is the TPU
decode scan, not the web layer.

    python -m skypilot_tpu.inference.server --model tiny --port 8080

Endpoints:
    GET  /health               -> 200 {"status": "ok", "model": ...}
    GET  /stats                -> decode throughput counters (JSON)
    GET  /metrics              -> the same counters, Prometheus text
    POST /generate             -> {"prompts": [...], "max_new_tokens":
                                   N, "temperature": t}
                                  -> {"outputs": [...]}
    POST /v1/completions       -> OpenAI-compatible (incl. SSE
    POST /v1/chat/completions     streaming with the continuous
                                  engine) — point an OpenAI client's
                                  base_url here; the serve stack's
                                  load balancer forwards these too.

Parity: the JetStream/vLLM serving payloads of the reference
(``examples/tpu/v6e/benchmark-llama2-7b.yaml``, ``llm/vllm`` — whose
clients speak exactly this OpenAI surface).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from skypilot_tpu.inference.engine import InferenceEngine
from skypilot_tpu.server import metrics as server_metrics
from skypilot_tpu.utils import log

logger = log.init_logger(__name__)


def make_handler(engine: InferenceEngine):

    class Handler(BaseHTTPRequestHandler):
        protocol_version = 'HTTP/1.1'

        def log_message(self, fmt, *args):  # quiet
            logger.debug(fmt, *args)

        def _json(self, code: int, payload) -> None:
            self._body(code, json.dumps(payload).encode('utf-8'),
                       'application/json')

        def _body(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header('Content-Type', ctype)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _stats(self):
            stats = engine.stats
            return stats() if callable(stats) else stats

        # Monotonic counters vs point-in-time gauges (Prometheus type
        # correctness: rate() over a gauge breaks scrapers/linters).
        # The split is declared ONCE, next to the static metric
        # registry (server/metrics.py) where skylint SKYT003 audits
        # it; slots/active/pending and the paged-pool block_*
        # occupancy stats stay gauges.
        _COUNTERS = server_metrics.INFERENCE_COUNTER_STATS

        def _adapter_kwargs(self, req):
            """Per-tenant LoRA adapter selection: JSON ``adapter``
            field, or the ``X-Skyt-Adapter`` header the serve LB's
            affinity routing stamps. Continuous engines only; unknown
            adapters are rejected by the engine with a clean error."""
            adapter = (req.get('adapter') or
                       self.headers.get('X-Skyt-Adapter') or '')
            if adapter and hasattr(engine, 'register_adapter'):
                return {'adapter': str(adapter)}
            return {}

        def _trace_kwargs(self):
            """Incoming traceparent (forwarded by the serve LB) ->
            engine trace_ctx kwarg, so queue-wait/prefill/decode spans
            join the caller's distributed trace. Continuous engine
            only — the batch engine has no per-request lifecycle."""
            from skypilot_tpu.utils import tracing
            if not tracing.armed() or not hasattr(engine, 'stream_ids'):
                return {}
            ctx = tracing.parse_traceparent(
                self.headers.get(tracing.TRACEPARENT_HEADER))
            return {'trace_ctx': ctx} if ctx is not None else {}

        def do_GET(self):
            if self.path == '/health':
                self._json(200, {'status': 'ok',
                                 'model': engine.cfg.name})
            elif self.path == '/stats':
                self._json(200, self._stats())
            elif self.path == '/adapters':
                # Per-adapter demand/residency (skyt serve status and
                # the controller's working-set tracking).
                stats_fn = getattr(engine, 'adapter_stats', None)
                self._json(200, stats_fn() if stats_fn else {})
            elif self.path == '/metrics':
                # Prometheus text format for external scrapers
                # (parity: vLLM's /metrics; the serve stack's
                # autoscalers use the load balancer's LoadStats, not
                # this endpoint).
                lines = []
                for key, value in sorted(self._stats().items()):
                    if isinstance(value, (int, float)):
                        kind = ('counter' if key in self._COUNTERS
                                else 'gauge')
                        if kind == 'gauge' and key.endswith('_total'):
                            # A gauge family must not end _total
                            # (scrapers rate() it): blocks_total is
                            # the pool CAPACITY, expose it as such.
                            key = key[:-len('_total')] + '_capacity'
                        name = f'skyt_inference_{key}'
                        if kind == 'counter':
                            name += '_total'
                        lines.append(f'# TYPE {name} {kind}')
                        lines.append(f'{name} {value}')
                self._body(200, ('\n'.join(lines) + '\n').encode(),
                           'text/plain; version=0.0.4')
            elif self.path.startswith('/kv/'):
                # KV-block migration surface (prefill role only): the
                # decode fleet pulls manifests/blocks/tails from here
                # (inference/kv_migrate.py).
                from skypilot_tpu.inference import kv_migrate
                exporter = getattr(engine, 'exporter', None)
                if exporter is None:
                    self._json(404, {'error': 'not a prefill replica'})
                    return
                status, headers, body = kv_migrate.handle_kv_get(
                    self.path, exporter,
                    range_header=self.headers.get('Range'))
                self.send_response(status)
                for key, value in headers.items():
                    self.send_header(key, value)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith('/fanout/'):
                # Peer weight-serving surface: sibling replicas pull
                # committed checkpoint shards from here instead of
                # the bucket (data/fanout.py; the weights dir comes
                # from SKYT_FANOUT_DIR).
                from skypilot_tpu.data import fanout
                status, headers, body = fanout.handle_peer_get(
                    self.path, range_header=self.headers.get('Range'))
                ctype = headers.pop('Content-Type',
                                    'application/json')
                self.send_response(status)
                for key, value in headers.items():
                    self.send_header(key, value)
                self.send_header('Content-Type', ctype)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {'error': 'not found'})

        def do_POST(self):
            try:
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length) or b'{}')
                if self.path == '/generate':
                    self._generate(req)
                elif self.path == '/v1/completions':
                    self._openai(req, chat=False)
                elif self.path == '/v1/chat/completions':
                    self._openai(req, chat=True)
                elif self.path == '/disagg/prefill':
                    self._disagg_prefill(req)
                elif self.path.startswith('/kv/release/'):
                    from skypilot_tpu.inference import kv_migrate
                    exporter = getattr(engine, 'exporter', None)
                    if exporter is None:
                        self._json(404,
                                   {'error': 'not a prefill replica'})
                        return
                    status, _headers, body = kv_migrate.handle_kv_release(
                        self.path, exporter)
                    self._body(status, body, 'application/json')
                else:
                    self._json(404, {'error': 'not found'})
            except Exception as e:  # pylint: disable=broad-except
                logger.error('generate failed: %s', e, exc_info=True)
                try:
                    self._json(500, {'error': str(e)})
                except (BrokenPipeError, ConnectionResetError):
                    pass

        # -- disaggregated serving (docs/disaggregated_serving.md) -----

        def _prompt_ids(self, path, req):
            """Token ids for the request's single prompt, derived the
            SAME way on the prefill and decode replicas (both run this
            exact code over the same body) — the import cross-checks
            chain digests, so any divergence falls back to a local
            re-prefill instead of decoding wrong KV. None for shapes
            the two-hop route doesn't carry (multi-prompt batches)."""
            tok = engine.tokenizer
            if path == '/v1/chat/completions':
                prompt = tok.apply_chat_template(req.get('messages') or [])
                add_bos = not getattr(tok, 'chat_template', None)
            elif path == '/v1/completions':
                prompt = req.get('prompt', '')
                if isinstance(prompt, list):
                    prompt = prompt[0] if prompt else ''
                add_bos = True
            else:  # /generate
                prompts = req.get('prompts') or [req.get('prompt', '')]
                if len(prompts) != 1:
                    return None
                prompt = prompts[0]
                add_bos = True
            return tok.encode(prompt, add_bos=add_bos)

        def _disagg_prefill(self, req):
            """Hop 1 of the LB's two-hop route: absorb the prompt and
            park the serialized KV for the decode fleet's pull. The
            body is the CLIENT's body verbatim; X-Skyt-Disagg-Path
            says which API shape to parse it as."""
            if getattr(engine, 'role', '') != 'prefill':
                self._json(400, {'error': 'not a prefill replica'})
                return
            path = self.headers.get('X-Skyt-Disagg-Path', '/generate')
            ids = self._prompt_ids(path, req)
            if ids is None:
                self._json(400, {'error': 'not a single-prompt request'})
                return
            request_id = engine.prefill_and_export(
                ids, temperature=float(req.get('temperature') or 0.0),
                seed=int(req.get('seed') or 0), **self._trace_kwargs())
            self._json(200, {'request_id': request_id,
                             'n_tokens': len(ids)})

        def _migrated_request(self, ids, kwargs):
            """When the LB's prefill hop stamped this request with a KV
            export (X-Skyt-Kv-* headers), pull the delta and enter
            decode directly; None -> caller prefills locally (the
            re-prefill fallback: a dead prefill replica or failed pull
            costs latency, never the request)."""
            request_id = self.headers.get('X-Skyt-Kv-Request-Id')
            endpoint = self.headers.get('X-Skyt-Kv-Endpoint')
            if (not request_id or not endpoint or
                    not hasattr(engine, 'submit_migrated') or
                    getattr(engine, 'role', '') == 'prefill'):
                return None
            from skypilot_tpu.inference import kv_migrate
            handoff_start = time.monotonic()
            try:
                source = kv_migrate.HTTPKvSource(endpoint)
                puller = kv_migrate.KvPuller(source)
                pulled = puller.pull(
                    request_id,
                    resident_digests=engine.probe_resident(ids))
                request = engine.submit_migrated(
                    ids, pulled, handoff_start=handoff_start, **kwargs)
                source.release(request_id)
                return request
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(
                    'KV pull for %s failed (%s: %s); falling back to '
                    'local prefill', request_id, type(e).__name__, e)
                return None

        def _generate(self, req):
            prompts = req.get('prompts') or [req.get('prompt', '')]
            kwargs = dict(
                max_new_tokens=int(req.get('max_new_tokens', 32)),
                temperature=float(req.get('temperature', 0.0)),
                seed=int(req.get('seed', 0)))
            if hasattr(engine, 'generate_texts'):
                kwargs.update(self._trace_kwargs())
                kwargs.update(self._adapter_kwargs(req))
                tok = engine.tokenizer
                ids = self._prompt_ids('/generate', req)
                migrated = (self._migrated_request(
                    ids, dict(eos_id=tok.eos_id, **kwargs))
                    if ids is not None else None)
                if migrated is not None:
                    out_ids = list(engine.tail_tokens(
                        migrated, eos_id=tok.eos_id))
                    self._json(200, {'outputs': [tok.decode(out_ids)]})
                    return
                outputs = engine.generate_texts(prompts, **kwargs)
            else:
                outputs = engine.generate_text(prompts, **kwargs)
            self._json(200, {'outputs': outputs})

        # -- OpenAI-compatible surface (parity: the reference serves
        # vLLM, whose clients speak this API; point an OpenAI client's
        # base_url here and it works, streaming included) -------------

        def _openai(self, req, chat: bool):
            tok = engine.tokenizer
            # Templated chat prompts render their own BOS — encoding
            # must not prepend a second one.
            add_bos = True
            if chat:
                messages = req.get('messages') or []
                # The checkpoint's own chat template (jinja in
                # tokenizer_config.json) — what the model was actually
                # tuned on; plain transcript otherwise.
                prompt = tok.apply_chat_template(messages)
                add_bos = not getattr(tok, 'chat_template', None)
            else:
                prompt = req.get('prompt', '')
                if isinstance(prompt, list):
                    prompt = prompt[0] if prompt else ''
            # `null` is how OpenAI clients spell "default" — never
            # float(None)-crash on it.
            max_tokens = int(req.get('max_tokens') or 64)
            kwargs = dict(
                max_new_tokens=max_tokens,
                temperature=float(req.get('temperature') or 0.0))
            kwargs.update(self._trace_kwargs())
            kwargs.update(self._adapter_kwargs(req))
            rid = f'cmpl-{os.urandom(8).hex()}'
            model = engine.cfg.name
            if req.get('stream'):
                if not hasattr(engine, 'stream_ids'):
                    # A silent JSON body would break SSE-expecting
                    # clients: refuse clearly instead.
                    self._json(400, {
                        'error': 'stream=true requires the continuous '
                                 'engine (--engine continuous)'})
                    return
                self._openai_stream(rid, model, prompt, chat, kwargs,
                                    add_bos=add_bos)
                return
            ids = tok.encode(prompt, add_bos=add_bos)
            if hasattr(engine, 'generate_texts'):
                # continuous engine: single-request ids API
                migrated = self._migrated_request(
                    ids, dict(eos_id=tok.eos_id, **kwargs))
                if migrated is not None:
                    out_ids = list(engine.tail_tokens(
                        migrated, eos_id=tok.eos_id))
                else:
                    out_ids = engine.generate_ids(
                        ids, eos_id=tok.eos_id, **kwargs)
            else:
                # batch engine: list-in, list-out
                out_ids = engine.generate_ids([ids], **kwargs)[0]
                if tok.eos_id in out_ids:
                    out_ids = out_ids[:out_ids.index(tok.eos_id)]
            text = tok.decode(out_ids)
            finish = ('length' if len(out_ids) >= max_tokens
                      else 'stop')
            if chat:
                choice = {'index': 0, 'finish_reason': finish,
                          'message': {'role': 'assistant',
                                      'content': text}}
                obj = 'chat.completion'
            else:
                choice = {'index': 0, 'finish_reason': finish,
                          'text': text}
                obj = 'text_completion'
            self._json(200, {'id': rid, 'object': obj, 'model': model,
                             'created': int(time.time()),
                             'choices': [choice]})

        def _openai_stream(self, rid, model, prompt, chat, kwargs,
                           add_bos: bool = True):
            # Everything that can fail with a clean 500 must happen
            # BEFORE the 200 + chunked headers go out (after that, a
            # second status line would corrupt the stream).
            tok = engine.tokenizer
            ids = tok.encode(prompt, add_bos=add_bos)
            migrated = self._migrated_request(
                ids, dict(eos_id=tok.eos_id, **kwargs))
            if migrated is not None:
                # First decode tokens stream the moment the migration
                # lands — the handoff is the TTFT, not a re-prefill.
                token_iter = engine.tail_tokens(migrated,
                                                eos_id=tok.eos_id)
            else:
                token_iter = engine.stream_ids(ids, eos_id=tok.eos_id,
                                               **kwargs)
            self.send_response(200)
            self.send_header('Content-Type', 'text/event-stream')
            self.send_header('Cache-Control', 'no-cache')
            self.send_header('Transfer-Encoding', 'chunked')
            self.end_headers()

            def send(obj_bytes: bytes) -> None:
                frame = b'data: ' + obj_bytes + b'\n\n'
                self.wfile.write(f'{len(frame):x}\r\n'.encode() +
                                 frame + b'\r\n')
                self.wfile.flush()

            created = int(time.time())
            obj = 'chat.completion.chunk' if chat else 'text_completion'

            def chunk(choice) -> bytes:
                return json.dumps({'id': rid, 'object': obj,
                                   'model': model, 'created': created,
                                   'choices': [choice]}).encode()

            try:
                out_ids, text_so_far = [], ''
                try:
                    for token in token_iter:
                        out_ids.append(token)
                        text = tok.decode(out_ids)
                        delta = text[len(text_so_far):]
                        text_so_far = text
                        if not delta:
                            continue
                        if chat:
                            choice = {'index': 0, 'finish_reason': None,
                                      'delta': {'content': delta}}
                        else:
                            choice = {'index': 0, 'finish_reason': None,
                                      'text': delta}
                        send(chunk(choice))
                    finish = ('length'
                              if len(out_ids) >=
                              kwargs['max_new_tokens'] else 'stop')
                except Exception as e:  # pylint: disable=broad-except
                    # Mid-stream failure: the status line is gone; the
                    # honest move is an error frame + clean termination.
                    logger.error('stream failed: %s', e, exc_info=True)
                    send(json.dumps({'error': str(e)}).encode())
                    finish = None
                if finish is not None:
                    final = ({'index': 0, 'finish_reason': finish,
                              'delta': {}} if chat else
                             {'index': 0, 'finish_reason': finish,
                              'text': ''})
                    send(chunk(final))
                send(b'[DONE]')
                self.wfile.write(b'0\r\n\r\n')
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass  # client went away mid-stream

    return Handler


def watch_policy_store(engine, store_root: str,
                       poll_s: float = None) -> 'threading.Thread':
    """Serve an RL pipeline's policy store live (continuous engine):
    pull the committed policy synchronously before the first request —
    on an empty local copy the manifest diff IS the full weight tree —
    then poll the store and refresh the engine in place with each
    newer version's shard delta (``docs/rl_pipeline.md``).  The eval
    fleet follows the learner with the same staggered, step-boundary
    swaps the rollout fleet uses; a mid-pull manifest race is retried
    on the next poll."""
    import tempfile
    import threading

    from skypilot_tpu.jobs.rl_pipeline import PolicyStore
    from skypilot_tpu.utils import env_registry

    if poll_s is None:
        poll_s = env_registry.get_float('SKYT_RL_EVAL_POLL_S',
                                        minimum=0.1)
    store = PolicyStore(store_root)
    dest = tempfile.mkdtemp(prefix='skyt-eval-policy-')
    served = [-1]

    def pull_once() -> bool:
        if store.version() in (None, served[0]):
            return False
        res = store.pull(dest)
        if res is None or res['version'] == served[0]:
            return False
        if res['updates']:
            engine.refresh_weights(updates=res['updates'],
                                   version=res['version'],
                                   mode='step')
        served[0] = res['version']
        logger.info('policy store %s: serving version %d '
                    '(%d shards, %d bytes pulled)', store_root,
                    res['version'], res['shards_pulled'],
                    res['bytes_pulled'])
        return True

    pull_once()  # blocking: never serve the random-init weights

    def loop():
        while True:
            time.sleep(poll_s)
            try:
                pull_once()
            except Exception as exc:  # mid-publish race: retry
                logger.warning('policy store poll failed: %s', exc)

    thread = threading.Thread(target=loop, name='policy-store-watch',
                              daemon=True)
    thread.start()
    return thread


def serve(engine: InferenceEngine, host: str, port: int):
    server = ThreadingHTTPServer((host, port), make_handler(engine))
    logger.info('Inference server for %s on %s:%d', engine.cfg.name, host,
                port)
    return server


def main(argv=None) -> int:
    from skypilot_tpu.utils.jax_env import honor_jax_platforms
    honor_jax_platforms()
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='tiny')
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--hf-checkpoint', default=None,
                        help='HF-layout checkpoint dir (config.json + '
                             'safetensors + tokenizer.json): serve real '
                             'published weights with the real BPE '
                             'tokenizer (models/hf_interop.py).')
    parser.add_argument('--host', default='0.0.0.0')
    parser.add_argument('--port', type=int, default=8080)
    parser.add_argument('--max-batch', type=int, default=8)
    parser.add_argument('--engine', default='batch',
                        choices=['batch', 'continuous'],
                        help='continuous = slot-based continuous '
                             'batching (JetStream-style serving core).')
    parser.add_argument('--max-len', type=int, default=None,
                        help='KV-cache length per slot (continuous '
                             'engine; default: the model context).')
    parser.add_argument('--block-size', type=int, default=None,
                        help='paged KV block granularity in tokens '
                             '(continuous engine; default '
                             '$SKYT_INFER_BLOCK_SIZE or 16).')
    parser.add_argument('--prefill-chunk', type=int, default=None,
                        help='chunked-prefill budget in tokens per '
                             'decode step (continuous engine; default '
                             '$SKYT_INFER_PREFILL_CHUNK or 64).')
    parser.add_argument('--kv-blocks', type=int, default=None,
                        help='total paged KV pool blocks (continuous '
                             'engine; default sized to max_slots * '
                             'max_len, i.e. the monolithic-cache HBM).')
    parser.add_argument('--spec-decode', action='store_true',
                        default=None,
                        help='speculative decoding (continuous engine): '
                             'n-gram drafts + one fused verify step per '
                             'window; greedy output is identical to the '
                             'plain engine (default $SKYT_SPEC_DECODE).')
    parser.add_argument('--draft-k', type=int, default=None,
                        help='draft tokens per speculative verify step '
                             '(default $SKYT_SPEC_DRAFT_K or 4).')
    parser.add_argument('--quantize', action='store_true',
                        help='int8 W8A8 weights (half the decode HBM '
                             'traffic, 2x MXU int8 rate).')
    parser.add_argument('--quantize-kv', action='store_true',
                        help='int8 KV cache (half the cache memory -> '
                             '2x context/slots; in-kernel dequant).')
    parser.add_argument('--mesh', default=None,
                        help="tensor-parallel serving, e.g. 'tensor=8' "
                             '(shards params over the local chips; how '
                             'flagship models span a slice).')
    parser.add_argument('--lora-pages', type=int, default=None,
                        help='device adapter page slots for multi-LoRA '
                             'serving (continuous engine; default '
                             '$SKYT_LORA_PAGES or 0 = disabled). Each '
                             'resident adapter charges KV blocks from '
                             'the shared paged pool '
                             '(docs/multi_lora_serving.md).')
    parser.add_argument('--lora-max-rank', type=int, default=None,
                        help='largest adapter rank the page stack '
                             'holds (default $SKYT_LORA_MAX_RANK or 8).')
    parser.add_argument('--lora-dir', default=None,
                        help='adapter registry root: every committed '
                             'adapter under it is registered at '
                             'startup (base-digest checked against '
                             'the served checkpoint).')
    parser.add_argument('--role', default=None,
                        choices=['prefill', 'decode'],
                        help='disaggregated serving role (continuous '
                             'engine; default $SKYT_DISAGG_ROLE): '
                             'prefill replicas export KV for the '
                             'decode fleet to pull, decode replicas '
                             'import it and stream tokens '
                             '(docs/disaggregated_serving.md).')
    parser.add_argument('--policy-store', default=None,
                        help='RL-pipeline policy store to serve '
                             '(continuous engine; default '
                             '$SKYT_RL_STORE): pull the committed '
                             'policy before serving, then poll every '
                             '$SKYT_RL_EVAL_POLL_S seconds and '
                             'live-refresh the engine with the shard '
                             'delta of each newer version '
                             '(docs/rl_pipeline.md).')
    args = parser.parse_args(argv)
    if args.engine == 'continuous':
        from skypilot_tpu.inference.continuous import (
            ContinuousBatchingEngine)
        base_digest = None
        if args.lora_dir:
            # Bind the served base to its content digest so adapter
            # registration can reject mispointed registries.
            from skypilot_tpu.serve import adapter_registry
            ckpt = args.hf_checkpoint or args.checkpoint_dir
            if ckpt and os.path.isdir(ckpt):
                base_digest = adapter_registry.checkpoint_digest(ckpt)
        engine = ContinuousBatchingEngine(
            args.model,
            checkpoint_dir=args.checkpoint_dir,
            hf_checkpoint=args.hf_checkpoint,
            max_slots=args.max_batch,
            max_len=args.max_len,
            block_size=args.block_size,
            prefill_chunk=args.prefill_chunk,
            num_blocks=args.kv_blocks,
            quantize=args.quantize,
            quantize_kv=args.quantize_kv,
            mesh=args.mesh,
            spec_decode=args.spec_decode,
            draft_k=args.draft_k,
            role=args.role,
            lora_pages=args.lora_pages,
            lora_max_rank=args.lora_max_rank,
            base_digest=base_digest)
        if args.lora_dir:
            from skypilot_tpu.serve import adapter_registry
            names = adapter_registry.load_registry_into(
                engine, args.lora_dir)
            logger.info('registered %d adapters from %s: %s',
                        len(names), args.lora_dir, names)
        policy_store = args.policy_store
        if policy_store is None:
            from skypilot_tpu.utils import env_registry
            policy_store = env_registry.get_str('SKYT_RL_STORE')
        if policy_store:
            watch_policy_store(engine, policy_store)
        if engine.role == 'prefill':
            # Warm the prefill program; drop the throwaway export.
            engine.exporter.pop(engine.prefill_and_export(
                engine.tokenizer.encode('warmup')))
        else:
            engine.generate_text('warmup', max_new_tokens=8)
    else:
        engine = InferenceEngine(args.model,
                                 checkpoint_dir=args.checkpoint_dir,
                                 hf_checkpoint=args.hf_checkpoint,
                                 max_batch=args.max_batch,
                                 quantize=args.quantize,
                                 quantize_kv=args.quantize_kv,
                                 mesh=args.mesh)
        # Warm the compile cache so the first real request (and the
        # serve stack's readiness window) isn't paying XLA compile time.
        engine.generate_text(['warmup'], max_new_tokens=8)
    server = serve(engine, args.host, args.port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == '__main__':
    sys.exit(main())
