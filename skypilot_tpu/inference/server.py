"""Inference HTTP server: the in-tree serving payload.

Runs behind the serve stack (``serve/``): the replica manager launches
this per replica, the readiness probe hits /health, the load balancer
proxies /generate. stdlib HTTP (threaded) -- the data plane is the TPU
decode scan, not the web layer.

    python -m skypilot_tpu.inference.server --model tiny --port 8080

Endpoints:
    GET  /health            -> 200 {"status": "ok", "model": ...}
    GET  /stats             -> decode throughput counters
    POST /generate          -> {"prompts": [...], "max_new_tokens": N,
                                "temperature": t} -> {"outputs": [...]}

Parity: the JetStream/vLLM serving payloads of the reference
(``examples/tpu/v6e/benchmark-llama2-7b.yaml``, ``llm/vllm``).
"""
from __future__ import annotations

import argparse
import json
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from skypilot_tpu.inference.engine import InferenceEngine
from skypilot_tpu.utils import log

logger = log.init_logger(__name__)


def make_handler(engine: InferenceEngine):

    class Handler(BaseHTTPRequestHandler):
        protocol_version = 'HTTP/1.1'

        def log_message(self, fmt, *args):  # quiet
            logger.debug(fmt, *args)

        def _json(self, code: int, payload) -> None:
            body = json.dumps(payload).encode('utf-8')
            self.send_response(code)
            self.send_header('Content-Type', 'application/json')
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == '/health':
                self._json(200, {'status': 'ok',
                                 'model': engine.cfg.name})
            elif self.path == '/stats':
                stats = engine.stats
                self._json(200, stats() if callable(stats) else stats)
            else:
                self._json(404, {'error': 'not found'})

        def do_POST(self):
            if self.path != '/generate':
                self._json(404, {'error': 'not found'})
                return
            try:
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length) or b'{}')
                prompts = req.get('prompts') or [req.get('prompt', '')]
                kwargs = dict(
                    max_new_tokens=int(req.get('max_new_tokens', 32)),
                    temperature=float(req.get('temperature', 0.0)),
                    seed=int(req.get('seed', 0)))
                if hasattr(engine, 'generate_texts'):
                    outputs = engine.generate_texts(prompts, **kwargs)
                else:
                    outputs = engine.generate_text(prompts, **kwargs)
                self._json(200, {'outputs': outputs})
            except Exception as e:  # pylint: disable=broad-except
                logger.error('generate failed: %s', e, exc_info=True)
                self._json(500, {'error': str(e)})

    return Handler


def serve(engine: InferenceEngine, host: str, port: int):
    server = ThreadingHTTPServer((host, port), make_handler(engine))
    logger.info('Inference server for %s on %s:%d', engine.cfg.name, host,
                port)
    return server


def main(argv=None) -> int:
    from skypilot_tpu.utils.jax_env import honor_jax_platforms
    honor_jax_platforms()
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='tiny')
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--hf-checkpoint', default=None,
                        help='HF-layout checkpoint dir (config.json + '
                             'safetensors + tokenizer.json): serve real '
                             'published weights with the real BPE '
                             'tokenizer (models/hf_interop.py).')
    parser.add_argument('--host', default='0.0.0.0')
    parser.add_argument('--port', type=int, default=8080)
    parser.add_argument('--max-batch', type=int, default=8)
    parser.add_argument('--engine', default='batch',
                        choices=['batch', 'continuous'],
                        help='continuous = slot-based continuous '
                             'batching (JetStream-style serving core).')
    parser.add_argument('--max-len', type=int, default=None,
                        help='KV-cache length per slot (continuous '
                             'engine; default: the model context).')
    parser.add_argument('--quantize', action='store_true',
                        help='int8 W8A8 weights (half the decode HBM '
                             'traffic, 2x MXU int8 rate).')
    parser.add_argument('--quantize-kv', action='store_true',
                        help='int8 KV cache (half the cache memory -> '
                             '2x context/slots; in-kernel dequant).')
    parser.add_argument('--mesh', default=None,
                        help="tensor-parallel serving, e.g. 'tensor=8' "
                             '(shards params over the local chips; how '
                             'flagship models span a slice).')
    args = parser.parse_args(argv)
    if args.engine == 'continuous':
        from skypilot_tpu.inference.continuous import (
            ContinuousBatchingEngine)
        engine = ContinuousBatchingEngine(
            args.model,
            checkpoint_dir=args.checkpoint_dir,
            hf_checkpoint=args.hf_checkpoint,
            max_slots=args.max_batch,
            max_len=args.max_len,
            quantize=args.quantize,
            quantize_kv=args.quantize_kv,
            mesh=args.mesh)
        engine.generate_text('warmup', max_new_tokens=8)
    else:
        engine = InferenceEngine(args.model,
                                 checkpoint_dir=args.checkpoint_dir,
                                 hf_checkpoint=args.hf_checkpoint,
                                 max_batch=args.max_batch,
                                 quantize=args.quantize,
                                 quantize_kv=args.quantize_kv,
                                 mesh=args.mesh)
        # Warm the compile cache so the first real request (and the
        # serve stack's readiness window) isn't paying XLA compile time.
        engine.generate_text(['warmup'], max_new_tokens=8)
    server = serve(engine, args.host, args.port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == '__main__':
    sys.exit(main())
