"""Minimal Azure Blob client: SharedKey over stdlib HTTP.

Parity: ``sky/data/storage.py:144 AzureBlobStore`` — the reference
shells out to az-cli/azure SDKs; neither is in this image, so the wire
protocol is implemented directly, the same stance as ``data/s3.py``
(SigV4) and the GCP driver (urllib REST): SharedKey signing is ~40
lines of hmac and removes the dependency entirely.

Credentials/endpoint resolution:
1. explicit ``AzureBlobConfig`` arguments;
2. env: ``AZURE_STORAGE_ACCOUNT`` / ``AZURE_STORAGE_KEY`` /
   ``SKYT_AZURE_BLOB_ENDPOINT`` (testing: point at a fake server);
3. layered config: ``storage.azure.{account,key,endpoint_url}``.

Also a tiny CLI (``python3 -m skypilot_tpu.data.azure_blob``) for the
cluster-side download commands (hosts carry the shipped runtime).
"""
from __future__ import annotations

import base64
import dataclasses
import datetime
import hashlib
import hmac
import os
import sys
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional
from xml.etree import ElementTree

from skypilot_tpu import exceptions

API_VERSION = '2021-08-06'
# Files above this stream as Put Block / Put Block List instead of one
# Put Blob (single-put has a service limit and would buffer the whole
# file in memory).
SINGLE_PUT_LIMIT = 64 * 1024 * 1024
BLOCK_SIZE = 32 * 1024 * 1024


class AzureHttpError(exceptions.StorageError):
    """Storage error carrying the HTTP status (never classify by
    substring — a container named 'x-404' must not read as missing)."""

    def __init__(self, message: str, code: int) -> None:
        super().__init__(message)
        self.code = code


@dataclasses.dataclass
class AzureBlobConfig:
    account: str
    key: str
    endpoint_url: str  # e.g. https://{account}.blob.core.windows.net

    @classmethod
    def load(cls,
             account: Optional[str] = None,
             key: Optional[str] = None,
             endpoint_url: Optional[str] = None,
             require_credentials: bool = True) -> 'AzureBlobConfig':
        from skypilot_tpu import config as config_lib

        def pick(explicit, env_key, cfg_key):
            if explicit:
                return explicit
            if os.environ.get(env_key):
                return os.environ[env_key]
            return config_lib.get_nested(('storage', 'azure', cfg_key),
                                         None)

        account = pick(account, 'AZURE_STORAGE_ACCOUNT', 'account')
        key = pick(key, 'AZURE_STORAGE_KEY', 'key')
        endpoint = pick(endpoint_url, 'SKYT_AZURE_BLOB_ENDPOINT',
                        'endpoint_url')
        if (not account or not key) and require_credentials:
            raise exceptions.StorageError(
                'Azure Blob needs credentials: set '
                'AZURE_STORAGE_ACCOUNT/AZURE_STORAGE_KEY or '
                'storage.azure.account/key in config.')
        if not endpoint:
            endpoint = f'https://{account}.blob.core.windows.net'
        return cls(account=account or '', key=key or '',
                   endpoint_url=endpoint.rstrip('/'))


class AzureBlobClient:
    """Container/blob operations with SharedKey request signing."""

    def __init__(self, cfg: AzureBlobConfig) -> None:
        self.cfg = cfg

    # -- SharedKey -----------------------------------------------------

    def _signed_request(self, method: str, container: str, blob: str = '',
                        query: Optional[Dict[str, str]] = None,
                        body: bytes = b'',
                        extra_headers: Optional[Dict[str, str]] = None
                        ) -> urllib.request.Request:
        cfg = self.cfg
        query = dict(sorted((query or {}).items()))
        path = f'/{container}'
        if blob:
            path += f'/{urllib.parse.quote(blob)}'
        url = cfg.endpoint_url + path
        if query:
            url += '?' + urllib.parse.urlencode(query)
        now = datetime.datetime.now(datetime.timezone.utc)
        headers = {
            'x-ms-date': now.strftime('%a, %d %b %Y %H:%M:%S GMT'),
            'x-ms-version': API_VERSION,
        }
        headers.update(extra_headers or {})
        # Always pin Content-Type: urllib injects
        # 'application/x-www-form-urlencoded' whenever data is not None
        # (always here) — an unsigned header the real service includes
        # in ITS string-to-sign, so leaving it implicit 403s every
        # request on real Azure.
        headers.setdefault('Content-Type', 'application/octet-stream')
        canonical_headers = ''.join(
            f'{k.lower()}:{v}\n'
            for k, v in sorted(headers.items())
            if k.lower().startswith('x-ms-'))
        # Canonicalized resource: /account/path plus each query param
        # lowercase-sorted on its own line.
        canonical_resource = f'/{cfg.account}{path}'
        for k, v in query.items():
            canonical_resource += f'\n{k.lower()}:{v}'
        content_length = str(len(body)) if body else ''
        string_to_sign = '\n'.join([
            method,
            '',                       # Content-Encoding
            '',                       # Content-Language
            content_length,           # Content-Length ('' when 0)
            '',                       # Content-MD5
            headers.get('Content-Type', ''),
            '',                       # Date (x-ms-date is used)
            '', '', '', '', '',       # If-* / Range
        ]) + '\n' + canonical_headers + canonical_resource
        signature = base64.b64encode(
            hmac.new(base64.b64decode(cfg.key),
                     string_to_sign.encode('utf-8'),
                     hashlib.sha256).digest()).decode()
        headers['Authorization'] = (
            f'SharedKey {cfg.account}:{signature}')
        return urllib.request.Request(url, data=body,
                                      headers=headers, method=method)

    def _call(self, method: str, container: str, blob: str = '',
              query: Optional[Dict[str, str]] = None,
              body: bytes = b'',
              extra_headers: Optional[Dict[str, str]] = None) -> bytes:
        req = self._signed_request(method, container, blob, query, body,
                                   extra_headers)
        try:
            # data always set (b'' included) so urllib emits
            # Content-Length: 0 — Azure 411s length-less PUTs.
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode('utf-8', errors='replace')[:300]
            raise AzureHttpError(
                f'Azure Blob {method} {container}/{blob}: HTTP '
                f'{e.code} {detail}', code=e.code) from None
        except urllib.error.URLError as e:
            raise exceptions.StorageError(
                f'Azure Blob endpoint unreachable: {e}') from None

    # -- operations ----------------------------------------------------

    def container_exists(self, container: str) -> bool:
        try:
            self._call('GET', container,
                       query={'restype': 'container'})
            return True
        except AzureHttpError as e:
            if e.code == 404:
                return False
            raise

    def create_container(self, container: str) -> None:
        try:
            self._call('PUT', container, query={'restype': 'container'})
        except AzureHttpError as e:
            if e.code != 409:  # 409 = already exists
                raise

    def put_blob(self, container: str, blob: str, data: bytes) -> None:
        self._call('PUT', container, blob, body=data,
                   extra_headers={'x-ms-blob-type': 'BlockBlob',
                                  'Content-Type':
                                      'application/octet-stream'})

    def put_blob_from_file(self, container: str, blob: str,
                           path: str,
                           block_size: int = BLOCK_SIZE) -> None:
        """Upload a file; large files stream as Put Block + Put Block
        List (bounded memory, no single-put size limit)."""
        size = os.path.getsize(path)
        if size <= SINGLE_PUT_LIMIT and size <= block_size * 2:
            with open(path, 'rb') as f:
                self.put_blob(container, blob, f.read())
            return
        block_ids: List[str] = []
        with open(path, 'rb') as f:
            index = 0
            while True:
                chunk = f.read(block_size)
                if not chunk:
                    break
                block_id = base64.b64encode(
                    f'{index:08d}'.encode()).decode()
                self._call('PUT', container, blob, body=chunk,
                           query={'comp': 'block',
                                  'blockid': block_id})
                block_ids.append(block_id)
                index += 1
        manifest = ('<?xml version="1.0" encoding="utf-8"?><BlockList>'
                    + ''.join(f'<Latest>{bid}</Latest>'
                              for bid in block_ids)
                    + '</BlockList>').encode()
        self._call('PUT', container, blob, body=manifest,
                   query={'comp': 'blocklist'},
                   extra_headers={'Content-Type': 'application/xml'})

    def get_blob(self, container: str, blob: str) -> bytes:
        return self._call('GET', container, blob)

    def get_blob_to_file(self, container: str, blob: str,
                         path: str) -> None:
        """Stream a blob to disk (no full-blob buffer)."""
        import shutil
        req = self._signed_request('GET', container, blob)
        try:
            with urllib.request.urlopen(req, timeout=300) as resp, \
                    open(path, 'wb') as f:
                shutil.copyfileobj(resp, f, length=1024 * 1024)
        except urllib.error.HTTPError as e:
            raise AzureHttpError(
                f'Azure Blob GET {container}/{blob}: HTTP {e.code}',
                code=e.code) from None

    def list_blobs(self, container: str,
                   prefix: str = '') -> Iterator[str]:
        marker = ''
        while True:
            query = {'restype': 'container', 'comp': 'list'}
            if prefix:
                query['prefix'] = prefix
            if marker:
                query['marker'] = marker
            root = ElementTree.fromstring(
                self._call('GET', container, query=query))
            for el in root.iter('Name'):
                yield el.text or ''
            marker_el = root.find('NextMarker')
            marker = (marker_el.text or '') if marker_el is not None \
                else ''
            if not marker:
                return

    def delete_blob(self, container: str, blob: str) -> None:
        self._call('DELETE', container, blob)

    def delete_container(self, container: str) -> None:
        self._call('DELETE', container, query={'restype': 'container'})

    # -- sync helpers (store + CLI surface) ----------------------------

    def sync_up(self, local_dir: str, container: str,
                prefix: str = '') -> int:
        local_dir = os.path.expanduser(local_dir)
        count = 0
        if os.path.isfile(local_dir):
            name = (f'{prefix.rstrip("/")}/' if prefix else '') + \
                os.path.basename(local_dir)
            self.put_blob_from_file(container, name, local_dir)
            return 1
        for root, _dirs, files in os.walk(local_dir):
            for fn in files:
                full = os.path.join(root, fn)
                rel = os.path.relpath(full, local_dir)
                name = (f'{prefix.rstrip("/")}/' if prefix else '') + rel
                self.put_blob_from_file(container,
                                        name.replace(os.sep, '/'), full)
                count += 1
        return count

    def sync_down(self, container: str, prefix: str, dest: str) -> int:
        dest = os.path.abspath(os.path.expanduser(dest))
        count = 0
        for name in self.list_blobs(container, prefix):
            rel = name[len(prefix):].lstrip('/') if prefix else name
            target = os.path.join(dest, rel) if rel else os.path.join(
                dest, os.path.basename(name))
            # Server-supplied names must not escape dest ('..'
            # segments would let a shared bucket overwrite arbitrary
            # host files).
            target = os.path.normpath(target)
            if os.path.commonpath([dest, target]) != dest:
                raise exceptions.StorageError(
                    f'refusing blob name escaping the destination: '
                    f'{name!r}')
            os.makedirs(os.path.dirname(target) or '.', exist_ok=True)
            self.get_blob_to_file(container, name, target)
            count += 1
        return count


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest='op', required=True)
    down = sub.add_parser('download')
    down.add_argument('container')
    down.add_argument('prefix')
    down.add_argument('dest')
    up = sub.add_parser('upload')
    up.add_argument('source')
    up.add_argument('container')
    up.add_argument('--prefix', default='')
    args = parser.parse_args(argv)
    client = AzureBlobClient(AzureBlobConfig.load())
    if args.op == 'download':
        n = client.sync_down(args.container, args.prefix, args.dest)
    else:
        n = client.sync_up(args.source, args.container, args.prefix)
    print(f'{n} objects')
    return 0


if __name__ == '__main__':
    sys.exit(main())
