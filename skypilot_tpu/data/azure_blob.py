"""Minimal Azure Blob client: SharedKey over stdlib HTTP.

Parity: ``sky/data/storage.py:144 AzureBlobStore`` — the reference
shells out to az-cli/azure SDKs; neither is in this image, so the wire
protocol is implemented directly, the same stance as ``data/s3.py``
(SigV4) and the GCP driver (urllib REST): SharedKey signing is ~40
lines of hmac and removes the dependency entirely.

Credentials/endpoint resolution:
1. explicit ``AzureBlobConfig`` arguments;
2. env: ``AZURE_STORAGE_ACCOUNT`` / ``AZURE_STORAGE_KEY`` /
   ``SKYT_AZURE_BLOB_ENDPOINT`` (testing: point at a fake server);
3. layered config: ``storage.azure.{account,key,endpoint_url}``.

Also a tiny CLI (``python3 -m skypilot_tpu.data.azure_blob``) for the
cluster-side download commands (hosts carry the shipped runtime).
"""
from __future__ import annotations

import base64
import dataclasses
import datetime
import hashlib
import hmac
import os
import sys
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple
from xml.etree import ElementTree

from skypilot_tpu import exceptions
from skypilot_tpu.data import s3

API_VERSION = '2021-08-06'
# Files above this stream as Put Block / Put Block List instead of one
# Put Blob (single-put has a service limit and would buffer the whole
# file in memory).
SINGLE_PUT_LIMIT = 64 * 1024 * 1024
BLOCK_SIZE = 32 * 1024 * 1024


class AzureHttpError(exceptions.StorageError):
    """Storage error carrying the HTTP status (never classify by
    substring — a container named 'x-404' must not read as missing)."""

    def __init__(self, message: str, code: int,
                 retry_after: Optional[float] = None) -> None:
        super().__init__(message, http_status=code,
                         retry_after=retry_after)
        self.code = code


@dataclasses.dataclass
class AzureBlobConfig:
    account: str
    key: str
    endpoint_url: str  # e.g. https://{account}.blob.core.windows.net

    @classmethod
    def load(cls,
             account: Optional[str] = None,
             key: Optional[str] = None,
             endpoint_url: Optional[str] = None,
             require_credentials: bool = True) -> 'AzureBlobConfig':
        from skypilot_tpu import config as config_lib

        def pick(explicit, env_key, cfg_key):
            if explicit:
                return explicit
            if os.environ.get(env_key):
                return os.environ[env_key]
            return config_lib.get_nested(('storage', 'azure', cfg_key),
                                         None)

        account = pick(account, 'AZURE_STORAGE_ACCOUNT', 'account')
        key = pick(key, 'AZURE_STORAGE_KEY', 'key')
        endpoint = pick(endpoint_url, 'SKYT_AZURE_BLOB_ENDPOINT',
                        'endpoint_url')
        if (not account or not key) and require_credentials:
            raise exceptions.StorageError(
                'Azure Blob needs credentials: set '
                'AZURE_STORAGE_ACCOUNT/AZURE_STORAGE_KEY or '
                'storage.azure.account/key in config.')
        if not endpoint:
            endpoint = f'https://{account}.blob.core.windows.net'
        return cls(account=account or '', key=key or '',
                   endpoint_url=endpoint.rstrip('/'))


class AzureBlobClient:
    """Container/blob operations with SharedKey request signing."""

    def __init__(self, cfg: AzureBlobConfig) -> None:
        self.cfg = cfg

    # -- SharedKey -----------------------------------------------------

    def _signed_request(self, method: str, container: str, blob: str = '',
                        query: Optional[Dict[str, str]] = None,
                        body: bytes = b'',
                        extra_headers: Optional[Dict[str, str]] = None
                        ) -> urllib.request.Request:
        cfg = self.cfg
        query = dict(sorted((query or {}).items()))
        path = f'/{container}'
        if blob:
            path += f'/{urllib.parse.quote(blob)}'
        url = cfg.endpoint_url + path
        if query:
            url += '?' + urllib.parse.urlencode(query)
        now = datetime.datetime.now(datetime.timezone.utc)
        headers = {
            'x-ms-date': now.strftime('%a, %d %b %Y %H:%M:%S GMT'),
            'x-ms-version': API_VERSION,
        }
        headers.update(extra_headers or {})
        # Always pin Content-Type: urllib injects
        # 'application/x-www-form-urlencoded' whenever data is not None
        # (always here) — an unsigned header the real service includes
        # in ITS string-to-sign, so leaving it implicit 403s every
        # request on real Azure.
        headers.setdefault('Content-Type', 'application/octet-stream')
        canonical_headers = ''.join(
            f'{k.lower()}:{v}\n'
            for k, v in sorted(headers.items())
            if k.lower().startswith('x-ms-'))
        # Canonicalized resource: /account/path plus each query param
        # lowercase-sorted on its own line.
        canonical_resource = f'/{cfg.account}{path}'
        for k, v in query.items():
            canonical_resource += f'\n{k.lower()}:{v}'
        content_length = str(len(body)) if body else ''
        string_to_sign = '\n'.join([
            method,
            '',                       # Content-Encoding
            '',                       # Content-Language
            content_length,           # Content-Length ('' when 0)
            '',                       # Content-MD5
            headers.get('Content-Type', ''),
            '',                       # Date (x-ms-date is used)
            '', '', '', '', '',       # If-* / Range
        ]) + '\n' + canonical_headers + canonical_resource
        signature = base64.b64encode(
            hmac.new(base64.b64decode(cfg.key),
                     string_to_sign.encode('utf-8'),
                     hashlib.sha256).digest()).decode()
        headers['Authorization'] = (
            f'SharedKey {cfg.account}:{signature}')
        return urllib.request.Request(url, data=body,
                                      headers=headers, method=method)

    def _call_full(self, method: str, container: str, blob: str = '',
                   query: Optional[Dict[str, str]] = None,
                   body: bytes = b'',
                   extra_headers: Optional[Dict[str, str]] = None):
        """Returns (response headers, body)."""
        req = self._signed_request(method, container, blob, query, body,
                                   extra_headers)
        try:
            # data always set (b'' included) so urllib emits
            # Content-Length: 0 — Azure 411s length-less PUTs.
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.headers, resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode('utf-8', errors='replace')[:300]
            raise AzureHttpError(
                f'Azure Blob {method} {container}/{blob}: HTTP '
                f'{e.code} {detail}', code=e.code,
                retry_after=s3._retry_after_seconds(e.code, e.headers)
            ) from None
        except urllib.error.URLError as e:
            raise exceptions.StorageError(
                f'Azure Blob endpoint unreachable: {e}') from None

    def _call(self, method: str, container: str, blob: str = '',
              query: Optional[Dict[str, str]] = None,
              body: bytes = b'',
              extra_headers: Optional[Dict[str, str]] = None) -> bytes:
        _, payload = self._call_full(method, container, blob, query,
                                     body, extra_headers)
        return payload

    # -- operations ----------------------------------------------------

    def container_exists(self, container: str) -> bool:
        try:
            self._call('GET', container,
                       query={'restype': 'container'})
            return True
        except AzureHttpError as e:
            if e.code == 404:
                return False
            raise

    def create_container(self, container: str) -> None:
        try:
            self._call('PUT', container, query={'restype': 'container'})
        except AzureHttpError as e:
            if e.code != 409:  # 409 = already exists
                raise

    def put_blob(self, container: str, blob: str, data: bytes) -> str:
        """Single-request Put Blob; returns the service ETag ('' if
        absent)."""
        headers, _ = self._call_full(
            'PUT', container, blob, body=data,
            extra_headers={'x-ms-blob-type': 'BlockBlob',
                           'Content-Type':
                               'application/octet-stream'})
        return (headers.get('ETag') or '').strip('"')

    def put_block(self, container: str, blob: str, block_id: str,
                  data: bytes) -> None:
        """Stage one block (blocks of one blob may upload in
        parallel)."""
        self._call('PUT', container, blob, body=data,
                   query={'comp': 'block', 'blockid': block_id})

    def put_block_list(self, container: str, blob: str,
                       block_ids: List[str]) -> str:
        """Commit staged blocks in order; returns the blob ETag."""
        manifest = ('<?xml version="1.0" encoding="utf-8"?><BlockList>'
                    + ''.join(f'<Latest>{bid}</Latest>'
                              for bid in block_ids)
                    + '</BlockList>').encode()
        headers, _ = self._call_full(
            'PUT', container, blob, body=manifest,
            query={'comp': 'blocklist'},
            extra_headers={'Content-Type': 'application/xml'})
        return (headers.get('ETag') or '').strip('"')

    def put_blob_from_file(self, container: str, blob: str,
                           path: str,
                           block_size: int = BLOCK_SIZE) -> str:
        """Upload a file; large files stream as Put Block + Put Block
        List (bounded memory, no single-put size limit). Returns the
        blob ETag."""
        size = os.path.getsize(path)
        if size <= SINGLE_PUT_LIMIT and size <= block_size * 2:
            with open(path, 'rb') as f:
                return self.put_blob(container, blob, f.read())
        block_ids: List[str] = []
        with open(path, 'rb') as f:
            index = 0
            while True:
                chunk = f.read(block_size)
                if not chunk:
                    break
                block_id = base64.b64encode(
                    f'{index:08d}'.encode()).decode()
                self.put_block(container, blob, block_id, chunk)
                block_ids.append(block_id)
                index += 1
        return self.put_block_list(container, blob, block_ids)

    def get_blob(self, container: str, blob: str) -> bytes:
        return self._call('GET', container, blob)

    def get_blob_range(self, container: str, blob: str, start: int,
                       length: int) -> bytes:
        """Ranged read via ``x-ms-range`` (signed as an x-ms header, so
        no Range slot gymnastics in the SharedKey string-to-sign)."""
        end = start + length - 1
        req = self._signed_request(
            'GET', container, blob,
            extra_headers={'x-ms-range': f'bytes={start}-{end}'})
        try:
            with urllib.request.urlopen(req, timeout=300) as resp:
                if resp.status == 206:
                    return resp.read()
                # Endpoint ignored the range header (some emulators):
                # stream to the slice and close — never buffer the
                # whole blob per part request.
                from skypilot_tpu.data.s3 import _read_slice
                return _read_slice(resp, start, length)
        except urllib.error.HTTPError as e:
            e.read()
            raise AzureHttpError(
                f'Azure Blob ranged GET {container}/{blob} '
                f'[{start}-{end}]: HTTP {e.code}', code=e.code,
                retry_after=s3._retry_after_seconds(e.code, e.headers)
            ) from None
        except urllib.error.URLError as e:
            raise exceptions.StorageError(
                f'Azure Blob endpoint unreachable: {e}') from None

    def get_blob_to_file(self, container: str, blob: str,
                         path: str) -> str:
        """Stream a blob to disk (no full-blob buffer), atomically:
        the bytes land in a same-dir .tmp renamed into place, so a kill
        mid-download never leaves a truncated ``path``. Returns the md5
        hex of the content."""
        tmp = f'{path}.skyt-tmp.{os.getpid()}'
        md5 = hashlib.md5()
        req = self._signed_request('GET', container, blob)
        try:
            with urllib.request.urlopen(req, timeout=300) as resp, \
                    open(tmp, 'wb') as f:
                while True:
                    chunk = resp.read(1024 * 1024)
                    if not chunk:
                        break
                    md5.update(chunk)
                    f.write(chunk)
            os.replace(tmp, path)
            return md5.hexdigest()
        except urllib.error.HTTPError as e:
            raise AzureHttpError(
                f'Azure Blob GET {container}/{blob}: HTTP {e.code}',
                code=e.code,
                retry_after=s3._retry_after_seconds(e.code, e.headers)
            ) from None
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def list_blobs_meta(self, container: str, prefix: str = ''
                        ) -> Iterator[Tuple[str, int, str]]:
        """Yield (name, size, etag) per blob; size -1 / etag '' when
        the listing omits Properties."""
        marker = ''
        while True:
            query = {'restype': 'container', 'comp': 'list'}
            if prefix:
                query['prefix'] = prefix
            if marker:
                query['marker'] = marker
            root = ElementTree.fromstring(
                self._call('GET', container, query=query))
            for blob_el in root.iter('Blob'):
                name_el = blob_el.find('Name')
                if name_el is None:
                    continue
                name = name_el.text or ''
                size, etag = -1, ''
                props = blob_el.find('Properties')
                if props is not None:
                    size_el = props.find('Content-Length')
                    etag_el = props.find('Etag')
                    try:
                        size = int(size_el.text) if size_el is not None \
                            and size_el.text else -1
                    except ValueError:
                        size = -1
                    etag = (etag_el.text or '') if etag_el is not None \
                        else ''
                yield name, size, etag
            marker_el = root.find('NextMarker')
            marker = (marker_el.text or '') if marker_el is not None \
                else ''
            if not marker:
                return

    def list_blobs(self, container: str,
                   prefix: str = '') -> Iterator[str]:
        for name, _, _ in self.list_blobs_meta(container, prefix):
            yield name

    def delete_blob(self, container: str, blob: str) -> None:
        self._call('DELETE', container, blob)

    def delete_container(self, container: str) -> None:
        self._call('DELETE', container, query={'restype': 'container'})

    # -- sync helpers (store + CLI surface; parallel delta engine) -----

    def sync_up(self, local_dir: str, container: str,
                prefix: str = '') -> int:
        """Upload a file or directory tree; returns object count
        (transferred + delta-skipped)."""
        from skypilot_tpu.data import transfer_engine
        engine = transfer_engine.TransferEngine()
        return engine.sync_up(
            local_dir, transfer_engine.AzureAdapter(self, container),
            prefix).count

    def sync_down(self, container: str, prefix: str, dest: str) -> int:
        """Download all blobs under prefix into dest; returns count
        (transferred + delta-skipped). The engine enforces the
        traversal guard (blob names may not escape ``dest``) and atomic
        placement."""
        from skypilot_tpu.data import transfer_engine
        engine = transfer_engine.TransferEngine()
        return engine.sync_down(
            transfer_engine.AzureAdapter(self, container), prefix,
            dest).count


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest='op', required=True)
    down = sub.add_parser('download')
    down.add_argument('container')
    down.add_argument('prefix')
    down.add_argument('dest')
    up = sub.add_parser('upload')
    up.add_argument('source')
    up.add_argument('container')
    up.add_argument('--prefix', default='')
    args = parser.parse_args(argv)
    client = AzureBlobClient(AzureBlobConfig.load())
    if args.op == 'download':
        n = client.sync_down(args.container, args.prefix, args.dest)
    else:
        n = client.sync_up(args.source, args.container, args.prefix)
    print(f'{n} objects')
    return 0


if __name__ == '__main__':
    sys.exit(main())
