"""Parallel delta-aware bulk transfer engine for the data plane.

Every launch and recovery moves code, datasets, and checkpoints through
object stores (SURVEY §5: file_mounts/storage COPY, MOUNT_CACHED
checkpoint resume). The previous path was strictly serial — one object
at a time, whole files buffered in memory (``f.read()`` per file), no
retries, no skip-unchanged — so a TPU pod resuming from a multi-GB
Orbax checkpoint paid the full serial round-trip on every preemption
even after PR 2/3 made the control-plane side fast. This engine is the
shared data-plane counterpart (Check-N-Run, NSDI '22: checkpoint upload
time bounds recovery cost; SkyPilot, NSDI '23: bulk data movement is a
first-class input):

* bounded worker pool (``SKYT_TRANSFER_WORKERS``, default 16) shared by
  object-level AND part-level tasks — many small files and the parts of
  one large object ride the same pool;
* constant-memory streaming I/O: files stream in ``CHUNK_SIZE`` pieces,
  parts are bounded by ``SKYT_TRANSFER_PART_SIZE`` (default 8 MiB) per
  in-flight worker — never a whole-file ``read()``;
* large objects (> ``SKYT_TRANSFER_MULTIPART_THRESHOLD``, default
  2x part size) split into multipart uploads / ranged parallel GETs
  when the backend supports them;
* manifest-based delta sync: a per-(src,dst,prefix) manifest under the
  state dir records ``size``/``mtime_ns`` per file plus the local md5
  and the observed remote ETag, so a warm re-sync of an unchanged tree
  is one listing and ZERO object bodies (size+mtime fast path; ETag /
  content-hash confirm when the stat cache misses);
* per-attempt retries with jittered backoff
  (:func:`skypilot_tpu.utils.resilience.backoff_delays`), chaos-testable
  via the deterministic ``SKYT_FAULT_SPEC`` sites ``data.put_object`` /
  ``data.get_object``;
* ``skyt_transfer_bytes_total{direction,outcome}`` /
  ``skyt_transfer_objects_total{direction,outcome}`` /
  ``skyt_transfer_seconds{direction}`` metrics in
  :mod:`skypilot_tpu.server.metrics`.

Callers: ``S3Client``/``AzureBlobClient`` sync methods (and therefore
the cluster-side CLIs every COPY mount runs), the store ``upload()``
implementations, and bucket-to-bucket ``data/data_transfer.py``.
Adapters wrap the wire clients; the engine owns scheduling, delta
decisions, retries, atomic placement (same-dir ``.tmp`` +
``os.replace``) and the path-traversal guard on downloads.

Knobs (documented in ``docs/data_plane.md``):
``SKYT_TRANSFER_WORKERS``, ``SKYT_TRANSFER_PART_SIZE``,
``SKYT_TRANSFER_MULTIPART_THRESHOLD``, ``SKYT_TRANSFER_RETRIES``,
``SKYT_TRANSFER_DELTA=0`` (disable delta sync).
"""
from __future__ import annotations

import concurrent.futures
import dataclasses
import hashlib
import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.server import metrics
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import log
from skypilot_tpu.utils import resilience
from skypilot_tpu.utils import timeline

logger = log.init_logger(__name__)

CHUNK_SIZE = 1024 * 1024

_MD5_HEX = re.compile(r'[0-9a-f]{32}')

# Transient failures worth re-attempting: backend HTTP errors surface as
# StorageError; socket resets/timeouts are OSError subclasses.
_RETRYABLE = (exceptions.StorageError, OSError)


def _is_retryable(exc: BaseException) -> bool:
    """Permanent failures (4xx except timeout/throttle, explicit
    ``permanent`` markers like traversal rejections) must fail fast —
    backing off four times on a 403 only turns an immediate hard error
    into seconds of sleeps per object."""
    if getattr(exc, 'permanent', False):
        return False
    status = getattr(exc, 'http_status', None)
    if status is not None and 400 <= status < 500 and \
            status not in (408, 429):
        return False
    return True

def _retry_reason(exc: BaseException, retry_after) -> str:
    """Classify a deferred attempt for skyt_transfer_retries_total.

    ``server_backpressure`` means the server named its own recovery
    horizon (Retry-After present) — the signal operators watch when
    deciding whether slowness is ours or the store's."""
    if retry_after is not None:
        return 'server_backpressure'
    status = getattr(exc, 'http_status', None)
    if status in (429, 503):
        return 'throttled'
    if isinstance(exc, TimeoutError):
        return 'timeout'
    if isinstance(exc, ConnectionError):
        return 'connection'
    return 'other'


PUT_SITE = 'data.put_object'
GET_SITE = 'data.get_object'


def norm_etag(etag: Optional[str]) -> str:
    """Strip quotes/whitespace; ETags compare as opaque lowercase."""
    if not etag:
        return ''
    return etag.strip().strip('"').lower()


def file_md5(path: str) -> str:
    md5 = hashlib.md5()
    with open(path, 'rb') as f:
        for chunk in iter(lambda: f.read(CHUNK_SIZE), b''):
            md5.update(chunk)
    return md5.hexdigest()


def _join_key(prefix: str, rel: str) -> str:
    return f'{prefix.rstrip("/")}/{rel}' if prefix else rel


def _rel_under(key: str, prefix: str) -> Optional[str]:
    """Relative path of ``key`` under ``prefix``, or None when the key
    merely shares the prefix string without a '/' boundary — listing
    prefix 'ckpt' also returns 'ckpt-old/...' (S3 prefix match is a
    plain string match); those are siblings, not children."""
    if not prefix:
        return key
    p = prefix.rstrip('/')
    if key == p:  # the prefix named the object itself
        return os.path.basename(key.rstrip('/')) or key
    if key.startswith(f'{p}/'):
        return key[len(p) + 1:].lstrip('/')
    return None


@dataclasses.dataclass
class ObjectMeta:
    key: str
    size: int
    etag: str = ''  # normalized ('' when the backend exposes none)


@dataclasses.dataclass
class TransferResult:
    transferred: int = 0
    skipped: int = 0
    bytes_moved: int = 0
    retries: int = 0

    @property
    def count(self) -> int:
        """Objects accounted for (kept + moved) — what the legacy sync
        methods reported as their object count."""
        return self.transferred + self.skipped


# ---------------------------------------------------------------------
# Adapters: the minimal per-backend surface the engine schedules over.
# ---------------------------------------------------------------------


class S3Adapter:
    """Wraps :class:`skypilot_tpu.data.s3.S3Client` for one bucket."""

    supports_ranges = True
    supports_multipart = True

    def __init__(self, client, bucket: str) -> None:
        self.client = client
        self.bucket = bucket

    def identity(self) -> str:
        return f's3://{self.client.cfg.endpoint_url}/{self.bucket}'

    def list_meta(self, prefix: str = '') -> List[ObjectMeta]:
        return [ObjectMeta(key, size, norm_etag(etag))
                for key, size, etag in
                self.client.list_objects_meta(self.bucket, prefix)]

    def get_to_file(self, key: str, path: str) -> str:
        return self.client.get_object_to_file(self.bucket, key, path)

    def get_range(self, key: str, start: int, length: int) -> bytes:
        return self.client.get_object_range(self.bucket, key, start,
                                            length)

    def get_bytes(self, key: str) -> bytes:
        return self.client.get_object(self.bucket, key)

    def put_file(self, key: str, path: str) -> str:
        return norm_etag(
            self.client.put_object_from_file(self.bucket, key, path))

    def put_bytes(self, key: str, data: bytes) -> str:
        self.client.put_object(self.bucket, key, data)
        return hashlib.md5(data).hexdigest()

    def multipart_begin(self, key: str) -> Dict:
        upload_id = self.client.create_multipart_upload(self.bucket, key)
        return {'key': key, 'upload_id': upload_id}

    def multipart_part(self, ctx: Dict, part_no: int,
                       data: bytes) -> str:
        return self.client.upload_part(self.bucket, ctx['key'],
                                       ctx['upload_id'], part_no, data)

    def multipart_complete(self, ctx: Dict,
                           parts: List[Tuple[int, str]]) -> str:
        return norm_etag(self.client.complete_multipart_upload(
            self.bucket, ctx['key'], ctx['upload_id'], parts))

    def multipart_abort(self, ctx: Dict) -> None:
        self.client.abort_multipart_upload(self.bucket, ctx['key'],
                                           ctx['upload_id'])


class AzureAdapter:
    """Wraps :class:`skypilot_tpu.data.azure_blob.AzureBlobClient` for
    one container. 'Multipart' is Put Block / Put Block List."""

    supports_ranges = True
    supports_multipart = True

    def __init__(self, client, container: str) -> None:
        self.client = client
        self.container = container

    def identity(self) -> str:
        return f'az://{self.client.cfg.endpoint_url}/{self.container}'

    def list_meta(self, prefix: str = '') -> List[ObjectMeta]:
        return [ObjectMeta(name, size, norm_etag(etag))
                for name, size, etag in
                self.client.list_blobs_meta(self.container, prefix)]

    def get_to_file(self, key: str, path: str) -> str:
        return self.client.get_blob_to_file(self.container, key, path)

    def get_range(self, key: str, start: int, length: int) -> bytes:
        return self.client.get_blob_range(self.container, key, start,
                                          length)

    def get_bytes(self, key: str) -> bytes:
        return self.client.get_blob(self.container, key)

    def put_file(self, key: str, path: str) -> str:
        return norm_etag(
            self.client.put_blob_from_file(self.container, key, path))

    def put_bytes(self, key: str, data: bytes) -> str:
        etag = self.client.put_blob(self.container, key, data)
        return norm_etag(etag) or hashlib.md5(data).hexdigest()

    @staticmethod
    def _block_id(part_no: int) -> str:
        import base64
        return base64.b64encode(f'{part_no:08d}'.encode()).decode()

    def multipart_begin(self, key: str) -> Dict:
        return {'key': key}

    def multipart_part(self, ctx: Dict, part_no: int,
                       data: bytes) -> str:
        block_id = self._block_id(part_no)
        self.client.put_block(self.container, ctx['key'], block_id, data)
        return block_id

    def multipart_complete(self, ctx: Dict,
                           parts: List[Tuple[int, str]]) -> str:
        block_ids = [token for _, token in sorted(parts)]
        return norm_etag(self.client.put_block_list(
            self.container, ctx['key'], block_ids))

    def multipart_abort(self, ctx: Dict) -> None:
        # Uncommitted Azure blocks are garbage-collected by the service
        # (7-day TTL); there is no abort verb to call.
        pass


class LocalFSAdapter:
    """A directory posing as a bucket (LocalStore's backend); gives the
    fake cloud the same engine path the real ones use."""

    supports_ranges = True
    supports_multipart = False

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(os.path.expanduser(root))

    def identity(self) -> str:
        return f'file://{self.root}'

    def _path(self, key: str) -> str:
        target = os.path.normpath(os.path.join(self.root, key))
        if os.path.commonpath([self.root, target]) != self.root:
            raise exceptions.StorageError(
                f'refusing object key escaping the bucket dir: {key!r}',
                permanent=True)
        return target

    def list_meta(self, prefix: str = '') -> List[ObjectMeta]:
        out: List[ObjectMeta] = []
        if not os.path.isdir(self.root):
            return out
        for dirpath, _, files in os.walk(self.root):
            for fn in files:
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.root).replace(os.sep,
                                                               '/')
                if prefix and not rel.startswith(prefix):
                    continue
                st = os.stat(path)
                out.append(ObjectMeta(rel, st.st_size, ''))
        return out

    def get_to_file(self, key: str, path: str) -> str:
        md5 = hashlib.md5()
        with open(self._path(key), 'rb') as src, open(path, 'wb') as dst:
            for chunk in iter(lambda: src.read(CHUNK_SIZE), b''):
                md5.update(chunk)
                dst.write(chunk)
        return md5.hexdigest()

    def get_range(self, key: str, start: int, length: int) -> bytes:
        with open(self._path(key), 'rb') as f:
            f.seek(start)
            return f.read(length)

    def get_bytes(self, key: str) -> bytes:
        with open(self._path(key), 'rb') as f:
            return f.read()

    def put_file(self, key: str, path: str) -> str:
        target = self._path(key)
        os.makedirs(os.path.dirname(target) or '.', exist_ok=True)
        tmp = f'{target}.skyt-tmp.{os.getpid()}'
        md5 = hashlib.md5()
        with open(path, 'rb') as src, open(tmp, 'wb') as dst:
            for chunk in iter(lambda: src.read(CHUNK_SIZE), b''):
                md5.update(chunk)
                dst.write(chunk)
        st = os.stat(path)
        os.utime(tmp, ns=(st.st_atime_ns, st.st_mtime_ns))
        os.replace(tmp, target)
        return md5.hexdigest()

    def put_bytes(self, key: str, data: bytes) -> str:
        target = self._path(key)
        os.makedirs(os.path.dirname(target) or '.', exist_ok=True)
        tmp = f'{target}.skyt-tmp.{os.getpid()}'
        with open(tmp, 'wb') as f:
            f.write(data)
        os.replace(tmp, target)
        return hashlib.md5(data).hexdigest()


# ---------------------------------------------------------------------
# Delta-sync manifest
# ---------------------------------------------------------------------


class _Manifest:
    """Per-(src, dst, prefix) sync state: for each object key, the local
    stat (``size``/``mtime_ns``), the local content ``md5`` ('' for
    multipart uploads, whose ETag is not an md5), and the remote
    ``remote_etag``/``remote_size`` observed when the object was last
    moved. The stat pair is the fast path — a warm re-sync never rehashes
    a file whose size+mtime are unchanged."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._files: Dict[str, Dict] = {}
        try:
            with open(path, 'r', encoding='utf-8') as f:
                data = json.load(f)
            files = data.get('files', {})
            if isinstance(files, dict):
                self._files = files
        except (OSError, ValueError):
            self._files = {}

    def get(self, key: str) -> Optional[Dict]:
        with self._lock:
            entry = self._files.get(key)
            return dict(entry) if entry else None

    def put(self, key: str, entry: Dict) -> None:
        with self._lock:
            self._files[key] = entry

    def save(self) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = f'{self.path}.{os.getpid()}.tmp'
        with self._lock:
            payload = json.dumps({'files': self._files})
        with open(tmp, 'w', encoding='utf-8') as f:
            f.write(payload)
        os.replace(tmp, self.path)


def _manifest_dir() -> str:
    state = os.environ.get('SKYT_STATE_DIR',
                           os.path.expanduser('~/.skyt'))
    return os.path.join(state, 'transfer_manifests')


class _NullManifest:
    """Delta disabled (SKYT_TRANSFER_DELTA=0): remembers nothing."""

    def get(self, key):  # noqa: D102
        return None

    def put(self, key, entry):  # noqa: D102
        pass

    def save(self):  # noqa: D102
        pass


# ---------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------


class TransferEngine:

    def __init__(self,
                 workers: Optional[int] = None,
                 part_size: Optional[int] = None,
                 multipart_threshold: Optional[int] = None,
                 max_attempts: Optional[int] = None,
                 delta: Optional[bool] = None) -> None:
        self.workers = workers or env_registry.get_int(
            'SKYT_TRANSFER_WORKERS', minimum=1)
        self.part_size = part_size or env_registry.get_int(
            'SKYT_TRANSFER_PART_SIZE', minimum=1)
        self.multipart_threshold = multipart_threshold or \
            env_registry.get_int('SKYT_TRANSFER_MULTIPART_THRESHOLD',
                                 default=2 * self.part_size, minimum=1)
        self.max_attempts = max_attempts or env_registry.get_int(
            'SKYT_TRANSFER_RETRIES', minimum=1)
        if delta is None:
            delta = env_registry.get_bool('SKYT_TRANSFER_DELTA')
        self.delta = delta

    # -- shared machinery ----------------------------------------------

    def _manifest(self, direction: str, src_id: str, dst_id: str,
                  prefix: str):
        if not self.delta:
            return _NullManifest()
        digest = hashlib.sha256(
            f'{direction}\n{src_id}\n{dst_id}\n{prefix}'.encode()
        ).hexdigest()[:24]
        return _Manifest(os.path.join(_manifest_dir(),
                                      f'{digest}.json'))

    def _attempt(self, direction: str, result: TransferResult,
                 lock: threading.Lock, fn: Callable, *,
                 site: Optional[str] = None, what: str = ''):
        """Run ``fn`` with bounded jittered-backoff retries; each retry
        is counted in the result and the skyt_transfer_* metrics."""
        delays = resilience.backoff_delays(base=0.05, cap=1.0)
        attempt = 0
        while True:
            attempt += 1
            try:
                if site:
                    fault_injection.inject(site)
                return fn()
            except _RETRYABLE as e:
                if attempt >= self.max_attempts or not _is_retryable(e):
                    raise
                with lock:
                    result.retries += 1
                metrics.TRANSFER_OBJECTS.inc(direction=direction,
                                             outcome='retried')
                delay = next(delays)
                # A Retry-After from a 429/503 is the server telling us
                # when capacity returns; honoring it as a *floor* under
                # our own jittered backoff keeps us polite without ever
                # retrying sooner than we otherwise would.
                retry_after = getattr(e, 'retry_after', None)
                metrics.TRANSFER_RETRIES.inc(
                    reason=_retry_reason(e, retry_after))
                if retry_after is not None:
                    delay = max(delay, retry_after)
                logger.debug('transfer %s failed (%s: %s); retry %d '
                             'in %.2fs', what, type(e).__name__, e,
                             attempt, delay)
                time.sleep(delay)

    def _execute(self, small_jobs: List[Callable],
                 large_jobs: List[Callable]) -> None:
        """Run object-level jobs on the bounded pool. Large jobs run
        from this thread and fan their part tasks onto the same pool
        (parts queue behind small objects; no worker ever blocks on
        another task, so the pool cannot deadlock)."""
        errors: List[BaseException] = []
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=self.workers) as pool:
            futures = [pool.submit(job) for job in small_jobs]
            for large in large_jobs:
                try:
                    large(pool)
                except BaseException as e:  # pylint: disable=broad-except
                    errors.append(e)
            for fut in futures:
                try:
                    fut.result()
                except BaseException as e:  # pylint: disable=broad-except
                    errors.append(e)
        if errors:
            first = errors[0]
            if isinstance(first, exceptions.StorageError):
                raise first
            raise exceptions.StorageError(
                f'transfer failed: {type(first).__name__}: '
                f'{first}') from first

    def _account_ok(self, direction: str, result: TransferResult,
                    lock: threading.Lock, nbytes: int) -> None:
        with lock:
            result.transferred += 1
            result.bytes_moved += nbytes
        metrics.TRANSFER_OBJECTS.inc(direction=direction, outcome='ok')
        metrics.TRANSFER_BYTES.inc(nbytes, direction=direction,
                                   outcome='ok')

    def _account_skip(self, direction: str, result: TransferResult,
                      lock: threading.Lock) -> None:
        with lock:
            result.skipped += 1
        metrics.TRANSFER_OBJECTS.inc(direction=direction,
                                     outcome='skipped')

    @staticmethod
    def _account_error(direction: str) -> None:
        metrics.TRANSFER_OBJECTS.inc(direction=direction,
                                     outcome='error')

    def _parts_of(self, size: int) -> List[Tuple[int, int]]:
        """(offset, length) pieces of a large object."""
        return [(off, min(self.part_size, size - off))
                for off in range(0, size, self.part_size)]

    @staticmethod
    def _gather(futs: List[concurrent.futures.Future]) -> List:
        """Wait for every part future — cancelling the still-queued ones
        on first failure — and only then raise. A part task must never
        outlive its job: a straggler would pwrite into a recycled fd of
        the next download, or upload a part to an already-aborted
        multipart id (recreating billed orphan storage)."""
        first: Optional[BaseException] = None
        results: List = []
        for fut in futs:
            try:
                results.append(fut.result())
            except concurrent.futures.CancelledError:
                pass
            except BaseException as e:  # pylint: disable=broad-except
                if first is None:
                    first = e
                    for other in futs:
                        other.cancel()
        if first is not None:
            raise first
        return results

    # -- upload (local -> store) ---------------------------------------

    # transfer.* timeline events double as distributed-tracing spans
    # when a request trace is ambient (an executor child syncing a
    # workdir/file mount): the data-plane hop shows up on the critical
    # path without a second instrumentation layer.
    @timeline.event('transfer.sync_up')
    def sync_up(self, local_root: str, adapter, prefix: str = ''
                ) -> TransferResult:
        started = time.monotonic()
        local_root = os.path.expanduser(local_root)
        files: List[Tuple[str, str]] = []  # (object key, local path)
        if os.path.isfile(local_root):
            files.append((_join_key(prefix, os.path.basename(local_root)),
                          local_root))
        else:
            for dirpath, _, filenames in os.walk(local_root):
                for fn in filenames:
                    path = os.path.join(dirpath, fn)
                    rel = os.path.relpath(path, local_root).replace(
                        os.sep, '/')
                    files.append((_join_key(prefix, rel), path))
        remote = {m.key: m for m in adapter.list_meta(prefix)}
        manifest = self._manifest('up', f'file://{os.path.abspath(local_root)}',
                                  adapter.identity(), prefix)
        result = TransferResult()
        lock = threading.Lock()
        uploads: List[Tuple[str, str, os.stat_result]] = []
        confirms: List[Tuple[str, str, os.stat_result, ObjectMeta]] = []
        for key, path in files:
            st = os.stat(path)
            action = self._classify_up(key, st, remote.get(key),
                                       manifest)
            if action == 'skip':
                self._account_skip('up', result, lock)
            elif action == 'confirm':
                confirms.append((key, path, st, remote[key]))
            else:
                uploads.append((key, path, st))
        uploads.extend(self._confirm_up(confirms, manifest, result,
                                        lock))
        small: List[Callable] = []
        large: List[Callable] = []
        for key, path, st in uploads:
            if st.st_size > self.multipart_threshold and \
                    adapter.supports_multipart:
                large.append(self._make_large_upload(
                    adapter, key, path, st, manifest, result, lock))
            else:
                small.append(self._make_small_upload(
                    adapter, key, path, st, manifest, result, lock))
        self._execute(small, large)
        manifest.save()
        metrics.TRANSFER_SECONDS.observe(time.monotonic() - started,
                                         direction='up')
        return result

    def _classify_up(self, key: str, st: os.stat_result,
                     remote: Optional[ObjectMeta], manifest) -> str:
        """'skip' (delta hit), 'confirm' (content-hash check pending),
        or 'upload'. Remote sizes of -1 mean the listing omitted Size —
        never a mismatch, fall through to the ETag evidence."""
        if not self.delta or remote is None:
            return 'upload'
        if remote.size >= 0 and remote.size != st.st_size:
            return 'upload'
        entry = manifest.get(key)
        stat_fast = (entry is not None and
                     entry.get('size') == st.st_size and
                     entry.get('mtime_ns') == st.st_mtime_ns)
        if stat_fast:
            if remote.etag and remote.etag in (
                    entry.get('remote_etag'), entry.get('md5')):
                return 'skip'
            if not remote.etag and \
                    entry.get('remote_size') == remote.size:
                return 'skip'
            return 'upload'
        # Stat cache miss (new file, touched file, or first sync from
        # this host): content-hash confirm, but only against a plain-md5
        # ETag — multipart ETags ('-' suffixed) cannot be recomputed
        # from the file cheaply.
        if remote.etag and '-' not in remote.etag:
            return 'confirm'
        return 'upload'

    def _confirm_up(self, confirms: List[Tuple[str, str, os.stat_result,
                                               ObjectMeta]],
                    manifest, result: TransferResult,
                    lock: threading.Lock
                    ) -> List[Tuple[str, str, os.stat_result]]:
        """Hash-confirm stat-cache misses on the pool (a fresh host
        re-syncing an already-uploaded tree must not hash it on one
        thread); returns the files that actually need uploading."""
        if not confirms:
            return []
        need: List[Tuple[str, str, os.stat_result]] = []
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=self.workers) as pool:
            futs = [(pool.submit(file_md5, path), key, path, st, rem)
                    for key, path, st, rem in confirms]
            for fut, key, path, st, rem in futs:
                try:
                    md5 = fut.result()
                except OSError:
                    need.append((key, path, st))
                    continue
                if md5 == rem.etag:
                    manifest.put(key, {
                        'size': st.st_size, 'mtime_ns': st.st_mtime_ns,
                        'md5': md5, 'remote_etag': rem.etag,
                        'remote_size': rem.size,
                    })
                    self._account_skip('up', result, lock)
                else:
                    need.append((key, path, st))
        return need

    def _make_small_upload(self, adapter, key, path, st, manifest,
                           result, lock) -> Callable:
        def job():
            try:
                etag = self._attempt(
                    'up', result, lock,
                    lambda: adapter.put_file(key, path),
                    site=PUT_SITE, what=f'put {key}')
                # A single-request PUT's ETag is the content md5 on S3
                # (and our LocalFS adapter); reuse it rather than pay a
                # third full read of the file just to hash it.
                md5 = etag if _MD5_HEX.fullmatch(etag or '') \
                    else file_md5(path)
                manifest.put(key, {
                    'size': st.st_size, 'mtime_ns': st.st_mtime_ns,
                    'md5': md5, 'remote_etag': etag or md5,
                    'remote_size': st.st_size,
                })
            except BaseException:
                self._account_error('up')
                raise
            self._account_ok('up', result, lock, st.st_size)
        return job

    def _abort_multipart(self, adapter, ctx) -> None:
        """Best-effort: a failed multipart upload must not leave billed
        orphan parts behind (S3 keeps them until aborted)."""
        try:
            adapter.multipart_abort(ctx)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug('multipart abort failed (ignored): %s', e)

    def _make_large_upload(self, adapter, key, path, st, manifest,
                           result, lock) -> Callable:
        def job(pool):
            ctx = None
            try:
                ctx = self._attempt(
                    'up', result, lock,
                    lambda: adapter.multipart_begin(key),
                    site=PUT_SITE, what=f'begin {key}')
                parts = self._parts_of(st.st_size)

                def put_part(part_no, off, length):
                    def attempt_once():
                        with open(path, 'rb') as f:
                            f.seek(off)
                            data = f.read(length)
                        return adapter.multipart_part(ctx, part_no, data)
                    return self._attempt('up', result, lock,
                                         attempt_once, site=PUT_SITE,
                                         what=f'part {key}#{part_no}')

                futs = [pool.submit(put_part, no, off, length)
                        for no, (off, length) in enumerate(parts,
                                                           start=1)]
                tokens = list(enumerate(self._gather(futs), start=1))
                etag = self._attempt(
                    'up', result, lock,
                    lambda: adapter.multipart_complete(ctx, tokens),
                    site=PUT_SITE, what=f'complete {key}')
                manifest.put(key, {
                    'size': st.st_size, 'mtime_ns': st.st_mtime_ns,
                    'md5': '', 'remote_etag': etag,
                    'remote_size': st.st_size,
                })
            except BaseException:
                self._account_error('up')
                if ctx is not None:
                    self._abort_multipart(adapter, ctx)
                raise
            self._account_ok('up', result, lock, st.st_size)
        return job

    # -- download (store -> local) -------------------------------------

    @timeline.event('transfer.sync_down')
    def sync_down(self, adapter, prefix: str, dest: str
                  ) -> TransferResult:
        started = time.monotonic()
        dest = os.path.abspath(os.path.expanduser(dest))
        metas = adapter.list_meta(prefix)
        manifest = self._manifest('down', adapter.identity(),
                                  f'file://{dest}', prefix)
        result = TransferResult()
        lock = threading.Lock()
        small: List[Callable] = []
        large: List[Callable] = []
        for meta in metas:
            rel = _rel_under(meta.key, prefix)
            if rel is None:
                logger.debug('not under prefix %r, skipping: %r',
                             prefix, meta.key)
                continue
            target = os.path.normpath(os.path.join(dest, rel))
            # Server-supplied names must not escape dest ('..' segments
            # from a shared bucket would overwrite arbitrary host files).
            if os.path.commonpath([dest, target]) != dest:
                raise exceptions.StorageError(
                    f'refusing object name escaping the destination: '
                    f'{meta.key!r}')
            if self._skip_down(meta, target, manifest):
                self._account_skip('down', result, lock)
                continue
            if meta.size > self.multipart_threshold and \
                    adapter.supports_ranges:
                large.append(self._make_large_download(
                    adapter, meta, target, manifest, result, lock))
            else:
                small.append(self._make_small_download(
                    adapter, meta, target, manifest, result, lock))
        self._execute(small, large)
        manifest.save()
        metrics.TRANSFER_SECONDS.observe(time.monotonic() - started,
                                         direction='down')
        return result

    def _skip_down(self, meta: ObjectMeta, target: str,
                   manifest) -> bool:
        if not self.delta:
            return False
        try:
            st = os.stat(target)
        except OSError:
            return False
        if meta.size >= 0 and st.st_size != meta.size:
            return False
        entry = manifest.get(meta.key)
        stat_fast = (entry is not None and
                     entry.get('size') == st.st_size and
                     entry.get('mtime_ns') == st.st_mtime_ns)
        if not stat_fast:
            return False
        if meta.etag:
            return meta.etag in (entry.get('remote_etag'),
                                 entry.get('md5'))
        return entry.get('remote_size') == meta.size

    def _record_down(self, manifest, meta: ObjectMeta, target: str,
                     md5: str) -> None:
        st = os.stat(target)
        manifest.put(meta.key, {
            'size': st.st_size, 'mtime_ns': st.st_mtime_ns,
            'md5': md5, 'remote_etag': meta.etag,
            'remote_size': meta.size,
        })

    def _make_small_download(self, adapter, meta, target, manifest,
                             result, lock) -> Callable:
        def job():
            try:
                os.makedirs(os.path.dirname(target) or '.',
                            exist_ok=True)
                tmp = f'{target}.skyt-tmp.{os.getpid()}'
                try:
                    md5 = self._attempt(
                        'down', result, lock,
                        lambda: adapter.get_to_file(meta.key, tmp),
                        site=GET_SITE, what=f'get {meta.key}')
                    os.replace(tmp, target)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
                self._record_down(manifest, meta, target, md5)
                # Listings may omit Size (meta.size == -1); account the
                # bytes actually landed, never a negative.
                nbytes = meta.size if meta.size >= 0 \
                    else os.path.getsize(target)
            except BaseException:
                self._account_error('down')
                raise
            self._account_ok('down', result, lock, nbytes)
        return job

    def _make_large_download(self, adapter, meta, target, manifest,
                             result, lock) -> Callable:
        def job(pool):
            try:
                os.makedirs(os.path.dirname(target) or '.',
                            exist_ok=True)
                tmp = f'{target}.skyt-tmp.{os.getpid()}'
                fd = os.open(tmp, os.O_CREAT | os.O_WRONLY | os.O_TRUNC,
                             0o644)
                try:

                    def get_part(off, length):
                        def attempt_once():
                            data = adapter.get_range(meta.key, off,
                                                     length)
                            if len(data) != length:
                                raise exceptions.StorageError(
                                    f'short ranged read of {meta.key}: '
                                    f'{len(data)} != {length} at '
                                    f'{off}')
                            os.pwrite(fd, data, off)
                        return self._attempt(
                            'down', result, lock, attempt_once,
                            site=GET_SITE,
                            what=f'get {meta.key}@{off}')

                    self._gather([
                        pool.submit(get_part, off, length)
                        for off, length in self._parts_of(meta.size)])
                    os.close(fd)
                    fd = -1
                    md5 = file_md5(tmp)
                    os.replace(tmp, target)
                finally:
                    if fd >= 0:
                        os.close(fd)
                    # A failed ranged download must not leave a partial
                    # tmp in dest — a later sync_up of that tree would
                    # upload the garbage as a real object.
                    if os.path.exists(tmp):
                        os.unlink(tmp)
                self._record_down(manifest, meta, target, md5)
            except BaseException:
                self._account_error('down')
                raise
            self._account_ok('down', result, lock, meta.size)
        return job

    # -- copy (store -> store) -----------------------------------------

    @timeline.event('transfer.copy')
    def copy(self, src_adapter, src_prefix: str, dst_adapter,
             dst_prefix: str = '') -> TransferResult:
        """Bucket-to-bucket, streamed through this host part-by-part
        (bounded memory) — never spooling whole objects."""
        started = time.monotonic()
        src_metas = src_adapter.list_meta(src_prefix)
        dst_metas = {m.key: m for m in dst_adapter.list_meta(dst_prefix)}
        manifest = self._manifest('copy', src_adapter.identity(),
                                  dst_adapter.identity(),
                                  f'{src_prefix}->{dst_prefix}')
        result = TransferResult()
        lock = threading.Lock()
        small: List[Callable] = []
        large: List[Callable] = []
        for meta in src_metas:
            rel = _rel_under(meta.key, src_prefix)
            if rel is None:
                logger.debug('not under prefix %r, skipping: %r',
                             src_prefix, meta.key)
                continue
            dst_key = _join_key(dst_prefix, rel)
            if self._skip_copy(meta, dst_metas.get(dst_key), manifest):
                self._account_skip('copy', result, lock)
                continue
            if meta.size > self.multipart_threshold and \
                    src_adapter.supports_ranges and \
                    dst_adapter.supports_multipart:
                large.append(self._make_large_copy(
                    src_adapter, dst_adapter, meta, dst_key, manifest,
                    result, lock))
            else:
                small.append(self._make_small_copy(
                    src_adapter, dst_adapter, meta, dst_key, manifest,
                    result, lock))
        self._execute(small, large)
        manifest.save()
        metrics.TRANSFER_SECONDS.observe(time.monotonic() - started,
                                         direction='copy')
        return result

    def _skip_copy(self, src: ObjectMeta, dst: Optional[ObjectMeta],
                   manifest) -> bool:
        if not self.delta or dst is None:
            return False
        if src.size >= 0 and dst.size >= 0 and dst.size != src.size:
            return False
        # Same-backend stores with content ETags: direct match.
        if src.etag and dst.etag and src.etag == dst.etag:
            return True
        entry = manifest.get(src.key)
        if entry is None or not src.etag or \
                entry.get('src_etag') != src.etag:
            return False
        if dst.etag:
            return dst.etag in (entry.get('dst_etag'),
                                entry.get('md5'))
        return entry.get('dst_size') == dst.size

    def _record_copy(self, manifest, src: ObjectMeta, dst_key: str,
                     dst_etag: str, md5: str) -> None:
        manifest.put(src.key, {
            'src_etag': src.etag, 'dst_etag': dst_etag,
            'dst_key': dst_key, 'md5': md5, 'dst_size': src.size,
        })

    def _make_small_copy(self, src_adapter, dst_adapter, meta, dst_key,
                         manifest, result, lock) -> Callable:
        def job():
            try:
                data = self._attempt(
                    'copy', result, lock,
                    lambda: src_adapter.get_bytes(meta.key),
                    site=GET_SITE, what=f'get {meta.key}')
                etag = self._attempt(
                    'copy', result, lock,
                    lambda: dst_adapter.put_bytes(dst_key, data),
                    site=PUT_SITE, what=f'put {dst_key}')
                self._record_copy(manifest, meta, dst_key, etag,
                                  hashlib.md5(data).hexdigest())
                nbytes = len(data)
            except BaseException:
                self._account_error('copy')
                raise
            self._account_ok('copy', result, lock, nbytes)
        return job

    def _make_large_copy(self, src_adapter, dst_adapter, meta, dst_key,
                         manifest, result, lock) -> Callable:
        def job(pool):
            ctx = None
            try:
                ctx = self._attempt(
                    'copy', result, lock,
                    lambda: dst_adapter.multipart_begin(dst_key),
                    site=PUT_SITE, what=f'begin {dst_key}')

                def move_part(part_no, off, length):
                    def attempt_once():
                        data = src_adapter.get_range(meta.key, off,
                                                     length)
                        if len(data) != length:
                            raise exceptions.StorageError(
                                f'short ranged read of {meta.key}')
                        return dst_adapter.multipart_part(ctx, part_no,
                                                          data)
                    return self._attempt('copy', result, lock,
                                         attempt_once, site=GET_SITE,
                                         what=f'part {dst_key}'
                                              f'#{part_no}')

                futs = [pool.submit(move_part, no, off, length)
                        for no, (off, length) in enumerate(
                            self._parts_of(meta.size), start=1)]
                tokens = list(enumerate(self._gather(futs), start=1))
                etag = self._attempt(
                    'copy', result, lock,
                    lambda: dst_adapter.multipart_complete(ctx, tokens),
                    site=PUT_SITE, what=f'complete {dst_key}')
                self._record_copy(manifest, meta, dst_key, etag, '')
            except BaseException:
                self._account_error('copy')
                if ctx is not None:
                    self._abort_multipart(dst_adapter, ctx)
                raise
            self._account_ok('copy', result, lock, meta.size)
        return job
