"""Bucket-to-bucket transfer (parity: ``sky/data/data_transfer.py``).

GCS<->GCS stays cloud-side (gsutil rsync — no bytes through this host).
Every pair involving the stdlib-wire stores (S3-compatible, Azure Blob,
LOCAL) rides the parallel delta-aware transfer engine
(:mod:`skypilot_tpu.data.transfer_engine`): S3<->Azure and S3<->S3 are
streamed cross-backend part-by-part with bounded memory instead of
raising ``Unsupported transfer``, and store->LOCAL is a parallel
ranged-download sync.
"""
from __future__ import annotations

import shutil
import subprocess

from skypilot_tpu import exceptions
from skypilot_tpu.data.storage import (AbstractStore, AzureBlobStore,
                                       GcsStore, LocalStore,
                                       S3CompatibleStore)


def _engine_adapter(store: AbstractStore):
    """The transfer-engine adapter for a store, or None when the store
    has no wire client here (GCS shells out to gsutil)."""
    from skypilot_tpu.data import transfer_engine
    if isinstance(store, S3CompatibleStore):
        return transfer_engine.S3Adapter(store._client(), store.name)
    if isinstance(store, AzureBlobStore):
        return transfer_engine.AzureAdapter(store._client(), store.name)
    if isinstance(store, LocalStore):
        return transfer_engine.LocalFSAdapter(store.bucket_dir)
    return None


def transfer(src: AbstractStore, dst: AbstractStore) -> None:
    """Copy all objects of src into dst (cloud-side when possible)."""
    from skypilot_tpu.data import transfer_engine
    if isinstance(src, GcsStore) and isinstance(dst, GcsStore):
        proc = subprocess.run(
            ['gsutil', '-m', 'rsync', '-r', src.url, dst.url],
            capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageError(
                f'Transfer {src.url} -> {dst.url} failed: '
                f'{proc.stderr[-500:]}')
        return
    if isinstance(src, LocalStore) and isinstance(dst, LocalStore):
        shutil.copytree(src.bucket_dir, dst.bucket_dir, dirs_exist_ok=True)
        return
    if isinstance(src, LocalStore):
        dst.upload(src.bucket_dir)
        return
    src_adapter = _engine_adapter(src)
    if src_adapter is not None:
        engine = transfer_engine.TransferEngine()
        if isinstance(dst, LocalStore):
            dst.create()
            engine.sync_down(src_adapter, '', dst.bucket_dir)
            return
        dst_adapter = _engine_adapter(dst)
        if dst_adapter is not None:
            engine.copy(src_adapter, '', dst_adapter, '')
            return
    raise exceptions.StorageError(
        f'Unsupported transfer {type(src).__name__} -> '
        f'{type(dst).__name__}')
