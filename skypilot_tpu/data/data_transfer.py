"""Bucket-to-bucket transfer (parity: ``sky/data/data_transfer.py``)."""
from __future__ import annotations

import shutil
import subprocess

from skypilot_tpu import exceptions
from skypilot_tpu.data.storage import AbstractStore, GcsStore, LocalStore


def transfer(src: AbstractStore, dst: AbstractStore) -> None:
    """Copy all objects of src into dst (cloud-side when possible)."""
    if isinstance(src, GcsStore) and isinstance(dst, GcsStore):
        proc = subprocess.run(
            ['gsutil', '-m', 'rsync', '-r', src.url, dst.url],
            capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.StorageError(
                f'Transfer {src.url} -> {dst.url} failed: '
                f'{proc.stderr[-500:]}')
        return
    if isinstance(src, LocalStore) and isinstance(dst, LocalStore):
        shutil.copytree(src.bucket_dir, dst.bucket_dir, dirs_exist_ok=True)
        return
    if isinstance(src, LocalStore):
        dst.upload(src.bucket_dir)
        return
    raise exceptions.StorageError(
        f'Unsupported transfer {type(src).__name__} -> '
        f'{type(dst).__name__}')
