"""Bucket storage abstraction.

Parity: ``sky/data/storage.py`` (StoreType :144, StorageMode :336,
AbstractStore :538, Storage :781). GCS is the primary store (the
TPU-adjacent object store); a LOCAL store — a directory under the state
dir posing as a bucket — serves tests and the fake cloud the same way
the fake provider serves provisioning (no credentials, full machinery).

A ``Storage`` object is one entry of a task's ``storage_mounts``::

    storage_mounts:
      /checkpoints:
        name: my-ckpt-bucket       # bucket name (created if missing)
        store: gcs                 # gcs | local (default: gcs)
        mode: MOUNT_CACHED         # MOUNT | COPY | MOUNT_CACHED
      /data:
        source: gs://public-ds     # existing bucket -> name from URI
        mode: COPY

Client-side responsibilities (this module): create/validate the bucket,
upload a local ``source`` if given. Cluster-side responsibilities
(command strings consumed by the backend): mount or download onto every
host.
"""
from __future__ import annotations

import enum
import os
import shutil
import subprocess
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.data import mounting_utils
from skypilot_tpu.utils import log
from skypilot_tpu.utils.registry import Registry

logger = log.init_logger(__name__)

STORE_REGISTRY: Registry = Registry('store')


class StorageMode(enum.Enum):
    MOUNT = 'MOUNT'
    COPY = 'COPY'
    MOUNT_CACHED = 'MOUNT_CACHED'


class StoreType(enum.Enum):
    GCS = 'gcs'
    S3 = 's3'
    AZURE = 'azure'
    LOCAL = 'local'

    @classmethod
    def from_uri(cls, uri: str) -> 'StoreType':
        if uri.startswith('gs://'):
            return cls.GCS
        # oci:// rides the S3-compatible path: OCI Object Storage
        # exposes an S3 compat endpoint (storage.s3.endpoint_url =
        # https://{ns}.compat.objectstorage.{region}.oraclecloud.com).
        if uri.startswith(('s3://', 'r2://', 'oci://')):
            return cls.S3
        if uri.startswith(('az://', 'azblob://')):
            return cls.AZURE
        if uri.startswith('file://') or uri.startswith('local://'):
            return cls.LOCAL
        raise exceptions.StorageError(f'Unsupported storage URI {uri!r} '
                                      '(expected gs://, s3://, r2://, '
                                      'oci://, az:// or file://)')


def _strip_scheme(uri: str) -> str:
    for scheme in ('gs://', 's3://', 'r2://', 'oci://', 'az://',
                   'azblob://', 'file://', 'local://'):
        if uri.startswith(scheme):
            return uri[len(scheme):]
    return uri


class AbstractStore:
    """One bucket in one store backend (ref AbstractStore :538)."""

    def __init__(self, name: str) -> None:
        self.name = name

    # client side ------------------------------------------------------
    def exists(self) -> bool:
        raise NotImplementedError

    def create(self) -> None:
        raise NotImplementedError

    def upload(self, local_source: str, prefix: str = '') -> None:
        """Sync a local file/dir into the bucket under `prefix`."""
        raise NotImplementedError

    def delete(self) -> None:
        raise NotImplementedError

    # cluster side (command generation) --------------------------------
    def mount_command(self, mount_path: str) -> str:
        raise NotImplementedError

    def mount_cached_command(self, mount_path: str) -> str:
        raise NotImplementedError

    def download_command(self, dest: str, prefix: str = '') -> str:
        raise NotImplementedError

    @property
    def url(self) -> str:
        raise NotImplementedError


@STORE_REGISTRY.register('gcs', default=True)
class GcsStore(AbstractStore):
    """GCS via the gsutil/gcloud CLI (the reference shells out to the
    same tools for transfers; the cloud SDK python client is avoided so
    `import skypilot_tpu` stays dependency-light, same reasoning as the
    reference's lazy adaptors)."""

    def _gsutil(self, *args: str) -> subprocess.CompletedProcess:
        if shutil.which('gsutil') is None:
            raise exceptions.StorageError(
                'gsutil not found; install the Google Cloud SDK or use '
                "store: local for offline development.")
        return subprocess.run(['gsutil', *args], capture_output=True,
                              text=True, check=False)

    def exists(self) -> bool:
        return self._gsutil('ls', '-b', self.url).returncode == 0

    def create(self) -> None:
        proc = self._gsutil('mb', self.url)
        if proc.returncode != 0 and 'already exists' not in proc.stderr:
            raise exceptions.StorageError(
                f'Failed to create bucket {self.url}: {proc.stderr[-500:]}')

    def upload(self, local_source: str, prefix: str = '') -> None:
        dest = self.url + (f'/{prefix}' if prefix else '')
        src = os.path.expanduser(local_source)
        if os.path.isdir(src):
            proc = self._gsutil('-m', 'rsync', '-r', src, dest)
        else:
            proc = self._gsutil('cp', src, dest + '/')
        if proc.returncode != 0:
            raise exceptions.StorageError(
                f'Upload {src} -> {dest} failed: {proc.stderr[-500:]}')

    def delete(self) -> None:
        self._gsutil('-m', 'rm', '-r', self.url)

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.gcs_mount_command(self.name, mount_path)

    def mount_cached_command(self, mount_path: str) -> str:
        return mounting_utils.gcs_mount_cached_command(self.name,
                                                       mount_path)

    def download_command(self, dest: str, prefix: str = '') -> str:
        return mounting_utils.gcs_download_command(self.name, prefix, dest)

    @property
    def url(self) -> str:
        return f'gs://{self.name}'


@STORE_REGISTRY.register('s3')
class S3CompatibleStore(AbstractStore):
    """Any S3-compatible endpoint -- AWS, Cloudflare R2, MinIO, Ceph --
    selected by ``storage.s3.endpoint_url`` config / env (parity:
    sky/data/storage.py:1855 S3CompatibleStore; one class, many
    providers). Wire protocol implemented in data/s3.py (stdlib SigV4),
    so no aws-cli/boto3 is needed client- OR cluster-side."""

    def _client(self):
        from skypilot_tpu.data import s3 as s3_lib
        return s3_lib.S3Client(s3_lib.S3Config.load())

    def _env_prefix(self) -> str:
        """Credential/endpoint exports prepended to cluster-side commands.

        Hosts have no client config, so the client resolves the S3
        endpoint + credentials at command-GENERATION time and embeds
        them (parity: the reference rsyncs ~/.aws credentials files to
        clusters -- same trust model, command-scoped instead of a file).
        Also exports the shipped-runtime PYTHONPATH: COPY commands run
        `python3 -m skypilot_tpu.data.s3` outside a job script.
        """
        import shlex
        from skypilot_tpu.data import s3 as s3_lib
        # Best-effort: commands still generate without client creds
        # (hosts may authenticate via instance roles / their own env).
        cfg = s3_lib.S3Config.load(require_credentials=False)
        exports = [
            'PYTHONPATH="$HOME/.skyt_runtime/runtime'
            '${PYTHONPATH:+:$PYTHONPATH}"',
            f'SKYT_S3_ENDPOINT_URL={shlex.quote(cfg.endpoint_url)}',
            f'AWS_DEFAULT_REGION={shlex.quote(cfg.region)}',
        ]
        if cfg.access_key_id and cfg.secret_access_key:
            exports.append(
                f'AWS_ACCESS_KEY_ID={shlex.quote(cfg.access_key_id)}')
            exports.append('AWS_SECRET_ACCESS_KEY='
                           f'{shlex.quote(cfg.secret_access_key)}')
        return 'export ' + ' '.join(exports) + ' && '

    def exists(self) -> bool:
        return self._client().bucket_exists(self.name)

    def create(self) -> None:
        self._client().create_bucket(self.name)

    def upload(self, local_source: str, prefix: str = '') -> None:
        self._client().sync_up(local_source, self.name, prefix)

    def delete(self) -> None:
        self._client().delete_bucket(self.name)

    def mount_command(self, mount_path: str) -> str:
        return self._env_prefix() + mounting_utils.s3_mount_command(
            self.name, mount_path)

    def mount_cached_command(self, mount_path: str) -> str:
        return self._env_prefix() + mounting_utils.s3_mount_cached_command(
            self.name, mount_path)

    def download_command(self, dest: str, prefix: str = '') -> str:
        return self._env_prefix() + mounting_utils.s3_download_command(
            self.name, prefix, dest)

    @property
    def url(self) -> str:
        return f's3://{self.name}'


@STORE_REGISTRY.register('azure')
class AzureBlobStore(AbstractStore):
    """Azure Blob containers via the stdlib SharedKey client
    (data/azure_blob.py). Parity: sky/data/storage.py:144
    AzureBlobStore (az-cli/SDK there; direct wire protocol here, the
    same stance as the S3 store). Mounts ride rclone's azureblob
    backend — the one FUSE tool covering gcs/s3/azure alike."""

    def _client(self):
        from skypilot_tpu.data import azure_blob
        return azure_blob.AzureBlobClient(
            azure_blob.AzureBlobConfig.load())

    def _env_prefix(self) -> str:
        """Gen-time credential embedding (same trust model as the S3
        store: command-scoped, no credential files rsynced)."""
        import shlex
        from skypilot_tpu.data import azure_blob
        cfg = azure_blob.AzureBlobConfig.load(require_credentials=False)
        exports = [
            'PYTHONPATH="$HOME/.skyt_runtime/runtime'
            '${PYTHONPATH:+:$PYTHONPATH}"',
        ]
        if cfg.account:
            exports.append(
                f'AZURE_STORAGE_ACCOUNT={shlex.quote(cfg.account)}')
        if cfg.key:
            exports.append(f'AZURE_STORAGE_KEY={shlex.quote(cfg.key)}')
        if cfg.endpoint_url and cfg.account and \
                not cfg.endpoint_url.endswith('blob.core.windows.net'):
            exports.append('SKYT_AZURE_BLOB_ENDPOINT='
                           f'{shlex.quote(cfg.endpoint_url)}')
        return 'export ' + ' '.join(exports) + ' && '

    def exists(self) -> bool:
        return self._client().container_exists(self.name)

    def create(self) -> None:
        self._client().create_container(self.name)

    def upload(self, local_source: str, prefix: str = '') -> None:
        self._client().sync_up(local_source, self.name, prefix)

    def delete(self) -> None:
        self._client().delete_container(self.name)

    def mount_command(self, mount_path: str) -> str:
        return self._env_prefix() + mounting_utils.azure_mount_command(
            self.name, mount_path)

    def mount_cached_command(self, mount_path: str) -> str:
        return (self._env_prefix() +
                mounting_utils.azure_mount_cached_command(
                    self.name, mount_path))

    def download_command(self, dest: str, prefix: str = '') -> str:
        return (self._env_prefix() +
                mounting_utils.azure_download_command(
                    self.name, prefix, dest))

    @property
    def url(self) -> str:
        return f'az://{self.name}'


@STORE_REGISTRY.register('local')
class LocalStore(AbstractStore):
    """A directory posing as a bucket (tests/dev; pairs with the fake
    cloud whose 'hosts' run on this machine)."""

    @staticmethod
    def _root() -> str:
        return os.path.join(
            os.environ.get('SKYT_STATE_DIR', os.path.expanduser('~/.skyt')),
            'buckets')

    @property
    def bucket_dir(self) -> str:
        # file:///abs/dir sources address a directory outside the
        # bucket root; plain names live under it.
        if os.path.isabs(self.name):
            return self.name
        return os.path.join(self._root(), self.name)

    def exists(self) -> bool:
        return os.path.isdir(self.bucket_dir)

    def create(self) -> None:
        os.makedirs(self.bucket_dir, exist_ok=True)

    def upload(self, local_source: str, prefix: str = '') -> None:
        from skypilot_tpu.data import transfer_engine
        src = os.path.expanduser(local_source)
        dest = (os.path.join(self.bucket_dir, prefix) if prefix
                else self.bucket_dir)
        os.makedirs(dest, exist_ok=True)
        # Same parallel delta engine as the cloud stores: warm re-syncs
        # of an unchanged tree copy nothing. The engine only moves
        # files, so mirror empty directories first (jobs pre-create
        # e.g. logs/ dirs and expect them in the bucket).
        if os.path.isdir(src):
            for dirpath, _, _ in os.walk(src):
                rel = os.path.relpath(dirpath, src)
                os.makedirs(dest if rel == '.'
                            else os.path.join(dest, rel), exist_ok=True)
        engine = transfer_engine.TransferEngine()
        engine.sync_up(src,
                       transfer_engine.LocalFSAdapter(self.bucket_dir),
                       prefix)

    def delete(self) -> None:
        shutil.rmtree(self.bucket_dir, ignore_errors=True)

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.local_mount_command(self.bucket_dir,
                                                  mount_path)

    # A local dir needs no cache layer; cached == plain mount.
    mount_cached_command = mount_command

    def download_command(self, dest: str, prefix: str = '') -> str:
        return mounting_utils.local_download_command(self.bucket_dir,
                                                     prefix, dest)

    @property
    def url(self) -> str:
        return f'file://{self.bucket_dir}'


class Storage:
    """One storage_mounts entry: a bucket + mode + optional local
    source (ref Storage :781)."""

    def __init__(self,
                 name: Optional[str] = None,
                 *,
                 source: Optional[str] = None,
                 store: Optional[str] = None,
                 mode: str = 'MOUNT',
                 persistent: bool = True) -> None:
        if name is None and source is None:
            raise exceptions.StorageError(
                'storage mount needs a name or a source')
        if source is not None and '://' in source:
            inferred = StoreType.from_uri(source).value
            if store is not None and store != inferred:
                raise exceptions.StorageError(
                    f'source {source!r} implies store {inferred!r}, got '
                    f'{store!r}')
            store = inferred
            stripped = _strip_scheme(source)
            # A local "bucket" URI is a directory path (absolute);
            # cloud URIs lead with the bucket name.
            inferred_name = (stripped
                             if inferred == StoreType.LOCAL.value
                             else stripped.split('/')[0])
            if name is not None and name != inferred_name:
                raise exceptions.StorageError(
                    f'name {name!r} conflicts with bucket {inferred_name!r}'
                    f' from source {source!r}; drop the name.')
            name = inferred_name
            self.bucket_source = source
            self.local_source = None
        else:
            self.bucket_source = None
            self.local_source = source
        assert name is not None
        self.name = name
        try:
            self.mode = StorageMode(mode.upper())
        except ValueError as e:
            raise exceptions.StorageError(
                f'Invalid storage mode {mode!r}; expected one of '
                f'{[m.value for m in StorageMode]}') from e
        self.persistent = persistent
        try:
            store_cls = STORE_REGISTRY.get(store)
        except KeyError as e:
            raise exceptions.StorageError(str(e)) from e
        self.store: AbstractStore = store_cls(name)

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Storage':
        known = {'name', 'source', 'store', 'mode', 'persistent'}
        unknown = set(config) - known
        if unknown:
            raise exceptions.StorageError(
                f'Unknown storage fields: {sorted(unknown)}')
        return cls(config.get('name'),
                   source=config.get('source'),
                   store=config.get('store'),
                   mode=config.get('mode', 'MOUNT'),
                   persistent=config.get('persistent', True))

    def ensure_bucket(self) -> None:
        """Create the bucket if needed; upload the local source."""
        if self.bucket_source is not None:
            if not self.store.exists():
                raise exceptions.StorageError(
                    f'Source bucket {self.bucket_source} does not exist.')
        elif not self.store.exists():
            self.store.create()
        if self.local_source is not None:
            src = os.path.expanduser(self.local_source)
            if not os.path.exists(src):
                raise exceptions.StorageError(
                    f'storage source {self.local_source!r} not found')
            self.store.upload(self.local_source)

    def cluster_command(self, mount_path: str) -> str:
        """The shell command every host runs to realize this mount."""
        # A bucket_source URI may carry a sub-prefix (gs://b/sub/dir);
        # the name covers the whole path for local dir "buckets".
        prefix = ''
        if self.bucket_source is not None:
            stripped = _strip_scheme(self.bucket_source)
            prefix = stripped[len(self.name):].lstrip('/')
        if self.mode == StorageMode.COPY:
            return self.store.download_command(mount_path, prefix)
        if prefix:
            raise exceptions.StorageError(
                f'MOUNT of a bucket sub-path ({self.bucket_source}) is '
                'not supported; mount the bucket root or use COPY.')
        if self.mode == StorageMode.MOUNT:
            return self.store.mount_command(mount_path)
        return self.store.mount_cached_command(mount_path)

    def delete(self) -> None:
        if not self.persistent:
            self.store.delete()
