"""Content-addressed shard manifests: the checkpoint commit protocol.

A checkpoint directory is a bag of shard files plus ONE manifest
(:data:`MANIFEST_NAME`) listing every shard with its sha256 and size.
The manifest is the commit marker — the write protocol is:

1. write every shard, fsync each;
2. serialize the manifest, embed the sha256 of its own payload;
3. write to a tmp name in the same directory, fsync;
4. ``os.replace`` onto :data:`MANIFEST_NAME`, fsync the directory.

A save that dies anywhere before step 4 leaves either no manifest or
the previous one — the half-written checkpoint is invisible. A save
that dies DURING step 4's rename is resolved by the filesystem (rename
is atomic); a torn manifest written by a pre-rename crash of some
other path (or a corrupted disk) fails the embedded payload checksum
and reads as absent, the same torn-tail rule the r14 TSDB applies to
its segment files.

Consumers (``train/checkpoint.py`` saves, ``data/fanout.py`` peer
pulls) treat shard files as content-addressed: a shard is valid iff
its digest matches the manifest entry, so incremental restore/refresh
moves only shards whose digest changed (:func:`diff`) and a transfer
from an untrusted peer is accepted only after :func:`hash_file`
agrees (docs/weight_distribution.md).
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import log

logger = log.init_logger(__name__)

MANIFEST_NAME = 'MANIFEST.skyt.json'
FORMAT = 'skyt-ckpt-manifest-v1'
# Partial downloads / in-flight writes carry this infix; builders and
# the peer-serving endpoint both skip them.
TMP_INFIX = '.skyt-tmp'

_CHUNK = 1024 * 1024


def hash_file(path: str) -> Dict[str, Any]:
    """``{'sha256': hex, 'size': bytes}`` of one file, streamed."""
    sha = hashlib.sha256()
    size = 0
    with open(path, 'rb') as f:
        for chunk in iter(lambda: f.read(_CHUNK), b''):
            sha.update(chunk)
            size += len(chunk)
    return {'sha256': sha.hexdigest(), 'size': size}


def manifest_path(root: str) -> str:
    return os.path.join(root, MANIFEST_NAME)


def _canonical(payload: Dict[str, Any]) -> bytes:
    return json.dumps(payload, sort_keys=True,
                      separators=(',', ':')).encode()


def build(root: str, step: Optional[int] = None,
          extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Walk ``root``, hash every shard file, return the manifest
    payload (not yet committed — see :func:`write`). Shard paths are
    '/'-separated and relative to ``root``; the manifest itself and
    tmp files are excluded."""
    shards: List[Dict[str, Any]] = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            if name == MANIFEST_NAME or TMP_INFIX in name:
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root).replace(os.sep, '/')
            entry = {'path': rel}
            entry.update(hash_file(full))
            shards.append(entry)
    shards.sort(key=lambda e: e['path'])
    payload: Dict[str, Any] = {'shards': shards}
    if step is not None:
        payload['step'] = int(step)
    if extra:
        payload.update(extra)
    return payload


def write(root: str, payload: Dict[str, Any]) -> str:
    """Commit ``payload`` as ``root``'s manifest: tmp + fsync +
    atomic rename + directory fsync. Returns the manifest path."""
    doc = {
        'format': FORMAT,
        'payload': payload,
        'payload_sha256': hashlib.sha256(
            _canonical(payload)).hexdigest(),
    }
    final = manifest_path(root)
    tmp = f'{final}{TMP_INFIX}.{os.getpid()}'
    data = json.dumps(doc, sort_keys=True, indent=1).encode()
    with open(tmp, 'wb') as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    _fsync_dir(root)
    return final


def read(root: str) -> Optional[Dict[str, Any]]:
    """The committed manifest payload, or None when the directory has
    no manifest OR the manifest is torn/corrupt (unparseable, wrong
    format, or failing its embedded payload checksum). A torn
    manifest is treated exactly like an uncommitted save — ignored,
    never an error (the r14 torn-tail rule)."""
    path = manifest_path(root)
    try:
        with open(path, 'rb') as f:
            raw = f.read()
    except OSError:
        return None
    try:
        doc = json.loads(raw)
    except ValueError:
        logger.warning('torn/unparseable manifest ignored: %s', path)
        return None
    if not isinstance(doc, dict) or doc.get('format') != FORMAT:
        logger.warning('unknown manifest format ignored: %s', path)
        return None
    payload = doc.get('payload')
    if not isinstance(payload, dict):
        return None
    digest = hashlib.sha256(_canonical(payload)).hexdigest()
    if digest != doc.get('payload_sha256'):
        logger.warning('manifest payload checksum mismatch ignored: '
                       '%s', path)
        return None
    return payload


def shard_map(payload: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """``rel_path -> shard entry`` for one manifest payload."""
    return {s['path']: s for s in payload.get('shards', ())}


def diff(old: Optional[Dict[str, Any]],
         new: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Shards of ``new`` absent from ``old`` or whose digest changed —
    the incremental-refresh transfer set. ``old=None`` means a cold
    start: every shard moves."""
    if old is None:
        return list(new.get('shards', ()))
    prev = shard_map(old)
    out = []
    for shard in new.get('shards', ()):
        before = prev.get(shard['path'])
        if before is None or before['sha256'] != shard['sha256']:
            out.append(shard)
    return out


def verify(root: str,
           payload: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Shards missing on disk or failing their digest — empty list
    means ``root`` holds a verified-complete copy of the manifest."""
    bad = []
    for shard in payload.get('shards', ()):
        full = os.path.join(root, *shard['path'].split('/'))
        try:
            entry = hash_file(full)
        except OSError:
            bad.append(shard)
            continue
        if entry['sha256'] != shard['sha256'] or \
                entry['size'] != shard['size']:
            bad.append(shard)
    return bad


def _fsync_dir(path: str) -> None:
    """Durably record a rename: fsync the containing directory (a
    no-op error-swallow on filesystems that refuse O_RDONLY dir
    fds — the rename itself is still atomic)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
