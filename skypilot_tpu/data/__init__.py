"""Data & storage layer: bucket abstraction, mounting, transfer
(parity: ``sky/data/``)."""
from skypilot_tpu.data.storage import (AbstractStore, Storage, StorageMode,
                                       StoreType)

__all__ = ['AbstractStore', 'Storage', 'StorageMode', 'StoreType']
