"""Minimal S3 client: SigV4 over stdlib HTTP, endpoint-configurable.

Parity: ``sky/data/storage.py:1855 S3CompatibleStore`` -- one store class
serving every S3-compatible endpoint (AWS, Cloudflare R2, MinIO, Ceph...)
selected by config. The reference shells out to aws-cli/boto3; neither is
in this image, so the wire protocol is implemented directly: SigV4
signing is ~40 lines of hmac/sha256 and removes the dependency entirely
(same reasoning as the reference's lazy adaptors -- `import skypilot_tpu`
must not drag cloud SDKs).

Credentials/endpoint resolution order:
1. explicit ``S3Config`` arguments;
2. env: ``SKYT_S3_ENDPOINT_URL`` / ``AWS_ACCESS_KEY_ID`` /
   ``AWS_SECRET_ACCESS_KEY`` / ``AWS_DEFAULT_REGION``;
3. layered config: ``storage.s3.{endpoint_url,access_key_id,...}``.

Also a tiny CLI (``python3 -m skypilot_tpu.data.s3``) used by the
cluster-side download commands -- every host has the shipped runtime on
PYTHONPATH, so no extra tooling is needed on nodes.
"""
from __future__ import annotations

import collections
import dataclasses
import datetime
import hashlib
import hmac
import http.client
import os
import sys
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple
from xml.etree import ElementTree

from skypilot_tpu import exceptions
from skypilot_tpu.utils import env_registry


def _retry_after_seconds(status: int, headers) -> Optional[float]:
    """Parse a ``Retry-After`` header into seconds on a 429/503 answer.

    Only the numeric form is honored (HTTP-date values are rare from
    object stores and would need wall-clock math); absent or malformed
    values yield ``None`` so callers fall back to their own backoff.
    """
    if status not in (429, 503) or headers is None:
        return None
    value = headers.get('Retry-After')
    try:
        return max(0.0, float(value))
    except (TypeError, ValueError):
        return None


def _read_slice(resp, start: int, length: int) -> bytes:
    """Read ``[start, start+length)`` from a response stream without
    buffering the rest; closing the response abandons the tail."""
    to_skip = start
    while to_skip > 0:
        chunk = resp.read(min(1024 * 1024, to_skip))
        if not chunk:
            return b''
        to_skip -= len(chunk)
    out = bytearray()
    while len(out) < length:
        chunk = resp.read(min(1024 * 1024, length - len(out)))
        if not chunk:
            break
        out += chunk
    return bytes(out)


class TransferConnectionPool:
    """Bounded keep-alive connections for the transfer engine's ranged
    GETs. A 16-way parallel large-object download through ``urlopen``
    dials a fresh TCP connection per part — against a far endpoint
    that's one RTT of pure dial overhead per part, serialized with the
    body bytes. Parts of one object all hit the same (scheme, host,
    port), so a small idle pool (``SKYT_TRANSFER_POOL_SIZE``) turns N
    dials into ~pool-width dials.

    Thread-safe; connections are checked out exclusively, so the pool
    holds only IDLE connections — the bound caps idle sockets kept
    alive, not concurrency (a burst past the bound dials extra
    connections and simply doesn't keep them)."""

    def __init__(self, size: Optional[int] = None) -> None:
        self._size = size
        self._idle: Dict[Tuple[str, str, int], collections.deque] = \
            collections.defaultdict(collections.deque)
        self._lock = threading.Lock()
        self.dials = 0
        self.reuses = 0

    def _bound(self) -> int:
        if self._size is not None:
            return self._size
        return env_registry.get_int('SKYT_TRANSFER_POOL_SIZE')

    def send(self, req: urllib.request.Request, timeout: float):
        """Issue a urllib ``Request`` over a pooled connection. Returns
        ``(status, headers, resp, finish)``; the caller reads ``resp``
        and MUST call ``finish(reusable=...)`` — reusable=True returns
        the connection to the pool if the response was drained and the
        server kept the connection open. Raises OSError /
        http.client.HTTPException on transport failure (a stale pooled
        connection is retried once on a fresh dial)."""
        parsed = urllib.parse.urlparse(req.full_url)
        scheme = parsed.scheme or 'http'
        port = parsed.port or (443 if scheme == 'https' else 80)
        key = (scheme, parsed.hostname or '', port)
        selector = parsed.path or '/'
        if parsed.query:
            selector += f'?{parsed.query}'
        headers = dict(req.header_items())
        headers.pop('Connection', None)
        last_error: Optional[Exception] = None
        for attempt in (0, 1):
            conn, reused = self._acquire(key, timeout)
            try:
                conn.request(req.get_method(), selector, headers=headers)
                resp = conn.getresponse()
                break
            except (http.client.HTTPException, OSError) as e:
                conn.close()
                last_error = e
                if not (reused and attempt == 0):
                    raise
                # A keep-alive connection the server quietly closed:
                # one retry on a guaranteed-fresh dial.
        else:  # pragma: no cover - loop always breaks or raises
            raise last_error  # type: ignore[misc]

        def finish(reusable: bool) -> None:
            if (reusable and not resp.will_close and resp.isclosed()
                    and self._release(key, conn)):
                return
            conn.close()

        return resp.status, resp.headers, resp, finish

    def _acquire(self, key, timeout: float):
        with self._lock:
            idle = self._idle[key]
            if idle:
                self.reuses += 1
                return idle.popleft(), True
            self.dials += 1
        scheme, host, port = key
        if scheme == 'https':
            conn = http.client.HTTPSConnection(host, port, timeout=timeout)
        else:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
        return conn, False

    def _release(self, key, conn) -> bool:
        with self._lock:
            idle = self._idle[key]
            if len(idle) < self._bound():
                idle.append(conn)
                return True
        return False

    def close(self) -> None:
        with self._lock:
            for idle in self._idle.values():
                while idle:
                    idle.popleft().close()


# One process-wide pool: parallel part downloads across all transfer
# threads share the same idle sockets (the bound is global by design).
_RANGE_POOL = TransferConnectionPool()


@dataclasses.dataclass
class S3Config:
    endpoint_url: str
    access_key_id: str
    secret_access_key: str
    region: str = 'us-east-1'

    @classmethod
    def load(cls,
             endpoint_url: Optional[str] = None,
             access_key_id: Optional[str] = None,
             secret_access_key: Optional[str] = None,
             region: Optional[str] = None,
             require_credentials: bool = True) -> 'S3Config':
        from skypilot_tpu import config as config_lib

        def pick(explicit, env_key, cfg_key, default=None):
            if explicit:
                return explicit
            if os.environ.get(env_key):
                return os.environ[env_key]
            return config_lib.get_nested(('storage', 's3', cfg_key),
                                         default)

        endpoint = pick(endpoint_url, 'SKYT_S3_ENDPOINT_URL',
                        'endpoint_url', 'https://s3.amazonaws.com')
        key = pick(access_key_id, 'AWS_ACCESS_KEY_ID', 'access_key_id')
        secret = pick(secret_access_key, 'AWS_SECRET_ACCESS_KEY',
                      'secret_access_key')
        reg = pick(region, 'AWS_DEFAULT_REGION', 'region', 'us-east-1')
        if (not key or not secret) and require_credentials:
            raise exceptions.StorageError(
                'S3-compatible store needs credentials: set '
                'AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY or '
                'storage.s3.access_key_id/secret_access_key in config.')
        return cls(endpoint_url=endpoint.rstrip('/'),
                   access_key_id=key or '',
                   secret_access_key=secret or '', region=reg)


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class S3Client:
    """Path-style S3 REST client with SigV4 request signing."""

    def __init__(self, cfg: S3Config) -> None:
        self.cfg = cfg

    # -- SigV4 ---------------------------------------------------------

    def _signed_request(self, method: str, bucket: str, key: str = '',
                        query: Optional[Dict[str, str]] = None,
                        body: bytes = b'',
                        unsigned_headers: Optional[Dict[str, str]] = None,
                        body_stream=None,
                        content_length: Optional[int] = None,
                        payload_sha256: Optional[str] = None
                        ) -> urllib.request.Request:
        """Build a SigV4-signed request.

        ``body`` is hashed and sent as usual; alternatively pass
        ``body_stream`` (a file-like object) with ``content_length`` and
        a precomputed ``payload_sha256`` to stream a large payload in
        chunks instead of buffering it (constant memory — the hash pass
        reads the file once, the send pass streams it). Headers in
        ``unsigned_headers`` (e.g. ``Range``) ride outside the
        signature, which SigV4 permits for anything not listed in
        SignedHeaders."""
        cfg = self.cfg
        parsed = urllib.parse.urlparse(cfg.endpoint_url)
        host = parsed.netloc
        path = f'/{bucket}' + (f'/{urllib.parse.quote(key)}' if key else '')
        if parsed.path and parsed.path != '/':
            path = parsed.path.rstrip('/') + path
        query = dict(sorted((query or {}).items()))
        # SigV4 canonicalizes with %20 (quote), never '+' (quote_plus) --
        # a space in a prefix would otherwise SignatureDoesNotMatch.
        canonical_query = urllib.parse.urlencode(
            query, quote_via=urllib.parse.quote)
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime('%Y%m%dT%H%M%SZ')
        datestamp = now.strftime('%Y%m%d')
        payload_hash = payload_sha256 or hashlib.sha256(body).hexdigest()
        headers = {
            'host': host,
            'x-amz-content-sha256': payload_hash,
            'x-amz-date': amz_date,
        }
        signed_headers = ';'.join(sorted(headers))
        canonical_headers = ''.join(
            f'{k}:{headers[k]}\n' for k in sorted(headers))
        canonical_request = '\n'.join([
            method, path, canonical_query, canonical_headers,
            signed_headers, payload_hash,
        ])
        scope = f'{datestamp}/{cfg.region}/s3/aws4_request'
        string_to_sign = '\n'.join([
            'AWS4-HMAC-SHA256', amz_date, scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ])
        k_date = _sign(f'AWS4{cfg.secret_access_key}'.encode(), datestamp)
        k_region = _sign(k_date, cfg.region)
        k_service = _sign(k_region, 's3')
        k_signing = _sign(k_service, 'aws4_request')
        signature = hmac.new(k_signing, string_to_sign.encode(),
                             hashlib.sha256).hexdigest()
        auth = (f'AWS4-HMAC-SHA256 '
                f'Credential={cfg.access_key_id}/{scope}, '
                f'SignedHeaders={signed_headers}, Signature={signature}')
        url = f'{parsed.scheme}://{host}{path}'
        if canonical_query:
            url += f'?{canonical_query}'
        data = body_stream if body_stream is not None else (body or None)
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header('Authorization', auth)
        for k, v in headers.items():
            if k != 'host':
                req.add_header(k, v)
        if content_length is not None:
            req.add_header('Content-Length', str(content_length))
        for k, v in (unsigned_headers or {}).items():
            req.add_header(k, v)
        return req

    def _send(self, req: urllib.request.Request,
              timeout: float = 120):
        """Returns (status, headers, body); HTTP errors are returned,
        not raised (callers decide which codes are acceptable)."""
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, resp.headers, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.headers, e.read()
        except urllib.error.URLError as e:
            raise exceptions.StorageError(
                f'S3 endpoint {self.cfg.endpoint_url} unreachable: '
                f'{e.reason}') from e

    def _call(self, method: str, bucket: str, key: str = '',
              query: Optional[Dict[str, str]] = None,
              body: bytes = b''
              ) -> Tuple[int, bytes, Optional[float]]:
        """Returns (status, body, retry_after): the third element is
        the parsed Retry-After on a 429/503 answer (None otherwise)
        so raise sites can hand server backpressure to retry loops."""
        status, headers, payload = self._send(
            self._signed_request(method, bucket, key, query, body))
        return status, payload, _retry_after_seconds(status, headers)

    # -- operations ----------------------------------------------------

    def bucket_exists(self, bucket: str) -> bool:
        code, _, _ = self._call('HEAD', bucket)
        return code == 200

    def create_bucket(self, bucket: str) -> None:
        code, body, retry_after = self._call('PUT', bucket)
        if code not in (200, 204) and b'BucketAlreadyOwnedByYou' not in body:
            raise exceptions.StorageError(
                f'create bucket {bucket}: HTTP {code} {body[:300]!r}',
                http_status=code, retry_after=retry_after)

    def put_object(self, bucket: str, key: str, data: bytes) -> None:
        code, body, retry_after = self._call('PUT', bucket, key,
                                             body=data)
        if code not in (200, 204):
            raise exceptions.StorageError(
                f'put {bucket}/{key}: HTTP {code} {body[:300]!r}',
                http_status=code, retry_after=retry_after)

    def get_object(self, bucket: str, key: str) -> bytes:
        code, body, retry_after = self._call('GET', bucket, key)
        if code != 200:
            raise exceptions.StorageError(
                f'get {bucket}/{key}: HTTP {code} {body[:300]!r}',
                http_status=code, retry_after=retry_after)
        return body

    def get_object_to_file(self, bucket: str, key: str,
                           path: str) -> str:
        """Stream an object to ``path`` in chunks (constant memory);
        returns the md5 hex of the content."""
        req = self._signed_request('GET', bucket, key)
        md5 = hashlib.md5()
        try:
            with urllib.request.urlopen(req, timeout=300) as resp, \
                    open(path, 'wb') as f:
                while True:
                    chunk = resp.read(1024 * 1024)
                    if not chunk:
                        break
                    md5.update(chunk)
                    f.write(chunk)
            return md5.hexdigest()
        except urllib.error.HTTPError as e:
            raise exceptions.StorageError(
                f'get {bucket}/{key}: HTTP {e.code}',
                http_status=e.code,
                retry_after=_retry_after_seconds(e.code, e.headers)
            ) from None
        except urllib.error.URLError as e:
            raise exceptions.StorageError(
                f'S3 endpoint {self.cfg.endpoint_url} unreachable: '
                f'{e.reason}') from e

    def get_object_range(self, bucket: str, key: str, start: int,
                         length: int) -> bytes:
        """Ranged GET of ``length`` bytes at ``start`` (parallel large-
        object downloads fetch disjoint ranges concurrently). Goes
        through the process-wide keep-alive pool: the N parts of one
        object hit the same endpoint, so re-dialing per part would pay
        one RTT of connection setup each (``SKYT_TRANSFER_POOL_SIZE``
        bounds the idle sockets kept between parts)."""
        end = start + length - 1
        req = self._signed_request(
            'GET', bucket, key,
            unsigned_headers={'Range': f'bytes={start}-{end}'})
        try:
            status, headers, resp, finish = _RANGE_POOL.send(
                req, timeout=300)
        except (http.client.HTTPException, OSError) as e:
            raise exceptions.StorageError(
                f'S3 endpoint {self.cfg.endpoint_url} unreachable: '
                f'{e}') from e
        try:
            if status == 206:
                body = resp.read()
                finish(reusable=True)
                return body
            if status == 200:
                # Endpoint ignored Range (some S3 compats do): stream
                # to the slice and close — never buffer the whole
                # object per part request (the undrained tail also
                # makes the connection unpoolable: finish() closes it).
                body = _read_slice(resp, start, length)
                finish(reusable=False)
                return body
            error_body = resp.read()
            finish(reusable=True)
        except (http.client.HTTPException, OSError) as e:
            finish(reusable=False)
            raise exceptions.StorageError(
                f'ranged get {bucket}/{key} [{start}-{end}]: '
                f'{e}') from e
        raise exceptions.StorageError(
            f'ranged get {bucket}/{key} [{start}-{end}]: HTTP '
            f'{status} {error_body[:300]!r}', http_status=status,
            retry_after=_retry_after_seconds(status, headers))

    def put_object_from_file(self, bucket: str, key: str,
                             path: str) -> str:
        """Streamed single-request PUT: one hash pass (SigV4 payload
        sha256) then a chunked send — the file is never held in memory.
        Returns the object ETag the endpoint reported ('' if none)."""
        size = os.path.getsize(path)
        sha = hashlib.sha256()
        with open(path, 'rb') as f:
            for chunk in iter(lambda: f.read(1024 * 1024), b''):
                sha.update(chunk)
        with open(path, 'rb') as f:
            req = self._signed_request(
                'PUT', bucket, key, body_stream=f, content_length=size,
                payload_sha256=sha.hexdigest())
            status, headers, body = self._send(req, timeout=300)
        if status not in (200, 204):
            raise exceptions.StorageError(
                f'put {bucket}/{key}: HTTP {status} {body[:300]!r}',
                http_status=status,
                retry_after=_retry_after_seconds(status, headers))
        return (headers.get('ETag') or '').strip('"')

    # -- multipart upload ----------------------------------------------

    def create_multipart_upload(self, bucket: str, key: str) -> str:
        code, body, retry_after = self._call('POST', bucket, key,
                                             query={'uploads': ''})
        if code != 200:
            raise exceptions.StorageError(
                f'initiate multipart {bucket}/{key}: HTTP {code} '
                f'{body[:300]!r}', http_status=code,
                retry_after=retry_after)
        root = ElementTree.fromstring(body)
        ns = root.tag.split('}')[0] + '}' if root.tag.startswith('{') \
            else ''
        el = root.find(f'{ns}UploadId')
        if el is None or not el.text:
            raise exceptions.StorageError(
                f'initiate multipart {bucket}/{key}: no UploadId in '
                f'{body[:300]!r}')
        return el.text

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_number: int, data: bytes) -> str:
        req = self._signed_request(
            'PUT', bucket, key,
            query={'partNumber': str(part_number),
                   'uploadId': upload_id}, body=data)
        status, headers, body = self._send(req, timeout=300)
        if status not in (200, 204):
            raise exceptions.StorageError(
                f'upload part {part_number} of {bucket}/{key}: HTTP '
                f'{status} {body[:300]!r}', http_status=status,
                retry_after=_retry_after_seconds(status, headers))
        etag = (headers.get('ETag') or '').strip('"')
        return etag or hashlib.md5(data).hexdigest()

    def complete_multipart_upload(self, bucket: str, key: str,
                                  upload_id: str,
                                  parts: List[Tuple[int, str]]) -> str:
        """``parts`` is [(part_number, etag)]; returns the final ETag."""
        manifest = '<CompleteMultipartUpload>' + ''.join(
            f'<Part><PartNumber>{n}</PartNumber><ETag>"{etag}"</ETag>'
            f'</Part>' for n, etag in sorted(parts)) + \
            '</CompleteMultipartUpload>'
        code, body, retry_after = self._call(
            'POST', bucket, key, query={'uploadId': upload_id},
            body=manifest.encode())
        if code != 200:
            raise exceptions.StorageError(
                f'complete multipart {bucket}/{key}: HTTP {code} '
                f'{body[:300]!r}', http_status=code,
                retry_after=retry_after)
        root = ElementTree.fromstring(body)
        # S3 can answer CompleteMultipartUpload with HTTP 200 whose body
        # is an <Error> document (e.g. InternalError after its internal
        # retry window) — 200 alone does not mean the object assembled.
        if root.tag.endswith('Error'):
            raise exceptions.StorageError(
                f'complete multipart {bucket}/{key}: HTTP 200 with '
                f'error body {body[:300]!r}')
        ns = root.tag.split('}')[0] + '}' if root.tag.startswith('{') \
            else ''
        el = root.find(f'{ns}ETag')
        return (el.text or '').strip('"') if el is not None else ''

    def abort_multipart_upload(self, bucket: str, key: str,
                               upload_id: str) -> None:
        """Best-effort AbortMultipartUpload so a failed upload does not
        leave billed orphan parts behind."""
        self._call('DELETE', bucket, key, query={'uploadId': upload_id})

    # -- listing -------------------------------------------------------

    def list_objects_meta(self, bucket: str, prefix: str = ''
                          ) -> Iterator[Tuple[str, int, str]]:
        """Yield (key, size, etag) under prefix (ListObjectsV2,
        paginated). ``etag`` keeps its raw quoting; size is -1 when the
        endpoint omits it."""
        token: Optional[str] = None
        while True:
            query = {'list-type': '2'}
            if prefix:
                query['prefix'] = prefix
            if token:
                query['continuation-token'] = token
            code, body, retry_after = self._call('GET', bucket,
                                                 query=query)
            if code != 200:
                raise exceptions.StorageError(
                    f'list {bucket}/{prefix}: HTTP {code} '
                    f'{body[:300]!r}', http_status=code,
                    retry_after=retry_after)
            root = ElementTree.fromstring(body)
            ns = ''
            if root.tag.startswith('{'):
                ns = root.tag.split('}')[0] + '}'
            for el in root.findall(f'{ns}Contents'):
                key_el = el.find(f'{ns}Key')
                if key_el is None or not key_el.text:
                    continue
                size_el = el.find(f'{ns}Size')
                etag_el = el.find(f'{ns}ETag')
                try:
                    size = int(size_el.text) if size_el is not None \
                        and size_el.text else -1
                except ValueError:
                    size = -1
                yield (key_el.text, size,
                       (etag_el.text or '') if etag_el is not None
                       else '')
            truncated = root.find(f'{ns}IsTruncated')
            if truncated is None or truncated.text != 'true':
                return
            token_el = root.find(f'{ns}NextContinuationToken')
            token = token_el.text if token_el is not None else None
            if not token:
                return

    def list_objects(self, bucket: str,
                     prefix: str = '') -> Iterator[str]:
        """Yield keys under prefix (ListObjectsV2, paginated)."""
        for key, _, _ in self.list_objects_meta(bucket, prefix):
            yield key

    def delete_object(self, bucket: str, key: str) -> None:
        self._call('DELETE', bucket, key)

    def delete_prefix(self, bucket: str, prefix: str = '') -> None:
        for key in list(self.list_objects(bucket, prefix)):
            self.delete_object(bucket, key)

    def delete_bucket(self, bucket: str) -> None:
        self.delete_prefix(bucket)
        self._call('DELETE', bucket)

    # -- directory sync (parallel delta-aware engine) ------------------

    def sync_up(self, local_dir: str, bucket: str, prefix: str = '') -> int:
        """Upload a file or directory tree; returns object count
        (transferred + delta-skipped)."""
        from skypilot_tpu.data import transfer_engine
        engine = transfer_engine.TransferEngine()
        return engine.sync_up(
            local_dir, transfer_engine.S3Adapter(self, bucket),
            prefix).count

    def sync_down(self, bucket: str, prefix: str, dest: str) -> int:
        """Download all objects under prefix into dest; returns count
        (transferred + delta-skipped). Writes are atomic (same-dir .tmp
        + rename) and keys may not escape ``dest``."""
        from skypilot_tpu.data import transfer_engine
        engine = transfer_engine.TransferEngine()
        return engine.sync_down(
            transfer_engine.S3Adapter(self, bucket), prefix, dest).count


def main(argv: Optional[List[str]] = None) -> int:
    """CLI used by cluster-side COPY commands (runtime is shipped, so
    every host can run `python3 -m skypilot_tpu.data.s3 ...`)."""
    import argparse
    parser = argparse.ArgumentParser('skyt-s3')
    sub = parser.add_subparsers(dest='cmd', required=True)
    down = sub.add_parser('sync-down')
    down.add_argument('bucket')
    down.add_argument('prefix')
    down.add_argument('dest')
    up = sub.add_parser('sync-up')
    up.add_argument('source')
    up.add_argument('bucket')
    up.add_argument('--prefix', default='')
    args = parser.parse_args(argv)
    client = S3Client(S3Config.load())
    if args.cmd == 'sync-down':
        n = client.sync_down(args.bucket, args.prefix, args.dest)
        print(f'downloaded {n} objects')
    else:
        n = client.sync_up(args.source, args.bucket, args.prefix)
        print(f'uploaded {n} objects')
    return 0


if __name__ == '__main__':
    sys.exit(main())
