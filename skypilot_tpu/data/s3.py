"""Minimal S3 client: SigV4 over stdlib HTTP, endpoint-configurable.

Parity: ``sky/data/storage.py:1855 S3CompatibleStore`` -- one store class
serving every S3-compatible endpoint (AWS, Cloudflare R2, MinIO, Ceph...)
selected by config. The reference shells out to aws-cli/boto3; neither is
in this image, so the wire protocol is implemented directly: SigV4
signing is ~40 lines of hmac/sha256 and removes the dependency entirely
(same reasoning as the reference's lazy adaptors -- `import skypilot_tpu`
must not drag cloud SDKs).

Credentials/endpoint resolution order:
1. explicit ``S3Config`` arguments;
2. env: ``SKYT_S3_ENDPOINT_URL`` / ``AWS_ACCESS_KEY_ID`` /
   ``AWS_SECRET_ACCESS_KEY`` / ``AWS_DEFAULT_REGION``;
3. layered config: ``storage.s3.{endpoint_url,access_key_id,...}``.

Also a tiny CLI (``python3 -m skypilot_tpu.data.s3``) used by the
cluster-side download commands -- every host has the shipped runtime on
PYTHONPATH, so no extra tooling is needed on nodes.
"""
from __future__ import annotations

import dataclasses
import datetime
import hashlib
import hmac
import os
import sys
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple
from xml.etree import ElementTree

from skypilot_tpu import exceptions


@dataclasses.dataclass
class S3Config:
    endpoint_url: str
    access_key_id: str
    secret_access_key: str
    region: str = 'us-east-1'

    @classmethod
    def load(cls,
             endpoint_url: Optional[str] = None,
             access_key_id: Optional[str] = None,
             secret_access_key: Optional[str] = None,
             region: Optional[str] = None,
             require_credentials: bool = True) -> 'S3Config':
        from skypilot_tpu import config as config_lib

        def pick(explicit, env_key, cfg_key, default=None):
            if explicit:
                return explicit
            if os.environ.get(env_key):
                return os.environ[env_key]
            return config_lib.get_nested(('storage', 's3', cfg_key),
                                         default)

        endpoint = pick(endpoint_url, 'SKYT_S3_ENDPOINT_URL',
                        'endpoint_url', 'https://s3.amazonaws.com')
        key = pick(access_key_id, 'AWS_ACCESS_KEY_ID', 'access_key_id')
        secret = pick(secret_access_key, 'AWS_SECRET_ACCESS_KEY',
                      'secret_access_key')
        reg = pick(region, 'AWS_DEFAULT_REGION', 'region', 'us-east-1')
        if (not key or not secret) and require_credentials:
            raise exceptions.StorageError(
                'S3-compatible store needs credentials: set '
                'AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY or '
                'storage.s3.access_key_id/secret_access_key in config.')
        return cls(endpoint_url=endpoint.rstrip('/'),
                   access_key_id=key or '',
                   secret_access_key=secret or '', region=reg)


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class S3Client:
    """Path-style S3 REST client with SigV4 request signing."""

    def __init__(self, cfg: S3Config) -> None:
        self.cfg = cfg

    # -- SigV4 ---------------------------------------------------------

    def _signed_request(self, method: str, bucket: str, key: str = '',
                        query: Optional[Dict[str, str]] = None,
                        body: bytes = b'') -> urllib.request.Request:
        cfg = self.cfg
        parsed = urllib.parse.urlparse(cfg.endpoint_url)
        host = parsed.netloc
        path = f'/{bucket}' + (f'/{urllib.parse.quote(key)}' if key else '')
        if parsed.path and parsed.path != '/':
            path = parsed.path.rstrip('/') + path
        query = dict(sorted((query or {}).items()))
        # SigV4 canonicalizes with %20 (quote), never '+' (quote_plus) --
        # a space in a prefix would otherwise SignatureDoesNotMatch.
        canonical_query = urllib.parse.urlencode(
            query, quote_via=urllib.parse.quote)
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime('%Y%m%dT%H%M%SZ')
        datestamp = now.strftime('%Y%m%d')
        payload_hash = hashlib.sha256(body).hexdigest()
        headers = {
            'host': host,
            'x-amz-content-sha256': payload_hash,
            'x-amz-date': amz_date,
        }
        signed_headers = ';'.join(sorted(headers))
        canonical_headers = ''.join(
            f'{k}:{headers[k]}\n' for k in sorted(headers))
        canonical_request = '\n'.join([
            method, path, canonical_query, canonical_headers,
            signed_headers, payload_hash,
        ])
        scope = f'{datestamp}/{cfg.region}/s3/aws4_request'
        string_to_sign = '\n'.join([
            'AWS4-HMAC-SHA256', amz_date, scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ])
        k_date = _sign(f'AWS4{cfg.secret_access_key}'.encode(), datestamp)
        k_region = _sign(k_date, cfg.region)
        k_service = _sign(k_region, 's3')
        k_signing = _sign(k_service, 'aws4_request')
        signature = hmac.new(k_signing, string_to_sign.encode(),
                             hashlib.sha256).hexdigest()
        auth = (f'AWS4-HMAC-SHA256 '
                f'Credential={cfg.access_key_id}/{scope}, '
                f'SignedHeaders={signed_headers}, Signature={signature}')
        url = f'{parsed.scheme}://{host}{path}'
        if canonical_query:
            url += f'?{canonical_query}'
        req = urllib.request.Request(url, data=body or None, method=method)
        req.add_header('Authorization', auth)
        for k, v in headers.items():
            if k != 'host':
                req.add_header(k, v)
        return req

    def _call(self, method: str, bucket: str, key: str = '',
              query: Optional[Dict[str, str]] = None,
              body: bytes = b'') -> Tuple[int, bytes]:
        """Returns (status, body); HTTP errors are returned, not raised
        (callers decide which codes are acceptable per operation)."""
        req = self._signed_request(method, bucket, key, query, body)
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except urllib.error.URLError as e:
            raise exceptions.StorageError(
                f'S3 endpoint {self.cfg.endpoint_url} unreachable: '
                f'{e.reason}') from e

    # -- operations ----------------------------------------------------

    def bucket_exists(self, bucket: str) -> bool:
        code, _ = self._call('HEAD', bucket)
        return code == 200

    def create_bucket(self, bucket: str) -> None:
        code, body = self._call('PUT', bucket)
        if code not in (200, 204) and b'BucketAlreadyOwnedByYou' not in body:
            raise exceptions.StorageError(
                f'create bucket {bucket}: HTTP {code} {body[:300]!r}')

    def put_object(self, bucket: str, key: str, data: bytes) -> None:
        code, body = self._call('PUT', bucket, key, body=data)
        if code not in (200, 204):
            raise exceptions.StorageError(
                f'put {bucket}/{key}: HTTP {code} {body[:300]!r}')

    def get_object(self, bucket: str, key: str) -> bytes:
        code, body = self._call('GET', bucket, key)
        if code != 200:
            raise exceptions.StorageError(
                f'get {bucket}/{key}: HTTP {code} {body[:300]!r}')
        return body

    def list_objects(self, bucket: str,
                     prefix: str = '') -> Iterator[str]:
        """Yield keys under prefix (ListObjectsV2, paginated)."""
        token: Optional[str] = None
        while True:
            query = {'list-type': '2'}
            if prefix:
                query['prefix'] = prefix
            if token:
                query['continuation-token'] = token
            code, body = self._call('GET', bucket, query=query)
            if code != 200:
                raise exceptions.StorageError(
                    f'list {bucket}/{prefix}: HTTP {code} {body[:300]!r}')
            root = ElementTree.fromstring(body)
            ns = ''
            if root.tag.startswith('{'):
                ns = root.tag.split('}')[0] + '}'
            for el in root.findall(f'{ns}Contents'):
                key_el = el.find(f'{ns}Key')
                if key_el is not None and key_el.text:
                    yield key_el.text
            truncated = root.find(f'{ns}IsTruncated')
            if truncated is None or truncated.text != 'true':
                return
            token_el = root.find(f'{ns}NextContinuationToken')
            token = token_el.text if token_el is not None else None
            if not token:
                return

    def delete_object(self, bucket: str, key: str) -> None:
        self._call('DELETE', bucket, key)

    def delete_prefix(self, bucket: str, prefix: str = '') -> None:
        for key in list(self.list_objects(bucket, prefix)):
            self.delete_object(bucket, key)

    def delete_bucket(self, bucket: str) -> None:
        self.delete_prefix(bucket)
        self._call('DELETE', bucket)

    # -- directory sync ------------------------------------------------

    def sync_up(self, local_dir: str, bucket: str, prefix: str = '') -> int:
        """Upload a file or directory tree; returns object count."""
        local_dir = os.path.expanduser(local_dir)
        count = 0
        if os.path.isfile(local_dir):
            with open(local_dir, 'rb') as f:
                key = os.path.join(prefix, os.path.basename(local_dir)) \
                    if prefix else os.path.basename(local_dir)
                self.put_object(bucket, key, f.read())
            return 1
        for dirpath, _, filenames in os.walk(local_dir):
            for filename in filenames:
                path = os.path.join(dirpath, filename)
                rel = os.path.relpath(path, local_dir)
                key = os.path.join(prefix, rel) if prefix else rel
                with open(path, 'rb') as f:
                    self.put_object(bucket, key.replace(os.sep, '/'),
                                    f.read())
                count += 1
        return count

    def sync_down(self, bucket: str, prefix: str, dest: str) -> int:
        """Download all objects under prefix into dest; returns count."""
        dest = os.path.expanduser(dest)
        count = 0
        for key in self.list_objects(bucket, prefix):
            rel = key[len(prefix):].lstrip('/') if prefix else key
            target = os.path.join(dest, rel)
            os.makedirs(os.path.dirname(target) or dest, exist_ok=True)
            with open(target, 'wb') as f:
                f.write(self.get_object(bucket, key))
            count += 1
        return count


def main(argv: Optional[List[str]] = None) -> int:
    """CLI used by cluster-side COPY commands (runtime is shipped, so
    every host can run `python3 -m skypilot_tpu.data.s3 ...`)."""
    import argparse
    parser = argparse.ArgumentParser('skyt-s3')
    sub = parser.add_subparsers(dest='cmd', required=True)
    down = sub.add_parser('sync-down')
    down.add_argument('bucket')
    down.add_argument('prefix')
    down.add_argument('dest')
    up = sub.add_parser('sync-up')
    up.add_argument('source')
    up.add_argument('bucket')
    up.add_argument('--prefix', default='')
    args = parser.parse_args(argv)
    client = S3Client(S3Config.load())
    if args.cmd == 'sync-down':
        n = client.sync_down(args.bucket, args.prefix, args.dest)
        print(f'downloaded {n} objects')
    else:
        n = client.sync_up(args.source, args.bucket, args.prefix)
        print(f'uploaded {n} objects')
    return 0


if __name__ == '__main__':
    sys.exit(main())
