"""Self-healing peer fan-out: fleet weight distribution off one bucket.

N cold replicas pulling full weights from the object store is an N×
egress convoy (ROADMAP item 2). This module replaces it with a
binary-tree rendezvous: the controller hands each NEW replica a peer
plan — its ancestor chain in a k-ary tree laid over the fleet's
READY-order — and the replica pulls content-addressed shards
(``data/ckpt_manifest.py``) from its parent over ranged HTTP GETs,
falling back up the chain (parent → grandparent → … → bucket) on peer
death, timeout, or digest mismatch. The design is robustness-first:

* **Every transfer is digest-verified.** A shard is accepted only
  when its sha256 matches the manifest; a peer that serves corrupt
  bytes is reported and quarantined fleet-wide via a
  ``serve_state`` column so one flipped bit can never fan out.
* **Every peer is replaceable mid-stream.** Partial shards land in a
  deterministic ``.skyt-tmp`` file, so a re-parented (or preempted
  and relaunched) puller resumes from the byte offset it reached —
  the new source serves the remainder via a Range request.
* **The bucket is convoy-controlled.** Direct bucket reads require a
  lease; the bound is O(log N) (:func:`bucket_lease_bound`), so a
  1k-replica mass cold start costs the origin ~10 concurrent
  readers, not 1000. Leases carry a TTL so a puller that dies
  holding one cannot wedge the fleet.
* **The manifest commits last.** A puller's destination directory
  becomes valid only when the manifest lands (tmp + atomic rename),
  the same crash-consistency rule checkpoint saves follow — a
  preempted replica restarts with either a committed copy or
  resumable partial shards, never a silently-incomplete one.

Chaos sites: ``data.fanout.peer_get`` (peer fetch: dies / hangs /
serves corrupt bytes) and ``data.fanout.lease`` (lease acquisition).
Protocol details and the failure matrix: docs/weight_distribution.md.
"""
from __future__ import annotations

import hashlib
import http.server
import json
import math
import os
import threading
import time
import urllib.error
import urllib.request
from typing import (Any, Callable, Dict, Iterable, Iterator, List,
                    Optional, Tuple)

from skypilot_tpu.data import ckpt_manifest
from skypilot_tpu.utils import env_registry, fault_injection, log
from skypilot_tpu.utils import resilience

logger = log.init_logger(__name__)

PEER_GET_SITE = 'data.fanout.peer_get'
LEASE_SITE = 'data.fanout.lease'

# Payload envs the controller injects into a replica task (declared in
# env_registry; replica_managers.py builds the values).
PEERS_ENV = 'SKYT_FANOUT_PEERS'
DIR_ENV = 'SKYT_FANOUT_DIR'

_CHUNK = 256 * 1024


# -- topology (pure: shared with the simulator) ------------------------


def bucket_lease_bound(n_replicas: int, configured: int = 0) -> int:
    """Concurrent bucket readers allowed for a fleet of ``n``:
    the configured override, else ``ceil(log2(n+1))`` — the depth of
    the fan-out tree, so origin load grows with the tree's height,
    not its width."""
    if configured > 0:
        return int(configured)
    return max(1, int(math.ceil(math.log2(max(1, n_replicas) + 1))))


def tree_parent(position: int, arity: int = 2) -> Optional[int]:
    """Parent index of ``position`` in the canonical k-ary heap
    layout over the fleet join order (position 0 has no parent — it
    pulls from the bucket)."""
    if position <= 0:
        return None
    return (position - 1) // max(1, arity)


def tree_ancestors(position: int, arity: int = 2) -> List[int]:
    """Ancestor chain of ``position``, parent first — the heal order
    a puller walks before falling back to the bucket."""
    out: List[int] = []
    node = position
    while True:
        parent = tree_parent(node, arity)
        if parent is None:
            return out
        out.append(parent)
        node = parent


# -- controller-side planning ------------------------------------------


def plan_for_new_replica(service_name: str, replica_id: int,
                         arity: Optional[int] = None
                         ) -> Dict[str, Any]:
    """The peer plan the controller hands a newly-launching replica:
    its ancestor chain over the current READY, non-quarantined fleet
    (endpoint-bearing replicas, join order = ready_at then id). The
    chain may be empty — the replica then pulls from the bucket
    under a lease bounded by ``lease_bound`` (the O(log N) default,
    unless SKYT_FANOUT_BUCKET_LEASES pins a fixed bound)."""
    from skypilot_tpu.serve import serve_state
    if arity is None:
        arity = env_registry.get_int('SKYT_FANOUT_DEGREE', minimum=1)
    ready = [
        r for r in serve_state.list_replicas(service_name)
        if r.status == serve_state.ReplicaStatus.READY and r.endpoint
        and not getattr(r, 'fanout_quarantined', False)
    ]
    ready.sort(key=lambda r: (r.ready_at or 0.0, r.replica_id))
    position = len(ready)
    peers = [{'replica_id': ready[i].replica_id,
              'endpoint': ready[i].endpoint}
             for i in tree_ancestors(position, arity)]
    return {'service': service_name, 'replica_id': replica_id,
            'position': position, 'arity': arity, 'peers': peers,
            'lease_bound': bucket_lease_bound(
                position + 1,
                env_registry.get_int('SKYT_FANOUT_BUCKET_LEASES'))}


def quarantine_peer(service_name: str, replica_id: int,
                    reason: str) -> None:
    """Fleet-wide quarantine of a corrupt-serving peer: flips the
    serve_state column (future plans exclude it) and counts the
    event. Idempotent."""
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.server import metrics
    serve_state.set_fanout_quarantined(service_name, replica_id, True)
    metrics.FANOUT_QUARANTINES.inc(service=service_name)
    logger.error('fanout: replica %d of %s quarantined (%s) — '
                 'excluded from every future peer plan',
                 replica_id, service_name, reason)


# -- leases ------------------------------------------------------------


class LeaseManager:
    """In-process bucket-read leases: at most ``bound`` concurrent
    holders, each lease expiring ``ttl`` seconds after acquisition so
    a holder that dies mid-pull frees its slot. The serve path uses
    the DB-backed twin (``serve_state.try_acquire_fanout_lease``)
    with identical semantics; this one backs tests, benches, and
    single-process restores."""

    def __init__(self, bound: int, ttl: float = 120.0,
                 clock: Optional[Callable[[], float]] = None) -> None:
        if clock is None:
            clock = time.monotonic
        self._clock = clock
        self.bound = max(1, int(bound))
        self.ttl = float(ttl)
        self._held: Dict[str, float] = {}
        self._lock = threading.Lock()
        self.max_active = 0

    def _expire(self, now: float) -> None:
        dead = [h for h, t in self._held.items()
                if now - t > self.ttl]
        for holder in dead:
            del self._held[holder]
            logger.warning('fanout lease of %s expired after %.0fs',
                           holder, self.ttl)

    def try_acquire(self, holder: str) -> bool:
        fault_injection.inject(LEASE_SITE)
        now = self._clock()
        with self._lock:
            self._expire(now)
            if holder in self._held:
                self._held[holder] = now
                return True
            if len(self._held) >= self.bound:
                return False
            self._held[holder] = now
            self.max_active = max(self.max_active, len(self._held))
            return True

    def release(self, holder: str) -> None:
        with self._lock:
            self._held.pop(holder, None)

    def active(self) -> int:
        with self._lock:
            self._expire(self._clock())
            return len(self._held)


# -- transfer sources --------------------------------------------------


class PeerUnavailable(Exception):
    """Peer dead / timed out / refusing — heal to the next source."""


class ShardCorrupt(Exception):
    """Digest mismatch on bytes served whole by one source — the
    quarantine trigger."""


class HTTPPeerSource:
    """Ranged shard fetches from a peer replica's ``/fanout/shard``
    endpoint (mounted on the payload server). Connection errors and
    timeouts surface as :class:`PeerUnavailable`."""

    def __init__(self, replica_id: int, endpoint: str,
                 timeout: Optional[float] = None) -> None:
        self.replica_id = replica_id
        self.endpoint = endpoint.rstrip('/')
        if timeout is None:
            timeout = env_registry.get_float('SKYT_FANOUT_PEER_TIMEOUT')
        self.timeout = timeout
        self.name = f'peer:{replica_id}'

    def fetch(self, shard: Dict[str, Any],
              offset: int) -> Iterator[bytes]:
        fault_injection.inject(PEER_GET_SITE)
        url = f'{self.endpoint}/fanout/shard/{shard["sha256"]}'
        req = urllib.request.Request(url)
        if offset:
            req.add_header('Range', f'bytes={offset}-')
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                if resp.status not in (200, 206):
                    raise PeerUnavailable(
                        f'{self.name}: HTTP {resp.status}')
                if resp.status == 200 and offset:
                    # Peer ignored Range: discard the prefix so the
                    # resume offset stays truthful.
                    resp.read(offset)
                while True:
                    chunk = resp.read(_CHUNK)
                    if not chunk:
                        return
                    yield chunk
        except urllib.error.HTTPError as e:
            raise PeerUnavailable(f'{self.name}: HTTP {e.code}') \
                from None
        except (urllib.error.URLError, TimeoutError, OSError,
                ConnectionError) as e:
            raise PeerUnavailable(f'{self.name}: {e}') from None


class CallableSource:
    """Test/bench seam: wraps ``fn(shard, offset) -> bytes`` (peers
    in-process, latency injected by the callable)."""

    def __init__(self, name: str,
                 fn: Callable[[Dict[str, Any], int], bytes],
                 is_peer: bool = True) -> None:
        self.name = name
        self.replica_id: Optional[int] = None
        self._fn = fn
        self._is_peer = is_peer

    def fetch(self, shard: Dict[str, Any],
              offset: int) -> Iterator[bytes]:
        if self._is_peer:
            fault_injection.inject(PEER_GET_SITE)
        data = self._fn(shard, offset)
        for i in range(0, len(data), _CHUNK):
            yield data[i:i + _CHUNK]


class DirectorySource:
    """Local-directory bucket stand-in: serves shard bytes straight
    from a committed weights tree (the RL pipeline's policy store and
    the benches pull learner deltas through the same verified-ranged
    path remote buckets use)."""

    def __init__(self, root: str, name: str = 'bucket:dir') -> None:
        self.root = root
        self.name = name
        self.replica_id: Optional[int] = None

    def fetch(self, shard: Dict[str, Any],
              offset: int) -> Iterator[bytes]:
        path = os.path.join(self.root, shard['path'])
        try:
            with open(path, 'rb') as f:
                if offset:
                    f.seek(offset)
                while True:
                    chunk = f.read(_CHUNK)
                    if not chunk:
                        return
                    yield chunk
        except OSError as e:
            raise PeerUnavailable(f'{self.name}: {e}') from None


def fetch_manifest(endpoint: str,
                   timeout: Optional[float] = None
                   ) -> Optional[Dict[str, Any]]:
    """Fetch a peer's committed manifest (``/fanout/manifest``).

    Returns None when the peer has no committed manifest yet (404) —
    the same torn-reads-read-as-absent stance as the local
    ``ckpt_manifest.read``. Connection errors surface as
    :class:`PeerUnavailable` so pollers heal instead of crashing."""
    if timeout is None:
        timeout = env_registry.get_float('SKYT_FANOUT_PEER_TIMEOUT')
    url = f'{endpoint.rstrip("/")}/fanout/manifest'
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            if resp.status != 200:
                raise PeerUnavailable(f'manifest: HTTP {resp.status}')
            return json.loads(resp.read().decode('utf-8'))
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise PeerUnavailable(f'manifest: HTTP {e.code}') from None
    except (urllib.error.URLError, TimeoutError, OSError,
            ConnectionError) as e:
        raise PeerUnavailable(f'manifest: {e}') from None


def sources_from_plan(plan: Dict[str, Any],
                      timeout: Optional[float] = None
                      ) -> List[HTTPPeerSource]:
    """Ancestor-ordered HTTP sources from a controller peer plan
    (the :data:`PEERS_ENV` payload, parsed)."""
    return [HTTPPeerSource(p['replica_id'], p['endpoint'],
                           timeout=timeout)
            for p in plan.get('peers', ())]


# -- the puller --------------------------------------------------------


class FanoutPuller:
    """Pulls one manifest's shards into ``dest``, healing up the
    source chain and falling back to the lease-bounded bucket.

    ``sources`` is the ancestor chain (parent first); ``bucket`` is
    the origin source (its fetches never inject ``peer_get`` faults
    and never quarantine). ``lease`` gates bucket reads — an object
    with ``try_acquire(holder)/release(holder)`` (the in-process
    :class:`LeaseManager` or the serve_state-backed twin).
    ``on_corrupt(source, shard)`` fires when a source served a whole
    shard whose digest mismatched — the serve path wires it to
    :func:`quarantine_peer`.
    """

    def __init__(self, manifest: Dict[str, Any], dest: str,
                 sources: Iterable[Any], bucket: Any, *,
                 lease: Optional[Any] = None,
                 holder: Optional[str] = None,
                 on_corrupt: Optional[Callable] = None,
                 lease_wait_s: float = 300.0,
                 sleep: Optional[Callable[[float], None]] = None
                 ) -> None:
        if sleep is None:
            sleep = time.sleep
        self.manifest = manifest
        self.dest = dest
        self.sources = list(sources)
        self.bucket = bucket
        self.lease = lease
        self.holder = holder or f'puller-{os.getpid()}-{id(self)}'
        self.on_corrupt = on_corrupt
        self.lease_wait_s = float(lease_wait_s)
        self._sleep = sleep
        self._lease_held = False
        # Observability for tests/benches: where each shard came from.
        self.shard_sources: Dict[str, str] = {}
        self.heals: List[Tuple[str, str]] = []

    # -- public --------------------------------------------------------

    def pull(self) -> Dict[str, Any]:
        """Fetch every missing/changed shard, verify, commit the
        manifest last. Returns a small result dict. Raises only when
        ALL sources (bucket included) fail a shard."""
        from skypilot_tpu.server import metrics
        os.makedirs(self.dest, exist_ok=True)
        local = ckpt_manifest.read(self.dest)
        todo = ckpt_manifest.diff(local, self.manifest)
        # A committed manifest can still cover torn shards (a crashed
        # partial copy): re-check the ones diff skipped.
        if local is not None:
            have = {s['path'] for s in todo}
            todo += [s for s in ckpt_manifest.verify(
                self.dest, self.manifest) if s['path'] not in have]
        fetched = 0
        try:
            for shard in todo:
                self._pull_shard(shard)
                fetched += 1
        finally:
            self._release_lease()
        bad = ckpt_manifest.verify(self.dest, self.manifest)
        if bad:
            raise ShardCorrupt(
                f'{len(bad)} shard(s) failed final verification in '
                f'{self.dest}: {[s["path"] for s in bad[:4]]}')
        ckpt_manifest.write(self.dest, self.manifest)
        metrics.FANOUT_PULLS.inc(outcome='ok')
        return {'fetched': fetched, 'skipped':
                len(self.manifest.get('shards', ())) - fetched,
                'heals': len(self.heals),
                'sources': dict(self.shard_sources)}

    # -- internals -----------------------------------------------------

    def _pull_shard(self, shard: Dict[str, Any]) -> None:
        from skypilot_tpu.server import metrics
        while True:
            source = self.sources[0] if self.sources else None
            if source is None:
                self._ensure_lease()
                source = self.bucket
            try:
                self._fetch_from(source, shard)
                self.shard_sources[shard['path']] = source.name
                metrics.FANOUT_SHARDS.inc(
                    source=('bucket' if source is self.bucket
                            else 'peer'), outcome='ok')
                return
            except ShardCorrupt as e:
                if source is self.bucket:
                    # The origin is authoritative: a bucket digest
                    # mismatch means the manifest and the object
                    # disagree — nothing further up to heal to.
                    raise
                metrics.FANOUT_SHARDS.inc(source='peer',
                                          outcome='corrupt')
                self._heal(source, f'corrupt: {e}')
                if self.on_corrupt is not None:
                    self.on_corrupt(source, shard)
            except (PeerUnavailable, TimeoutError, ConnectionError,
                    OSError) as e:
                if source is self.bucket:
                    raise PeerUnavailable(
                        f'bucket fetch of {shard["path"]} failed: '
                        f'{e}') from e
                metrics.FANOUT_SHARDS.inc(source='peer',
                                          outcome='error')
                self._heal(source, f'unavailable: {e}')

    def _heal(self, source: Any, reason: str) -> None:
        from skypilot_tpu.server import metrics
        if self.sources and self.sources[0] is source:
            self.sources.pop(0)
        kind = 'corrupt' if reason.startswith('corrupt') else 'dead'
        metrics.FANOUT_HEALS.inc(reason=kind)
        self.heals.append((source.name, reason))
        nxt = self.sources[0].name if self.sources else 'bucket'
        logger.warning('fanout heal: %s %s; re-parenting to %s',
                       source.name, reason, nxt)

    def _fetch_from(self, source: Any,
                    shard: Dict[str, Any]) -> None:
        from skypilot_tpu.server import metrics
        final = os.path.join(self.dest, *shard['path'].split('/'))
        os.makedirs(os.path.dirname(final) or self.dest,
                    exist_ok=True)
        # Deterministic tmp name: a relaunched puller (replica
        # preemption) resumes the same partial file.
        tmp = f'{final}{ckpt_manifest.TMP_INFIX}.part'
        offset = os.path.getsize(tmp) if os.path.exists(tmp) else 0
        if offset > shard['size']:
            os.remove(tmp)
            offset = 0
        if offset:
            metrics.FANOUT_SHARDS.inc(
                source=('bucket' if source is self.bucket else 'peer'),
                outcome='resumed')
        started_at = offset
        with open(tmp, 'ab') as f:
            for chunk in source.fetch(shard, offset):
                f.write(chunk)
                metrics.FANOUT_BYTES.inc(
                    len(chunk),
                    source=('bucket' if source is self.bucket
                            else 'peer'))
            f.flush()
            os.fsync(f.fileno())
        entry = ckpt_manifest.hash_file(tmp)
        if entry['sha256'] != shard['sha256'] or \
                entry['size'] != shard['size']:
            os.remove(tmp)
            if started_at == 0:
                # The whole shard came from this source: its bytes
                # are provably bad — corrupt, quarantine-worthy.
                raise ShardCorrupt(
                    f'{shard["path"]} from {source.name}: got '
                    f'{entry["sha256"][:12]}, want '
                    f'{shard["sha256"][:12]}')
            # Mixed provenance (resumed across sources): the bad
            # byte could belong to an earlier source — restart the
            # shard without blaming this peer.
            raise PeerUnavailable(
                f'{shard["path"]}: resumed shard failed digest; '
                f'restarting from offset 0')
        os.replace(tmp, final)

    def _ensure_lease(self) -> None:
        from skypilot_tpu.server import metrics
        if self.lease is None or self._lease_held:
            return
        delays = resilience.backoff_delays(base=0.05, cap=2.0)
        waited = 0.0
        while True:
            if self.lease.try_acquire(self.holder):
                self._lease_held = True
                metrics.FANOUT_LEASE_WAIT.observe(waited)
                return
            delay = next(delays)
            waited += delay
            if waited > self.lease_wait_s:
                raise PeerUnavailable(
                    f'bucket lease not acquired within '
                    f'{self.lease_wait_s:.0f}s')
            self._sleep(delay)

    def _release_lease(self) -> None:
        if self.lease is not None and self._lease_held:
            self.lease.release(self.holder)
            self._lease_held = False


# -- peer-serving endpoint ---------------------------------------------


def handle_peer_get(path: str, weights_dir: Optional[str] = None,
                    range_header: Optional[str] = None
                    ) -> Tuple[int, Dict[str, str], bytes]:
    """Shared GET handler for the replica's peer-serving surface:
    ``/fanout/manifest`` (the committed manifest payload) and
    ``/fanout/shard/<sha256>`` (shard bytes, Range-resumable).
    Returns ``(status, headers, body)``; mounted by the payload
    server (inference/server.py) and :class:`PeerServer`. Serves
    only committed content — a torn manifest or a digest-less path
    is a 404, never a partial answer."""
    if weights_dir is None:
        weights_dir = env_registry.get_str(DIR_ENV) or ''
    if not weights_dir:
        return 503, {}, b'{"error": "fanout dir not configured"}'
    payload = ckpt_manifest.read(weights_dir)
    if payload is None:
        return 404, {}, b'{"error": "no committed manifest"}'
    if path == '/fanout/manifest':
        return 200, {'Content-Type': 'application/json'}, json.dumps(
            payload, sort_keys=True).encode()
    prefix = '/fanout/shard/'
    if not path.startswith(prefix):
        return 404, {}, b'{"error": "not found"}'
    digest = path[len(prefix):]
    by_sha = {s['sha256']: s for s in payload.get('shards', ())}
    shard = by_sha.get(digest)
    if shard is None:
        return 404, {}, b'{"error": "unknown shard"}'
    root = os.path.abspath(weights_dir)
    full = os.path.abspath(os.path.join(root, *shard['path'].split('/')))
    if not full.startswith(root + os.sep):
        return 403, {}, b'{"error": "path escapes weights dir"}'
    offset = _parse_range(range_header)
    try:
        with open(full, 'rb') as f:
            if offset:
                f.seek(offset)
            body = f.read()
    except OSError:
        return 404, {}, b'{"error": "shard missing on disk"}'
    headers = {'Content-Type': 'application/octet-stream',
               'X-Skyt-Shard-Sha256': shard['sha256']}
    if offset:
        headers['Content-Range'] = (
            f'bytes {offset}-{shard["size"] - 1}/{shard["size"]}')
        return 206, headers, body
    return 200, headers, body


def _parse_range(header: Optional[str]) -> int:
    """Start offset of a ``bytes=N-`` header (the only form pullers
    send); anything else reads as 0 (serve from the top — the
    puller's digest check still holds)."""
    if not header or not header.startswith('bytes='):
        return 0
    spec = header[len('bytes='):].split(',')[0].strip()
    start = spec.split('-')[0]
    try:
        return max(0, int(start))
    except ValueError:
        return 0


class PeerServer:
    """Standalone peer-serving HTTP server over one weights
    directory — what tests and benches stand up in place of a full
    replica payload (the real replica mounts the same handler on
    its inference server)."""

    def __init__(self, weights_dir: str) -> None:
        self.weights_dir = weights_dir
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib casing)
                status, headers, body = handle_peer_get(
                    self.path, outer.weights_dir,
                    self.headers.get('Range'))
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # noqa: D102
                pass

        self._server = http.server.ThreadingHTTPServer(
            ('127.0.0.1', 0), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    @property
    def endpoint(self) -> str:
        host, port = self._server.server_address[:2]
        return f'http://{host}:{port}'

    def __enter__(self) -> 'PeerServer':
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
