"""Mount-command generation for bucket stores.

Parity: ``sky/data/mounting_utils.py:23-65`` (gcsfuse/blobfuse2/s3fs/
rclone command gen). GCS is the TPU-adjacent store, so gcsfuse is the
primary tool (the reference invokes the same binary); MOUNT_CACHED uses
rclone's VFS cache like the reference's mount-cached path. All
functions return *shell command strings* executed on cluster hosts by
the backend — generation is pure and unit-testable offline.
"""
from __future__ import annotations

import shlex

GCSFUSE_VERSION = '2.4.0'
RCLONE_VERSION = '1.68.1'


def quote_path(path: str) -> str:
    """shlex.quote that keeps leading ``~`` expandable: mounts are
    host-side paths, and the local (fake-cluster) runner maps ``~`` to
    the host's private root via $HOME."""
    if path == '~':
        return '"$HOME"'
    if path.startswith('~/'):
        return f'"$HOME/{_dq(path[2:])}"'
    return shlex.quote(path)


def _dq(s: str) -> str:
    """Escape for inside double quotes."""
    return s.replace('\\', '\\\\').replace('"', '\\"').replace(
        '$', '\\$').replace('`', '\\`')

# Reference installs tooling on first mount (mounting_utils installs
# gcsfuse per distro); one idempotent snippet, amd64/arm64 aware.
GCSFUSE_INSTALL = (
    'command -v gcsfuse >/dev/null 2>&1 || {{ '
    'ARCH=$(uname -m | sed "s/x86_64/amd64/;s/aarch64/arm64/"); '
    'curl -fsSL -o /tmp/gcsfuse.deb '
    'https://github.com/GoogleCloudPlatform/gcsfuse/releases/download/'
    'v{v}/gcsfuse_{v}_${{ARCH}}.deb && '
    'sudo dpkg -i /tmp/gcsfuse.deb; }}').format(v=GCSFUSE_VERSION)

RCLONE_INSTALL = (
    'command -v rclone >/dev/null 2>&1 || '
    'curl -fsSL https://rclone.org/install.sh | sudo bash')


# Unprivileged k8s pods reach fusermount through the fuse-proxy shim
# (provision/kubernetes.py wires FUSE_PROXY_SOCKET + the shared bin dir;
# addons/fuse_proxy). Prepending in-shell preserves the image's PATH.
FUSE_PROXY_PATH_PREFIX = (
    'if [ -n "${FUSE_PROXY_SOCKET:-}" ]; then '
    'export PATH="$(dirname "$FUSE_PROXY_SOCKET")/bin:$PATH"; fi')


def gcs_mount_command(bucket: str, mount_path: str,
                      readonly: bool = False) -> str:
    """gcsfuse mount (MOUNT mode): direct bucket FS, writes go through."""
    flags = '--implicit-dirs'
    if readonly:
        flags += ' -o ro'
    path = quote_path(mount_path)
    return (f'{FUSE_PROXY_PATH_PREFIX} && '
            f'{GCSFUSE_INSTALL} && mkdir -p {path} && '
            f'{{ mountpoint -q {path} || '
            f'gcsfuse {flags} {shlex.quote(bucket)} {path}; }}')


def gcs_mount_cached_command(bucket: str, mount_path: str) -> str:
    """rclone VFS-cached mount (MOUNT_CACHED): local write-back cache,
    async upload — the checkpoint-bucket pattern (SURVEY.md §5
    checkpoint/resume) without blocking the training loop on GCS."""
    path = quote_path(mount_path)
    remote = f'skyt-gcs:{bucket}'
    return (
        f'{FUSE_PROXY_PATH_PREFIX} && '
        f'{RCLONE_INSTALL} && mkdir -p {path} ~/.config/rclone && '
        '{ grep -q "^\\[skyt-gcs\\]" ~/.config/rclone/rclone.conf '
        '2>/dev/null || printf "[skyt-gcs]\\ntype = gcs\\n" '
        '>> ~/.config/rclone/rclone.conf; } && '
        f'{{ mountpoint -q {path} || '
        f'rclone mount {shlex.quote(remote)} {path} --daemon '
        '--vfs-cache-mode writes --vfs-cache-max-size 10G '
        '--dir-cache-time 30s; }')


def gcs_download_command(bucket: str, prefix: str, dest: str) -> str:
    """COPY mode: one-shot bucket -> local sync on the host.

    The source may name a single object (``gs://b/w.txt`` — then
    ``dest`` is the destination *file* path) or a prefix/directory
    (rsync'd into ``dest``); ``gsutil stat`` succeeds only for objects,
    which disambiguates at run time.
    """
    src = shlex.quote(f'gs://{bucket}/{prefix}'.rstrip('/'))
    dst = quote_path(dest)
    return (f'if gsutil -q stat {src} 2>/dev/null; then '
            f'mkdir -p "$(dirname {dst})" && gsutil cp {src} {dst}; '
            f'else mkdir -p {dst} && '
            f'gsutil -m rsync -r {src} {dst}; fi')


def _rclone_s3_remote_config() -> str:
    """Idempotent rclone remote backed by the configured S3 endpoint.

    Credentials/endpoint come from AWS_* / SKYT_S3_ENDPOINT_URL env vars
    via rclone's env_auth; S3CompatibleStore._env_prefix embeds them in
    the generated command (the client resolves config at gen time --
    hosts have no client config)."""
    return (
        'mkdir -p ~/.config/rclone && '
        '{ grep -q "^\\[skyt-s3\\]" ~/.config/rclone/rclone.conf '
        '2>/dev/null || printf "[skyt-s3]\\ntype = s3\\n'
        'provider = Other\\nenv_auth = true\\n'
        'endpoint = ${SKYT_S3_ENDPOINT_URL:-https://s3.amazonaws.com}\\n" '
        '>> ~/.config/rclone/rclone.conf; }')


def s3_mount_command(bucket: str, mount_path: str) -> str:
    """rclone mount of an S3-compatible bucket (MOUNT mode; parity:
    s3fs/goofys command gen in the reference -- rclone is the one tool
    that covers every S3-compatible provider)."""
    path = quote_path(mount_path)
    remote = f'skyt-s3:{bucket}'
    return (f'{FUSE_PROXY_PATH_PREFIX} && '
            f'{RCLONE_INSTALL} && {_rclone_s3_remote_config()} && '
            f'mkdir -p {path} && '
            f'{{ mountpoint -q {path} || '
            f'rclone mount {shlex.quote(remote)} {path} --daemon '
            '--vfs-cache-mode off --dir-cache-time 30s; }')


def s3_mount_cached_command(bucket: str, mount_path: str) -> str:
    """rclone VFS write-back cache (MOUNT_CACHED; checkpoint pattern)."""
    path = quote_path(mount_path)
    remote = f'skyt-s3:{bucket}'
    return (f'{FUSE_PROXY_PATH_PREFIX} && '
            f'{RCLONE_INSTALL} && {_rclone_s3_remote_config()} && '
            f'mkdir -p {path} && '
            f'{{ mountpoint -q {path} || '
            f'rclone mount {shlex.quote(remote)} {path} --daemon '
            '--vfs-cache-mode writes --vfs-cache-max-size 10G '
            '--dir-cache-time 30s; }')


def s3_download_command(bucket: str, prefix: str, dest: str) -> str:
    """COPY mode via the shipped runtime's stdlib S3 client -- no
    aws-cli/rclone needed for one-shot downloads."""
    dst = quote_path(dest)
    return (f'mkdir -p {dst} && '
            f'python3 -m skypilot_tpu.data.s3 sync-down '
            f'{shlex.quote(bucket)} {shlex.quote(prefix)} {dst}')


def local_mount_command(bucket_dir: str, mount_path: str) -> str:
    """LOCAL (test/dev) store 'mount': a symlink into the bucket dir."""
    path = quote_path(mount_path)
    return (f'mkdir -p "$(dirname {path})" && '
            f'ln -sfn {shlex.quote(bucket_dir)} {path}')


def local_download_command(bucket_dir: str, prefix: str, dest: str) -> str:
    """Single file or directory, mirroring gcs_download_command."""
    src = shlex.quote(bucket_dir if not prefix
                      else f'{bucket_dir}/{prefix}')
    dst = quote_path(dest)
    return (f'if [ -f {src} ]; then '
            f'mkdir -p "$(dirname {dst})" && cp -a {src} {dst}; '
            f'else mkdir -p {dst} && cp -a {src}/. {dst}/; fi')


def unmount_command(mount_path: str) -> str:
    path = quote_path(mount_path)
    return (f'if [ -L {path} ]; then rm -f {path}; '
            f'elif mountpoint -q {path}; then '
            f'fusermount -u {path} || sudo umount {path}; fi')


AZURE_RCLONE_CONF = '~/.config/rclone/skyt-az.conf'


def _rclone_azure_remote_config() -> str:
    """Dedicated rclone conf for Azure Blob, REGENERATED on every mount
    (grep-once idempotency would freeze the first run's account/key —
    rotated storage keys must take effect on the next mount; env_auth
    does not cover azureblob storage-key auth, so the values bake into
    the file from the gen-time exports). Endpoint rides along so
    Azurite/sovereign clouds mount what COPY downloads from. Parity:
    blobfuse2 command gen in the reference; rclone covers the same."""
    return (
        'mkdir -p ~/.config/rclone && '
        'printf "[skyt-az]\\ntype = azureblob\\n'
        'account = ${AZURE_STORAGE_ACCOUNT}\\n'
        'key = ${AZURE_STORAGE_KEY}\\n'
        'endpoint = ${SKYT_AZURE_BLOB_ENDPOINT}\\n" '
        f'> {AZURE_RCLONE_CONF}')


def azure_mount_command(container: str, mount_path: str) -> str:
    """rclone mount of an Azure Blob container (MOUNT mode)."""
    path = quote_path(mount_path)
    remote = f'skyt-az:{container}'
    return (f'{FUSE_PROXY_PATH_PREFIX} && '
            f'{RCLONE_INSTALL} && {_rclone_azure_remote_config()} && '
            f'mkdir -p {path} && '
            f'{{ mountpoint -q {path} || '
            f'rclone mount --config {AZURE_RCLONE_CONF} '
            f'{shlex.quote(remote)} {path} --daemon '
            '--vfs-cache-mode off --dir-cache-time 30s; }')


def azure_mount_cached_command(container: str, mount_path: str) -> str:
    """rclone VFS write-back cache (MOUNT_CACHED; checkpoint pattern)."""
    path = quote_path(mount_path)
    remote = f'skyt-az:{container}'
    return (f'{FUSE_PROXY_PATH_PREFIX} && '
            f'{RCLONE_INSTALL} && {_rclone_azure_remote_config()} && '
            f'mkdir -p {path} && '
            f'{{ mountpoint -q {path} || '
            f'rclone mount --config {AZURE_RCLONE_CONF} '
            f'{shlex.quote(remote)} {path} --daemon '
            '--vfs-cache-mode writes --vfs-cache-max-size 10G '
            '--dir-cache-time 30s; }')


def azure_download_command(container: str, prefix: str,
                           dest: str) -> str:
    """COPY mode via the shipped runtime's stdlib Azure Blob client."""
    dst = quote_path(dest)
    return (f'mkdir -p {dst} && '
            'PYTHONPATH="$HOME/.skyt_runtime/runtime'
            '${PYTHONPATH:+:$PYTHONPATH}" '
            f'python3 -m skypilot_tpu.data.azure_blob download '
            f'{shlex.quote(container)} {shlex.quote(prefix)} {dst}')
