"""Packed-sequence batching over the native C++ packer.

The training input pipeline's hot loop (parity stance: the reference
keeps data-loaders native, SURVEY §2.11): EOS-delimited documents are
greedily first-fit packed into fixed [batch, seq] grids with per-token
segment ids and positions, so attention (segment mask) and RoPE
(position reset) treat packed neighbours as independent sequences — no
padding waste, no cross-document leakage.

``addons/dataloader/packer.cc`` is compiled on first use (g++, cached
under the state dir) and called via ctypes; hosts without a compiler
fall back to a bit-identical pure-Python implementation (the parity
test asserts exact equality).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from skypilot_tpu.utils import log

logger = log.init_logger(__name__)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, 'addons', 'dataloader', 'packer.cc')

_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build_dir() -> str:
    return os.path.join(
        os.environ.get('SKYT_STATE_DIR', os.path.expanduser('~/.skyt')),
        'native')


def load_native() -> Optional[ctypes.CDLL]:
    """Compile (once) + load the C++ packer; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        os.makedirs(_build_dir(), exist_ok=True)
        so_path = os.path.join(_build_dir(), 'libskyt_packer.so')
        have_src = os.path.exists(_SRC)
        stale = (have_src and os.path.exists(so_path) and
                 os.path.getmtime(so_path) < os.path.getmtime(_SRC))
        if not os.path.exists(so_path) or stale:
            if not have_src:
                raise OSError(f'no cached packer and no source at {_SRC}')
            # Compile to a private temp and rename into place: concurrent
            # processes (multi-worker launches, pytest-xdist) must never
            # dlopen a half-written library or rewrite a mapped one.
            tmp_path = f'{so_path}.{os.getpid()}.tmp'
            subprocess.run(
                ['g++', '-O3', '-fPIC', '-shared', '-std=c++17',
                 '-o', tmp_path, _SRC],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp_path, so_path)
        lib = ctypes.CDLL(so_path)
        lib.skyt_pack_batch.restype = ctypes.c_long
        lib.skyt_pack_batch.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_long, ctypes.c_long,
            ctypes.c_uint32, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_long),
        ]
        _lib = lib
        logger.debug('Native packer loaded from %s', so_path)
    except (OSError, subprocess.SubprocessError) as e:
        logger.info('Native packer unavailable (%s); using the Python '
                    'fallback.', e)
        _lib_failed = True
    return _lib


def pack_batch_native(tokens: np.ndarray, start: int, eos_id: int,
                      batch: int, seq: int
                      ) -> Tuple[Dict[str, np.ndarray], int, int]:
    lib = load_native()
    assert lib is not None
    if tokens.dtype != np.uint32 or not tokens.flags['C_CONTIGUOUS']:
        # Callers on the hot path (packed_batch_iterator) hand us a
        # uint32 view so this stays a no-op; a cold-path copy here is a
        # convenience for direct users, not the per-step norm.
        tokens = np.ascontiguousarray(tokens, dtype=np.uint32)
    out_tokens = np.zeros((batch, seq), np.uint32)
    out_segments = np.zeros((batch, seq), np.int32)
    out_positions = np.zeros((batch, seq), np.int32)
    next_offset = ctypes.c_long(start)
    placed = lib.skyt_pack_batch(
        tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        len(tokens), start, eos_id, batch, seq,
        out_tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        out_segments.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_positions.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.byref(next_offset))
    if placed < 0:
        raise ValueError(f'packer rejected batch={batch} seq={seq}')
    grid = {'tokens': out_tokens, 'segments': out_segments,
            'positions': out_positions}
    return grid, next_offset.value, int(placed)


def pack_batch_py(tokens: np.ndarray, start: int, eos_id: int,
                  batch: int, seq: int
                  ) -> Tuple[Dict[str, np.ndarray], int, int]:
    """Bit-identical Python mirror of skyt_pack_batch (see packer.cc
    for the semantics contract)."""
    out_tokens = np.zeros((batch, seq), np.uint32)
    out_segments = np.zeros((batch, seq), np.int32)
    out_positions = np.zeros((batch, seq), np.int32)
    fill = [0] * batch
    seg = [0] * batch
    offset = int(start)
    placed = 0
    row_hint = 0
    n = len(tokens)
    while offset < n:
        doc_len = 0
        while offset + doc_len < n and doc_len < seq:
            doc_len += 1
            if tokens[offset + doc_len - 1] == eos_id:
                break
        if doc_len == 0:
            break
        row = -1
        for probe in range(batch):
            r = (row_hint + probe) % batch
            if fill[r] + doc_len <= seq:
                row = r
                break
        if row < 0:
            break
        at = fill[row]
        seg[row] += 1
        out_tokens[row, at:at + doc_len] = tokens[offset:offset + doc_len]
        out_segments[row, at:at + doc_len] = seg[row]
        out_positions[row, at:at + doc_len] = np.arange(doc_len)
        fill[row] += doc_len
        placed += doc_len
        offset += doc_len
        row_hint = row
        if all(f >= seq for f in fill):
            break
    grid = {'tokens': out_tokens, 'segments': out_segments,
            'positions': out_positions}
    return grid, offset, placed


def pack_batch(tokens: np.ndarray, start: int, eos_id: int,
               batch: int, seq: int
               ) -> Tuple[Dict[str, np.ndarray], int, int]:
    if load_native() is not None:
        return pack_batch_native(tokens, start, eos_id, batch, seq)
    return pack_batch_py(tokens, start, eos_id, batch, seq)


def packed_batch_iterator(tokens, *, batch: int, seq: int,
                          eos_id: int, loop: bool = True
                          ) -> Iterator[Dict[str, np.ndarray]]:
    """Train-ready packed batches: tokens/targets/weights/segments/
    positions, each [batch, seq].

    ``tokens`` is a flat array OR a .npy path (memmapped). The array is
    viewed as uint32 ONCE — an int32 memmap reinterprets zero-copy, so
    datasets larger than RAM stream straight off disk.

    targets are next tokens WITHIN the same segment; the weight is 0 on
    padding and on each segment's last token (its next token belongs to
    a different document).
    """
    if isinstance(tokens, str):
        tokens = np.load(os.path.expanduser(tokens), mmap_mode='r')
    if tokens.dtype == np.int32:
        tokens = tokens.view(np.uint32)  # zero-copy, mmap-preserving
    elif tokens.dtype != np.uint32:
        tokens = np.ascontiguousarray(tokens, dtype=np.uint32)
    grid_seq = seq + 1  # pack one extra column so every target exists
    offset = 0
    while True:
        grid, offset, placed = pack_batch(tokens, offset, eos_id, batch,
                                          grid_seq)
        if placed == 0:
            if offset == 0:
                raise ValueError(
                    'token stream yields no packable documents '
                    '(empty file, or every document is empty)')
            if not loop:
                return
            offset = 0
            continue
        toks = grid['tokens'].astype(np.int32)
        segs = grid['segments']
        poss = grid['positions']
        same_segment = (segs[:, 1:] == segs[:, :-1]) & (segs[:, :-1] > 0)
        yield {
            'tokens': toks[:, :-1],
            'targets': toks[:, 1:],
            'weights': same_segment.astype(np.float32),
            'segments': segs[:, :-1],
            'positions': poss[:, :-1],
        }
