"""Hosted catalog feed: TTL refresh over the baked-in tables.

Parity: ``sky/catalog/common.py:193-245`` (``read_catalog`` pulls
versioned hosted CSVs with TTL re-fetch and falls back to the cached
copy). Stale price data silently corrupts the optimizer's ranking —
which is the product — so the baked-in tables (``gcp_data``/
``aws_data``, versioned with the code) act as the always-available
floor and a configured feed overlays fresher numbers:

* ``catalog.feed_url`` in layered config (or ``SKYT_CATALOG_FEED``) —
  an ``https://``/``file://``/plain-path JSON document produced by
  ``python -m skypilot_tpu.catalog.data_fetchers``.
* Fetched at most once per TTL (``catalog.refresh_ttl_hours``, default
  24; env ``SKYT_CATALOG_TTL_HOURS``); the last good copy is cached at
  ``~/.skyt/catalog/feed.json`` and used when the feed is unreachable,
  so fully offline operation is preserved.
* ``skyt check`` surfaces staleness (``staleness_warning``).

Feed schema (all sections optional — absent keys keep baked values):

    {"version": 1, "generated_at": 1700000000.0,
     "gcp": {"tpu_chip_hour_prices": {"v5e": [1.2, 0.54]},
             "gpu_offerings": {"A100": [2.9, 1.1, 40, "a2"]}},
     "aws": {"gpu_instance_types": {"A10G": {"1": ["g5.xlarge",
                                                    1.0, 0.45, 24]}}}}
"""
from __future__ import annotations

import json
import os
import time
import urllib.request
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu.utils import log

logger = log.init_logger(__name__)

_mem_cache: Dict[str, Tuple[float, Dict[str, Any]]] = {}


def _feed_url() -> Optional[str]:
    url = os.environ.get('SKYT_CATALOG_FEED')
    if url:
        return url
    from skypilot_tpu import config as config_lib
    return config_lib.get_nested(('catalog', 'feed_url'), None)


def _ttl_seconds() -> float:
    from skypilot_tpu.utils import env_registry
    hours = env_registry.get_float('SKYT_CATALOG_TTL_HOURS',
                                   default=None)
    if hours is None:
        from skypilot_tpu import config as config_lib
        hours = config_lib.get_nested(('catalog', 'refresh_ttl_hours'), 24)
    return float(hours) * 3600


def cache_path() -> str:
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, 'catalog', 'feed.json')


def _fetch(url: str) -> Dict[str, Any]:
    if url.startswith('file://'):
        url_path = url[len('file://'):]
        with open(url_path, encoding='utf-8') as f:
            return json.load(f)
    if '://' not in url:
        with open(url, encoding='utf-8') as f:
            return json.load(f)
    with urllib.request.urlopen(url, timeout=20) as resp:
        return json.loads(resp.read().decode('utf-8'))


def get_overlay(refresh: bool = False) -> Dict[str, Any]:
    """The current catalog overlay ({} when no feed is configured).

    Never raises: fetch failures fall back to the on-disk copy, then to
    the empty overlay (baked tables only).
    """
    url = _feed_url()
    if not url:
        return {}
    now = time.time()
    cached = _mem_cache.get(url)
    if not refresh and cached and now - cached[0] < _ttl_seconds():
        return cached[1]
    path = cache_path()

    def read_disk():
        """Cached overlay, ONLY if it came from this url (a changed
        feed_url must not serve the old feed's prices)."""
        try:
            with open(path, encoding='utf-8') as f:
                doc = json.load(f)
            if doc.get('_source_url') == url:
                return doc['overlay']
        except (OSError, json.JSONDecodeError, KeyError):
            pass
        return None

    disk_age = None
    if os.path.exists(path):
        disk_age = now - os.path.getmtime(path)
    if not refresh and disk_age is not None and disk_age < _ttl_seconds():
        overlay = read_disk()
        if overlay is not None:
            _mem_cache[url] = (now, overlay)
            return overlay
    try:
        overlay = _fetch(url)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + '.tmp'
        with open(tmp, 'w', encoding='utf-8') as f:
            json.dump({'_source_url': url, 'overlay': overlay}, f)
        os.replace(tmp, path)
        _mem_cache[url] = (now, overlay)
        return overlay
    except Exception as e:  # pylint: disable=broad-except
        logger.warning('catalog feed %s unreachable (%s); using %s', url,
                       e, 'cached copy' if disk_age is not None
                       else 'baked-in tables')
        overlay = read_disk()
        if overlay is None:
            overlay = {}
        _mem_cache[url] = (now, overlay)
        return overlay


def clear_cache() -> None:
    _mem_cache.clear()


def staleness_warning() -> Optional[str]:
    """Human warning for `skyt check` when the feed looks stale."""
    url = _feed_url()
    if not url:
        return None
    overlay = get_overlay()
    if not overlay:
        return (f'catalog feed {url} unreachable and no cached copy: '
                'prices come from the baked-in tables (may be stale)')
    generated = overlay.get('generated_at')
    if generated is not None:
        age_days = (time.time() - float(generated)) / 86400
        if age_days > 30:
            return (f'catalog feed is {age_days:.0f} days old; '
                    'regenerate with skypilot_tpu.catalog.data_fetchers')
    path = cache_path()
    if os.path.exists(path):
        age = time.time() - os.path.getmtime(path)
        if age > 2 * _ttl_seconds():
            return (f'catalog cache is {age / 3600:.0f}h old '
                    '(feed unreachable?); prices may be stale')
    return None


# -- overlay lookups used by catalog/common.py ------------------------------

def tpu_chip_prices(gen: str, baked: Tuple[float, float]
                    ) -> Tuple[float, float]:
    entry = get_overlay().get('gcp', {}).get('tpu_chip_hour_prices',
                                             {}).get(gen)
    return tuple(entry) if entry else baked


def gcp_gpu_offering(name: str, baked):
    entry = get_overlay().get('gcp', {}).get('gpu_offerings',
                                             {}).get(name)
    return tuple(entry) if entry else baked


def aws_gpu_instance(name: str, count: int, baked):
    entry = get_overlay().get('aws', {}).get('gpu_instance_types',
                                             {}).get(name, {}).get(
                                                 str(count))
    return tuple(entry) if entry else baked
