"""Catalog feed regenerator (parity: ``sky/catalog/data_fetchers/``).

The reference ships per-cloud fetcher scripts that regenerate its hosted
CSVs from cloud pricing APIs. Here the feed is one JSON document
(schema: ``catalog/refresh.py``); this tool emits it from the baked-in
tables so a maintainer can edit prices (or wire a pricing-API scraper
in) and host the result at ``catalog.feed_url``:

    python -m skypilot_tpu.catalog.data_fetchers --out feed.json
    # edit feed.json / post-process, then host it; clusters pick it up
    # within catalog.refresh_ttl_hours.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Optional

from skypilot_tpu.catalog import aws_data, gcp_data


def build_feed() -> dict:
    return {
        'version': 1,
        'generated_at': time.time(),
        'gcp': {
            'tpu_chip_hour_prices': {
                gen: list(prices)
                for gen, prices in gcp_data.TPU_CHIP_HOUR_PRICES.items()
            },
            'gpu_offerings': {
                name: list(entry)
                for name, entry in gcp_data.GPU_OFFERINGS.items()
            },
        },
        'aws': {
            'gpu_instance_types': {
                name: {str(count): list(entry)
                       for count, entry in shapes.items()}
                for name, shapes in aws_data.GPU_INSTANCE_TYPES.items()
            },
        },
    }


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--out', default='-',
                        help='output path (default: stdout)')
    args = parser.parse_args(argv)
    feed = build_feed()
    text = json.dumps(feed, indent=2, sort_keys=True)
    if args.out == '-':
        print(text)
    else:
        with open(args.out, 'w', encoding='utf-8') as f:
            f.write(text + '\n')
    return 0


if __name__ == '__main__':
    sys.exit(main())
