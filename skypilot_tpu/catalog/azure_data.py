"""Baked-in Azure offerings (parity: ``sky/catalog/azure_catalog.py``
over hosted CSVs from ``sky/catalog/data_fetchers/fetch_azure.py``).

Same stance as ``aws_data``/``gcp_data``: a versioned in-package table
(zero-egress operation) the TTL-refresh layer can overlay. Prices are
representative eastus pay-as-you-go/spot rates; the optimizer only needs
relative ordering.
"""
from __future__ import annotations

from typing import Dict, Tuple

# accelerator -> {count: (vm_size, price_hr, spot_price_hr,
#                         vram_gb_per_accel)}
# Azure sells GPUs via fixed N-series VM sizes, like AWS's P/G shapes.
GPU_INSTANCE_TYPES: Dict[str, Dict[int, Tuple[str, float, float, int]]] = {
    'H100': {8: ('Standard_ND96isr_H100_v5', 98.32, 39.33, 80)},
    'A100-80GB': {
        1: ('Standard_NC24ads_A100_v4', 3.673, 1.469, 80),
        2: ('Standard_NC48ads_A100_v4', 7.346, 2.938, 80),
        4: ('Standard_NC96ads_A100_v4', 14.692, 5.877, 80),
        8: ('Standard_ND96amsr_A100_v4', 32.77, 13.11, 80),
    },
    'A100': {8: ('Standard_ND96asr_v4', 27.20, 10.88, 40)},
    'V100': {1: ('Standard_NC6s_v3', 3.06, 0.92, 16),
             2: ('Standard_NC12s_v3', 6.12, 1.84, 16),
             4: ('Standard_NC24s_v3', 12.24, 3.67, 16)},
    'T4': {1: ('Standard_NC4as_T4_v3', 0.526, 0.21, 16),
           4: ('Standard_NC64as_T4_v3', 4.352, 1.74, 16)},
    'A10': {1: ('Standard_NV36ads_A10_v5', 3.20, 1.28, 24)},
}

# GPU availability by region. Azure zones are region-scoped ordinals
# ('1'/'2'/'3'), not region-prefixed names.
GPU_REGIONS: Dict[str, Dict[str, Tuple[str, ...]]] = {
    name: {
        'eastus': ('1', '2', '3'),
        'westus3': ('1', '2'),
        'westeurope': ('1', '2', '3'),
        'southcentralus': ('1', '2'),
    }
    for name in GPU_INSTANCE_TYPES
}
GPU_REGIONS['H100'] = {
    'eastus': ('1', '2'),
    'southcentralus': ('1',),
}

# name -> (vcpus, memory_gb, price_hr)
CPU_INSTANCE_TYPES: Dict[str, Tuple[int, float, float]] = {
    'Standard_D2s_v5': (2, 8.0, 0.096),
    'Standard_D4s_v5': (4, 16.0, 0.192),
    'Standard_D8s_v5': (8, 32.0, 0.384),
    'Standard_D16s_v5': (16, 64.0, 0.768),
    'Standard_F4s_v2': (4, 8.0, 0.169),
    'Standard_F16s_v2': (16, 32.0, 0.677),
    'Standard_E4s_v5': (4, 32.0, 0.252),
    'Standard_E16s_v5': (16, 128.0, 1.008),
}

ALL_AZURE_REGIONS = ('eastus', 'eastus2', 'westus2', 'westus3',
                     'westeurope', 'northeurope', 'southcentralus',
                     'japaneast', 'southeastasia')

DEFAULT_REGION = 'eastus'

# Canonical Ubuntu 22.04 Gen2 marketplace image (latest at deploy time).
DEFAULT_IMAGE = {
    'publisher': 'Canonical',
    'offer': '0001-com-ubuntu-server-jammy',
    'sku': '22_04-lts-gen2',
    'version': 'latest',
}


def instance_type_for(accelerator: str, count: int):
    """(vm_size, price, spot_price, vram_per_gpu) or None."""
    return GPU_INSTANCE_TYPES.get(accelerator, {}).get(count)
