"""Baked-in AWS offerings (parity: ``sky/catalog/aws_catalog.py`` over
hosted CSVs from ``sky/catalog/data_fetchers/fetch_aws.py``).

Same stance as ``gcp_data``: a versioned in-package table (zero-egress
operation) that the TTL-refresh layer (``catalog/refresh.py``) can
overlay with newer hosted data when a feed is configured. Prices are
representative us-east-1 on-demand/spot rates; the optimizer only needs
relative ordering to rank candidates.
"""
from __future__ import annotations

from typing import Dict, Tuple

# accelerator -> {accel_count: (instance_type, price_hr, spot_price_hr,
#                               vram_gb_per_accel)}
# The instance is the smallest type carrying exactly `count` of the
# accelerator (AWS sells GPUs only via fixed instance shapes).
GPU_INSTANCE_TYPES: Dict[str, Dict[int, Tuple[str, float, float, int]]] = {
    'H100': {8: ('p5.48xlarge', 98.32, 39.33, 80)},
    'A100': {8: ('p4d.24xlarge', 32.77, 9.83, 40)},
    'A100-80GB': {8: ('p4de.24xlarge', 40.97, 12.29, 80)},
    'V100': {1: ('p3.2xlarge', 3.06, 0.92, 16),
             4: ('p3.8xlarge', 12.24, 3.67, 16),
             8: ('p3.16xlarge', 24.48, 7.34, 16)},
    'A10G': {1: ('g5.xlarge', 1.006, 0.45, 24),
             4: ('g5.12xlarge', 5.672, 2.55, 24),
             8: ('g5.48xlarge', 16.288, 7.33, 24)},
    'T4': {1: ('g4dn.xlarge', 0.526, 0.24, 16),
           4: ('g4dn.12xlarge', 3.912, 1.76, 16),
           8: ('g4dn.metal', 7.824, 3.52, 16)},
    'L4': {1: ('g6.xlarge', 0.805, 0.36, 24),
           4: ('g6.12xlarge', 4.602, 2.07, 24),
           8: ('g6.48xlarge', 13.350, 6.01, 24)},
}

# GPU availability by region (zone suffixes appended per region).
GPU_REGIONS: Dict[str, Dict[str, Tuple[str, ...]]] = {
    name: {
        'us-east-1': ('us-east-1a', 'us-east-1b', 'us-east-1c'),
        'us-west-2': ('us-west-2a', 'us-west-2b', 'us-west-2c'),
        'eu-west-1': ('eu-west-1a', 'eu-west-1b'),
    }
    for name in GPU_INSTANCE_TYPES
}
# H100 capacity pools are narrower.
GPU_REGIONS['H100'] = {
    'us-east-1': ('us-east-1a', 'us-east-1b'),
    'us-west-2': ('us-west-2a',),
}

# name -> (vcpus, memory_gb, price_hr)
CPU_INSTANCE_TYPES: Dict[str, Tuple[int, float, float]] = {
    'm6i.large': (2, 8.0, 0.096),
    'm6i.xlarge': (4, 16.0, 0.192),
    'm6i.2xlarge': (8, 32.0, 0.384),
    'm6i.4xlarge': (16, 64.0, 0.768),
    'c6i.xlarge': (4, 8.0, 0.170),
    'c6i.4xlarge': (16, 32.0, 0.680),
    'r6i.xlarge': (4, 32.0, 0.252),
    'r6i.4xlarge': (16, 128.0, 1.008),
}

ALL_AWS_REGIONS = ('us-east-1', 'us-east-2', 'us-west-1', 'us-west-2',
                   'eu-west-1', 'eu-central-1', 'ap-northeast-1',
                   'ap-southeast-1')

DEFAULT_REGION = 'us-east-1'

# Resolved server-side by EC2 at RunInstances time — always the current
# canonical Ubuntu 22.04 AMI for the target region, no baked-in ids.
DEFAULT_AMI_SSM = ('resolve:ssm:/aws/service/canonical/ubuntu/server/'
                   '22.04/stable/current/amd64/hvm/ebs-gp2/ami-id')


def instance_type_for(accelerator: str, count: int):
    """(instance_type, price, spot_price, vram_per_gpu) or None."""
    return GPU_INSTANCE_TYPES.get(accelerator, {}).get(count)
