"""Catalog query API (parity: ``sky/catalog/common.py`` + ``gcp_catalog.py``).

An *offering* is an accelerator available in a (cloud, region, zone) at a
price. TPU offerings carry their parsed ``TpuTopology``.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.catalog import gcp_data
from skypilot_tpu.spec.topology import GENERATIONS, TpuTopology


@dataclasses.dataclass(frozen=True)
class AcceleratorOffering:
    cloud: str
    accelerator: str            # canonical name ('tpu-v5p-64', 'A100')
    count: int                  # devices per node (1 for TPU slices)
    region: str
    zone: str
    price_hr: float             # on-demand $/hr for the whole node request
    spot_price_hr: float
    tpu: Optional[TpuTopology] = None
    vram_gb: Optional[float] = None

    @property
    def is_tpu(self) -> bool:
        return self.tpu is not None

    def cost(self, use_spot: bool) -> float:
        return self.spot_price_hr if use_spot else self.price_hr


def _tpu_offerings(topology: TpuTopology,
                   region_filter: Optional[str] = None,
                   zone_filter: Optional[str] = None
                   ) -> List[AcceleratorOffering]:
    gen = topology.generation
    from skypilot_tpu.catalog import refresh
    price_chip, spot_chip = refresh.tpu_chip_prices(
        gen, gcp_data.TPU_CHIP_HOUR_PRICES[gen])
    chips = topology.total_chips
    out = []
    for region, zones in gcp_data.TPU_REGIONS.get(gen, {}).items():
        if region_filter is not None and region != region_filter:
            continue
        for zone in zones:
            if zone_filter is not None and zone != zone_filter:
                continue
            out.append(
                AcceleratorOffering(
                    cloud='gcp',
                    accelerator=topology.accelerator_name,
                    count=1,
                    region=region,
                    zone=zone,
                    price_hr=price_chip * chips,
                    spot_price_hr=spot_chip * chips,
                    tpu=topology,
                    vram_gb=topology.gen.hbm_gb_per_chip * chips,
                ))
    return out


def _gpu_offerings(name: str,
                   count: int,
                   region_filter: Optional[str] = None,
                   zone_filter: Optional[str] = None
                   ) -> List[AcceleratorOffering]:
    if name not in gcp_data.GPU_OFFERINGS:
        return []
    from skypilot_tpu.catalog import refresh
    price, spot, vram, _family = refresh.gcp_gpu_offering(
        name, gcp_data.GPU_OFFERINGS[name])
    out = []
    for region, zones in gcp_data.GPU_REGIONS.get(name, {}).items():
        if region_filter is not None and region != region_filter:
            continue
        for zone in zones:
            if zone_filter is not None and zone != zone_filter:
                continue
            out.append(
                AcceleratorOffering(
                    cloud='gcp',
                    accelerator=name,
                    count=count,
                    region=region,
                    zone=zone,
                    price_hr=price * count,
                    spot_price_hr=spot * count,
                    vram_gb=float(vram * count),
                ))
    return out


def _fixed_shape_gpu_offerings(cloud: str,
                               name: str,
                               count: int,
                               picked: tuple,
                               regions: Dict[str, tuple],
                               region_filter: Optional[str],
                               zone_filter: Optional[str]
                               ) -> List[AcceleratorOffering]:
    """Offerings for clouds that sell GPUs via fixed instance shapes
    (AWS, Azure): whole-instance prices, one entry per (region, zone)."""
    _instance, price, spot, vram = picked
    out = []
    for region, zones in regions.items():
        if region_filter is not None and region != region_filter:
            continue
        for zone in zones:
            if zone_filter is not None and zone != zone_filter:
                continue
            out.append(
                AcceleratorOffering(
                    cloud=cloud, accelerator=name, count=count,
                    region=region, zone=zone,
                    price_hr=price, spot_price_hr=spot,
                    vram_gb=float(vram * count)))
    return out


def _aws_gpu_offerings(name: str,
                       count: int,
                       region_filter: Optional[str] = None,
                       zone_filter: Optional[str] = None
                       ) -> List[AcceleratorOffering]:
    from skypilot_tpu.catalog import aws_data, refresh
    picked = aws_data.instance_type_for(name, count)
    if picked is None:
        return []
    picked = refresh.aws_gpu_instance(name, count, picked)
    return _fixed_shape_gpu_offerings(
        'aws', name, count, picked, aws_data.GPU_REGIONS.get(name, {}),
        region_filter, zone_filter)


def _azure_gpu_offerings(name: str,
                         count: int,
                         region_filter: Optional[str] = None,
                         zone_filter: Optional[str] = None
                         ) -> List[AcceleratorOffering]:
    from skypilot_tpu.catalog import azure_data
    picked = azure_data.instance_type_for(name, count)
    if picked is None:
        return []
    return _fixed_shape_gpu_offerings(
        'azure', name, count, picked,
        azure_data.GPU_REGIONS.get(name, {}), region_filter, zone_filter)


def _oci_gpu_offerings(name: str,
                       count: int,
                       region_filter: Optional[str] = None,
                       zone_filter: Optional[str] = None
                       ) -> List[AcceleratorOffering]:
    from skypilot_tpu.catalog import oci_data
    picked = oci_data.instance_type_for(name, count)
    if picked is None:
        return []
    return _fixed_shape_gpu_offerings(
        'oci', name, count, picked, oci_data.GPU_REGIONS.get(name, {}),
        region_filter, zone_filter)


def get_offerings(accelerator: str,
                  count: int = 1,
                  *,
                  cloud: Optional[str] = None,
                  num_slices: int = 1,
                  topology: Optional[str] = None,
                  region: Optional[str] = None,
                  zone: Optional[str] = None) -> List[AcceleratorOffering]:
    """All (region, zone, price) offerings for an accelerator request.

    ``cloud=None`` returns offerings across every cataloged cloud;
    'fake' and 'kubernetes' mirror the GCP table ('fake' is
    enable_all_clouds-style offline testing, ref
    tests/common_test_fixtures.py:195; k8s node hardware is priced by
    its GCP lookalike).
    """
    tpu = TpuTopology.maybe_from_accelerator(accelerator,
                                             topology=topology,
                                             num_slices=num_slices)
    out: List[AcceleratorOffering] = []
    if cloud in (None, 'gcp', 'fake', 'kubernetes'):
        if tpu is not None:
            out.extend(_tpu_offerings(tpu, region, zone))
        else:
            out.extend(_gpu_offerings(accelerator, count, region, zone))
    if tpu is None and cloud in (None, 'aws'):
        out.extend(_aws_gpu_offerings(accelerator, count, region, zone))
    if tpu is None and cloud in (None, 'azure'):
        out.extend(_azure_gpu_offerings(accelerator, count, region, zone))
    if tpu is None and cloud in (None, 'oci'):
        out.extend(_oci_gpu_offerings(accelerator, count, region, zone))
    return out


def list_accelerators(name_filter: Optional[str] = None,
                      tpus_only: bool = False) -> Dict[str, List[str]]:
    """name -> sorted regions; for `skyt show-tpus` (ref CLI `show-gpus`,
    sky/client/cli/command.py:4075)."""
    out: Dict[str, List[str]] = {}
    for gen_name, gen in GENERATIONS.items():
        chips = 1
        while chips <= gen.max_chips:
            count = chips * (gen.cores_per_chip
                             if gen.count_unit == 'cores' else 1)
            name = f'tpu-{gen_name}-{count}'
            if name_filter is None or name_filter.lower() in name.lower():
                regions = sorted(gcp_data.TPU_REGIONS.get(gen_name, {}))
                if regions:
                    out[name] = regions
            chips *= 2
    if not tpus_only:
        for name in gcp_data.GPU_OFFERINGS:
            if name_filter is None or name_filter.lower() in name.lower():
                out[name] = sorted(gcp_data.GPU_REGIONS.get(name, {}))
    return out


def get_regions_for_accelerator(accelerator: str) -> List[str]:
    tpu = TpuTopology.maybe_from_accelerator(accelerator)
    if tpu is not None:
        return sorted(gcp_data.TPU_REGIONS.get(tpu.generation, {}))
    return sorted(gcp_data.GPU_REGIONS.get(accelerator, {}))


def get_zones_for_region(accelerator: str, region: str) -> List[str]:
    tpu = TpuTopology.maybe_from_accelerator(accelerator)
    if tpu is not None:
        return list(gcp_data.TPU_REGIONS.get(tpu.generation, {}).get(region, []))
    return list(gcp_data.GPU_REGIONS.get(accelerator, {}).get(region, []))


def validate_region_zone(cloud: str, region: Optional[str],
                         zone: Optional[str]) -> None:
    if cloud not in ('gcp', 'aws', 'azure', 'oci', 'fake', 'local',
                     'kubernetes'):
        raise exceptions.InvalidSpecError(f'Unknown cloud {cloud!r}')
    if region is None:
        return
    if cloud == 'gcp':
        if region not in gcp_data.ALL_GCP_REGIONS:
            raise exceptions.InvalidSpecError(
                f'Unknown GCP region {region!r}. Known: '
                f'{gcp_data.ALL_GCP_REGIONS}')
    elif cloud == 'aws':
        from skypilot_tpu.catalog import aws_data
        if region not in aws_data.ALL_AWS_REGIONS:
            raise exceptions.InvalidSpecError(
                f'Unknown AWS region {region!r}. Known: '
                f'{aws_data.ALL_AWS_REGIONS}')
    elif cloud == 'azure':
        from skypilot_tpu.catalog import azure_data
        if region not in azure_data.ALL_AZURE_REGIONS:
            raise exceptions.InvalidSpecError(
                f'Unknown Azure region {region!r}. Known: '
                f'{azure_data.ALL_AZURE_REGIONS}')
        return  # Azure zones are ordinals ('1'), not region-prefixed
    elif cloud == 'oci':
        from skypilot_tpu.catalog import oci_data
        if region not in oci_data.REGIONS:
            raise exceptions.InvalidSpecError(
                f'Unknown OCI region {region!r}. Known: '
                f'{oci_data.REGIONS}')
        # OCI availability domains are region-prefixed
        # ('us-ashburn-1-AD-1'); fall through to the prefix check.
    else:
        return
    if zone is not None and not zone.startswith(region):
        raise exceptions.InvalidSpecError(
            f'Zone {zone!r} is not in region {region!r}')


def _cpu_tables(cloud: Optional[str]) -> Dict[str, tuple]:
    if cloud == 'aws':
        from skypilot_tpu.catalog import aws_data
        return aws_data.CPU_INSTANCE_TYPES
    if cloud == 'azure':
        from skypilot_tpu.catalog import azure_data
        return azure_data.CPU_INSTANCE_TYPES
    if cloud == 'oci':
        from skypilot_tpu.catalog import oci_data
        return oci_data.CPU_INSTANCE_TYPES
    return gcp_data.CPU_INSTANCE_TYPES


def get_hourly_cost(accelerator: Optional[str],
                    count: int = 1,
                    *,
                    cloud: Optional[str] = None,
                    num_slices: int = 1,
                    use_spot: bool = False,
                    region: Optional[str] = None,
                    cpus: Optional[float] = None,
                    memory: Optional[float] = None) -> float:
    """Estimated $/hr for a node request (0.0 if unknown)."""
    if accelerator is None:
        # Cheapest CPU instance satisfying cpus/memory.
        best = None
        for _name, (vcpu, mem, price) in _cpu_tables(cloud).items():
            if cpus is not None and vcpu < cpus:
                continue
            if memory is not None and mem < memory:
                continue
            if best is None or price < best:
                best = price
        return best if best is not None else 0.097
    offerings = get_offerings(accelerator, count, cloud=cloud,
                              num_slices=num_slices, region=region)
    if not offerings:
        return 0.0
    return min(o.cost(use_spot) for o in offerings)


def pick_cpu_instance_type(cpus: Optional[float],
                           memory: Optional[float],
                           cloud: Optional[str] = None) -> str:
    """Cheapest CPU instance type satisfying the request."""
    best_name, best_price = None, None
    for name, (vcpu, mem, price) in _cpu_tables(cloud).items():
        if cpus is not None and vcpu < cpus:
            continue
        if memory is not None and mem < memory:
            continue
        if best_price is None or price < best_price:
            best_name, best_price = name, price
    if best_name is None:
        raise exceptions.ResourcesUnavailableError(
            f'No CPU instance type with cpus>={cpus}, memory>={memory}')
    return best_name


def default_region(cloud: str) -> str:
    if cloud == 'aws':
        from skypilot_tpu.catalog import aws_data
        return aws_data.DEFAULT_REGION
    if cloud == 'azure':
        from skypilot_tpu.catalog import azure_data
        return azure_data.DEFAULT_REGION
    if cloud == 'oci':
        from skypilot_tpu.catalog import oci_data
        return oci_data.DEFAULT_REGION
    return 'us-central1'
