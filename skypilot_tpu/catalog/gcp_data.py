"""Baked-in GCP TPU/GPU offering data.

Prices are representative on-demand USD per chip-hour (TPU, host VM
included -- TPU-VM pricing bundles the host) or per GPU-hour, from public
GCP pricing pages; spot is the typical preemptible discount. The reference
fetches equivalent data as hosted CSVs (sky/catalog/common.py:193,245).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

# generation -> (price per chip-hr, spot price per chip-hr)
TPU_CHIP_HOUR_PRICES: Dict[str, Tuple[float, float]] = {
    'v2': (1.35 / 4, 0.60 / 4),
    'v3': (2.00 / 4, 0.88 / 4),
    'v4': (3.22, 1.45),
    'v5e': (1.20, 0.54),
    'v5p': (4.20, 1.89),
    'v6e': (2.70, 1.22),
}

# generation -> {region: [zones with TPU capacity]}
TPU_REGIONS: Dict[str, Dict[str, List[str]]] = {
    'v2': {
        'us-central1': ['us-central1-b', 'us-central1-c', 'us-central1-f'],
        'europe-west4': ['europe-west4-a'],
        'asia-east1': ['asia-east1-c'],
    },
    'v3': {
        'us-central1': ['us-central1-a', 'us-central1-b'],
        'europe-west4': ['europe-west4-a'],
    },
    'v4': {
        'us-central2': ['us-central2-b'],
    },
    'v5e': {
        'us-central1': ['us-central1-a', 'us-central1-b'],
        'us-west4': ['us-west4-a', 'us-west4-b'],
        'us-east1': ['us-east1-c'],
        'us-east5': ['us-east5-b'],
        'europe-west4': ['europe-west4-b'],
        'asia-southeast1': ['asia-southeast1-b'],
    },
    'v5p': {
        'us-east5': ['us-east5-a'],
        'us-central1': ['us-central1-a'],
        'europe-west4': ['europe-west4-b'],
    },
    'v6e': {
        'us-east1': ['us-east1-d'],
        'us-east5': ['us-east5-b'],
        'us-central2': ['us-central2-b'],
        'europe-west4': ['europe-west4-a'],
        'asia-northeast1': ['asia-northeast1-b'],
    },
}

# GPU offerings kept minimal so the optimizer can rank TPU against GPU
# (north star: TPUs rank alongside GPUs on cost/availability).
# name -> (price/hr per device, spot price/hr, vram GB, instance family)
GPU_OFFERINGS: Dict[str, Tuple[float, float, int, str]] = {
    'A100': (3.67, 1.10, 40, 'a2-highgpu'),
    'A100-80GB': (5.12, 1.57, 80, 'a2-ultragpu'),
    'H100': (11.06, 3.93, 80, 'a3-highgpu'),
    'L4': (0.70, 0.28, 24, 'g2-standard'),
    'V100': (2.48, 0.74, 16, 'n1-standard'),
    'T4': (0.35, 0.11, 16, 'n1-standard'),
}

GPU_REGIONS: Dict[str, Dict[str, List[str]]] = {
    'A100': {
        'us-central1': ['us-central1-a', 'us-central1-b'],
        'europe-west4': ['europe-west4-a'],
    },
    'A100-80GB': {
        'us-central1': ['us-central1-a'],
        'us-east4': ['us-east4-c'],
    },
    'H100': {
        'us-central1': ['us-central1-a'],
        'us-east4': ['us-east4-a'],
        'europe-west4': ['europe-west4-b'],
    },
    'L4': {
        'us-central1': ['us-central1-a', 'us-central1-b'],
        'us-east1': ['us-east1-b'],
        'europe-west4': ['europe-west4-a'],
    },
    'V100': {
        'us-central1': ['us-central1-a'],
    },
    'T4': {
        'us-central1': ['us-central1-a', 'us-central1-b'],
        'us-east1': ['us-east1-c'],
    },
}

# CPU-only fallback instance types: name -> (vcpus, memory GB, price/hr).
CPU_INSTANCE_TYPES: Dict[str, Tuple[int, float, float]] = {
    'n2-standard-2': (2, 8, 0.097),
    'n2-standard-4': (4, 16, 0.194),
    'n2-standard-8': (8, 32, 0.389),
    'n2-standard-16': (16, 64, 0.777),
    'n2-standard-32': (32, 128, 1.554),
    'n2-highmem-8': (8, 64, 0.524),
}

ALL_GCP_REGIONS: List[str] = sorted(
    {r for gen in TPU_REGIONS.values() for r in gen} |
    {r for acc in GPU_REGIONS.values() for r in acc} |
    {'us-central1', 'us-east1', 'us-west1', 'europe-west4'})
