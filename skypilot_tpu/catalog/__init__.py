"""Hardware catalog: instance/accelerator offerings + pricing.

Parity: ``sky/catalog`` (``common.py:193 read_catalog`` fetches hosted CSVs;
``gcp_catalog.py`` covers TPUs). This rebuild bakes the catalog into the
package (zero-egress, versioned with the code) and makes TPU offerings the
primary citizens: every entry knows its ``TpuTopology`` so the optimizer can
reason about chips/hosts/ICI rather than opaque accelerator strings.
"""
from skypilot_tpu.catalog.common import (
    AcceleratorOffering,
    get_hourly_cost,
    get_offerings,
    get_regions_for_accelerator,
    get_zones_for_region,
    list_accelerators,
    validate_region_zone,
)

__all__ = [
    'AcceleratorOffering',
    'get_hourly_cost',
    'get_offerings',
    'get_regions_for_accelerator',
    'get_zones_for_region',
    'list_accelerators',
    'validate_region_zone',
]
