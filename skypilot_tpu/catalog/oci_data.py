"""OCI catalog data: CPU/GPU shapes + regions (public list prices,
ballpark — parity: the reference's OCI catalog CSVs,
``sky/catalog/data_fetchers/fetch_oci.py``).

OCI's native model is FLEX shapes (pay per OCPU+GB); the catalog keeps
a few fixed presets so the optimizer can rank concrete offerings like
it does for every other cloud. 1 OCPU = 2 vCPUs on E-series.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

DEFAULT_REGION = 'us-ashburn-1'

REGIONS: List[str] = [
    'us-ashburn-1', 'us-phoenix-1', 'eu-frankfurt-1', 'uk-london-1',
    'ap-tokyo-1',
]

# name -> (vcpus, memory_gb, $/hr): E5.Flex presets at public
# per-OCPU/per-GB list price (0.03/OCPU + 0.002/GB ballpark).
CPU_INSTANCE_TYPES: Dict[str, Tuple[int, float, float]] = {
    'VM.Standard.E5.Flex-2-16': (2, 16.0, 0.062),
    'VM.Standard.E5.Flex-4-32': (4, 32.0, 0.124),
    'VM.Standard.E5.Flex-8-64': (8, 64.0, 0.248),
    'VM.Standard.E5.Flex-16-128': (16, 128.0, 0.496),
    'VM.Standard.E5.Flex-32-256': (32, 256.0, 0.992),
}

# accelerator -> count -> (shape, $/hr on-demand, $/hr spot, vram/GPU).
# OCI calls spot 'preemptible capacity' (50% of on-demand list).
GPU_INSTANCE_TYPES: Dict[str, Dict[int, Tuple[str, float, float, int]]] = {
    'A10': {
        1: ('VM.GPU.A10.1', 2.0, 1.0, 24),
        2: ('VM.GPU.A10.2', 4.0, 2.0, 24),
    },
    'A100-80GB': {
        8: ('BM.GPU.A100-v2.8', 32.0, 16.0, 80),
    },
    'H100': {
        8: ('BM.GPU.H100.8', 80.0, 40.0, 80),
    },
}

GPU_REGIONS: Dict[str, Dict[str, List[str]]] = {
    'A10': {r: [f'{r}-AD-1'] for r in REGIONS},
    'A100-80GB': {r: [f'{r}-AD-1'] for r in
                  ('us-ashburn-1', 'us-phoenix-1', 'eu-frankfurt-1')},
    'H100': {r: [f'{r}-AD-1'] for r in ('us-ashburn-1',)},
}


def instance_type_for(accelerator: str, count: int):
    """(shape, on_demand $/hr, spot $/hr) or None."""
    table = GPU_INSTANCE_TYPES.get(accelerator)
    if not table:
        return None
    return table.get(count)
