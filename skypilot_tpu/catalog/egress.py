"""Per-cloud-pair egress pricing ($/GB) for the optimizer's joint plans.

Replaces the flat ``EGRESS_PRICE_PER_GB = 0.08`` (VERDICT r5 weak #6):
a cross-cloud edge leaves through the SOURCE cloud's internet-egress
tier, which is several times the intra-cloud inter-region rate — a
joint plan that prices both at one number co-locates (or splits) tasks
wrongly exactly when egress dominates.

Rates are public list-price ballpark figures (continental tiers,
volume discounts and free allowances ignored — the optimizer needs the
RELATIVE ordering of edges right, not an invoice):

* intra-cloud = the provider's inter-region transfer rate;
* cross-cloud = the source provider's internet-egress rate (egress is
  billed by the sending side; ingress is free on all four).

On-prem/BYO placements (``local``/``slurm``/``ssh``) send for free;
data leaving a metered cloud toward them still pays the source's
internet tier (the cloud bills what crosses its boundary, regardless
of who receives it).
"""
from __future__ import annotations

from typing import Optional

# Fallback when the source cloud is unknown (legacy callers, hints
# without a cloud) — the historical flat GCP inter-region ballpark.
DEFAULT_EGRESS_PER_GB = 0.08

# Providers with no metered egress (user-owned networks).
_FREE_CLOUDS = frozenset({'local', 'slurm', 'ssh', 'kubernetes'})

# $/GB moving data BETWEEN REGIONS of one cloud.
_INTRA_CLOUD = {
    'gcp': 0.08,     # inter-region (intercontinental ballpark)
    'aws': 0.02,     # inter-region transfer
    'azure': 0.02,   # cross-region (intra-continent)
    'oci': 0.0085,   # oci inter-region is near its internet rate
}

# $/GB leaving a cloud to the internet (== to another cloud).
_INTERNET = {
    'gcp': 0.12,
    'aws': 0.09,
    'azure': 0.087,
    'oci': 0.0085,   # after the free tier; by far the cheapest egress
}


def egress_price_per_gb(src_cloud: Optional[str],
                        dst_cloud: Optional[str]) -> float:
    """$/GB for one GB moving src→dst across a region boundary.

    Same-region transfers cost 0 — callers check region equality before
    pricing the edge (this function prices the cheapest *boundary*
    crossing for the pair)."""
    src = (src_cloud or '').lower()
    dst = (dst_cloud or '').lower()
    if src in _FREE_CLOUDS:
        return 0.0                     # user-owned network sends free
    if not src:
        return DEFAULT_EGRESS_PER_GB
    if dst in _FREE_CLOUDS:
        # Leaving a metered cloud TOWARD a user-owned network still
        # bills the source's internet-egress tier — only the receiving
        # side is free.
        return _INTERNET.get(src, DEFAULT_EGRESS_PER_GB)
    if src == dst:
        return _INTRA_CLOUD.get(src, DEFAULT_EGRESS_PER_GB)
    return _INTERNET.get(src, DEFAULT_EGRESS_PER_GB)


def serving_hop_price_per_gb(src_cloud: Optional[str],
                             src_region: Optional[str],
                             dst_cloud: Optional[str],
                             dst_region: Optional[str]) -> float:
    """$/GB for serve-replica traffic flowing from a replica placed in
    ``(src_cloud, src_region)`` back to the service's home region
    (where the load balancer/users sit). Same cloud AND same region is
    free (in-region transfer); everything else prices the boundary
    crossing via :func:`egress_price_per_gb` — billed by the sending
    (replica) side. The serve mix policy folds this into a domain's
    effective $/replica-hour (mix_policy.MixPolicy.domain_price)."""
    same_cloud = (src_cloud or '').lower() == (dst_cloud or '').lower()
    if same_cloud and src_region is not None and src_region == dst_region:
        return 0.0
    return egress_price_per_gb(src_cloud, dst_cloud)
