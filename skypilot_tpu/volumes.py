"""Volumes: named persistent storage objects, attachable to clusters.

Parity: ``sky/volumes/`` (Volume model volume.py:25, server ops
server/core.py: volume_apply :305 / volume_list :170 / volume_delete :248
/ volume_refresh :29) and the ``sky volumes`` CLI group (command.py:5435).

TPU-native stance: the volume types that matter on our two providers are
Kubernetes PVCs (GKE TPU pods) and host-path-backed volumes on the
fake/local providers (tests + dev); GCE persistent disks are modeled for
the GCP provider's CPU controller VMs. A volume is created once, recorded
in the state DB, mounted into any number of clusters via the task's
``volumes:`` section, and deleted only when no UP cluster uses it.
"""
from __future__ import annotations

import enum
import json
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions, state
from skypilot_tpu.utils import log

logger = log.init_logger(__name__)


class VolumeType(enum.Enum):
    PVC = 'k8s-pvc'
    HOSTPATH = 'hostpath'
    GCE_PD = 'gce-pd'


class VolumeStatus(enum.Enum):
    READY = 'READY'
    IN_USE = 'IN_USE'


_TYPE_TO_CLOUD = {
    VolumeType.PVC: 'kubernetes',
    VolumeType.HOSTPATH: 'fake',
    VolumeType.GCE_PD: 'gcp',
}


class Volume:
    """A volume spec (parity: volumes/volume.py:25 Volume)."""

    def __init__(self,
                 name: str,
                 type: str,  # pylint: disable=redefined-builtin
                 size_gb: int = 10,
                 cloud: Optional[str] = None,
                 region: Optional[str] = None,
                 zone: Optional[str] = None,
                 labels: Optional[Dict[str, str]] = None,
                 use_existing: bool = False,
                 config: Optional[Dict[str, Any]] = None) -> None:
        if not name:
            raise exceptions.InvalidSpecError('volume needs a name')
        self.name = name
        try:
            self.type = VolumeType(type)
        except ValueError:
            raise exceptions.InvalidSpecError(
                f'Unknown volume type {type!r}; one of '
                f'{[t.value for t in VolumeType]}') from None
        self.size_gb = int(size_gb)
        self.cloud = cloud or _TYPE_TO_CLOUD[self.type]
        self.region = region
        self.zone = zone
        self.labels = dict(labels or {})
        self.use_existing = use_existing
        self.config = dict(config or {})

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Volume':
        config = dict(config)
        size = config.pop('size', None)
        if size is not None:
            config['size_gb'] = int(str(size).rstrip('GgiB '))
        return cls(**config)

    def to_yaml_config(self) -> Dict[str, Any]:
        return {
            'name': self.name,
            'type': self.type.value,
            'size_gb': self.size_gb,
            'cloud': self.cloud,
            'region': self.region,
            'zone': self.zone,
            'labels': self.labels,
            'use_existing': self.use_existing,
            'config': self.config,
        }


# -- state (volumes table lives next to clusters/storage) --------------


def _db():
    return state.volumes_db()


def _record_to_dict(row) -> Dict[str, Any]:
    return {
        'name': row['name'],
        'type': row['type'],
        'cloud': row['cloud'],
        'region': row['region'],
        'zone': row['zone'],
        'size_gb': row['size_gb'],
        'status': row['status'],
        'config': json.loads(row['config'] or '{}'),
        'created_at': row['created_at'],
        'last_attached': row['last_attached'],
        'attached_to': json.loads(row['attached_to'] or '[]'),
    }


# -- ops ---------------------------------------------------------------


def apply(volume: Volume) -> Dict[str, Any]:
    """Create (or adopt, when use_existing) a volume; idempotent.

    Parity: volumes/server/core.py:305 volume_apply.
    """
    db = _db()
    row = db.execute('SELECT * FROM volumes WHERE name=?',
                     (volume.name,)).fetchone()
    if row is not None:
        return _record_to_dict(row)
    from skypilot_tpu.provision.api import get_provider
    provider = get_provider(volume.cloud)
    if not hasattr(provider, 'create_volume'):
        raise exceptions.NotSupportedError(
            f'Provider {volume.cloud!r} does not support volumes.')
    provider_config = provider.create_volume(volume)
    merged = {**volume.config, **provider_config}
    db.execute(
        'INSERT INTO volumes (name, type, cloud, region, zone, size_gb, '
        'status, config, created_at) VALUES (?,?,?,?,?,?,?,?,?)',
        (volume.name, volume.type.value, volume.cloud, volume.region,
         volume.zone, volume.size_gb, VolumeStatus.READY.value,
         json.dumps(merged), time.time()))
    db.commit()
    logger.info('Volume %s (%s, %dGiB) ready', volume.name,
                volume.type.value, volume.size_gb)
    return get(volume.name)


def get(name: str) -> Dict[str, Any]:
    row = _db().execute('SELECT * FROM volumes WHERE name=?',
                        (name,)).fetchone()
    if row is None:
        raise exceptions.StorageError(f'Volume {name!r} does not exist.')
    return _record_to_dict(row)


def ls() -> List[Dict[str, Any]]:
    rows = _db().execute(
        'SELECT * FROM volumes ORDER BY created_at').fetchall()
    return [_record_to_dict(r) for r in rows]


def delete(name: str) -> None:
    """Delete a volume; refused while any live cluster has it attached.

    Parity: volumes/server/core.py:248 volume_delete.
    """
    record = get(name)
    attached = _live_attachments(record)
    if attached:
        raise exceptions.StorageError(
            f'Volume {name!r} is attached to cluster(s) {attached}; '
            f'tear them down first.')
    from skypilot_tpu.provision.api import get_provider
    provider = get_provider(record['cloud'])
    if hasattr(provider, 'delete_volume'):
        provider.delete_volume(record)
    db = _db()
    db.execute('DELETE FROM volumes WHERE name=?', (name,))
    db.commit()


def refresh() -> List[Dict[str, Any]]:
    """Reconcile IN_USE/READY with actual cluster liveness (parity:
    volumes/server/core.py:29 volume_refresh, run by the server daemon)."""
    out = []
    db = _db()
    for record in ls():
        attached = _live_attachments(record)
        status = (VolumeStatus.IN_USE if attached else
                  VolumeStatus.READY).value
        if status != record['status'] or attached != record['attached_to']:
            db.execute(
                'UPDATE volumes SET status=?, attached_to=? WHERE name=?',
                (status, json.dumps(attached), record['name']))
            db.commit()
            record = get(record['name'])
        out.append(record)
    return out


def _live_attachments(record: Dict[str, Any]) -> List[str]:
    live = []
    for cluster_name in record['attached_to']:
        cluster = state.get_cluster(cluster_name)
        if cluster is not None and cluster.status != state.ClusterStatus.INIT:
            live.append(cluster_name)
    return live


def note_attached(name: str, cluster_name: str) -> None:
    record = get(name)
    attached = set(record['attached_to'])
    attached.add(cluster_name)
    db = _db()
    db.execute(
        'UPDATE volumes SET status=?, attached_to=?, last_attached=? '
        'WHERE name=?',
        (VolumeStatus.IN_USE.value, json.dumps(sorted(attached)),
         time.time(), name))
    db.commit()


def mount_commands(name: str, mount_path: str) -> List[str]:
    """Shell commands that make the volume visible at mount_path on a
    host (run on every host during setup)."""
    record = get(name)
    from skypilot_tpu.provision.api import get_provider
    provider = get_provider(record['cloud'])
    if hasattr(provider, 'volume_mount_commands'):
        return provider.volume_mount_commands(record, mount_path)
    raise exceptions.NotSupportedError(
        f'Provider {record["cloud"]!r} cannot mount volumes via commands.')
