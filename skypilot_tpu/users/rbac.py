"""Role-based access control: a static permission matrix.

Parity: ``sky/users/permission.py:44`` (casbin enforcer over role->route
policies) and ``sky/users/rbac.py`` (role definitions). The rebuild keeps
the same two built-in roles and encodes the policy as data. Scope today:
user administration is admin-gated; payload routes (launch/serve/...) and
reads are open to ANY authenticated user (same default as the reference's
rbac.get_default_user_blocklist -- only user/workspace admin is blocked).
Workspace actions are listed here and enforced by the workspaces module.
"""
from __future__ import annotations

from typing import Optional

from skypilot_tpu.users.users_db import ROLE_ADMIN, ROLE_USER, UserRecord

# Actions a plain (non-admin) user may NOT perform.
_ADMIN_ONLY = frozenset({
    'users.create', 'users.delete', 'users.set_role', 'users.token.other',
    'workspaces.create', 'workspaces.delete', 'workspaces.update',
})


def check_permission(user: Optional[UserRecord], action: str) -> bool:
    """True when `user` may perform `action`.

    ``None`` user means auth is disabled (single-user deployment): allow
    everything, same as the reference with no auth middlewares installed.
    """
    if user is None:
        return True
    if user.role == ROLE_ADMIN:
        return True
    return action not in _ADMIN_ONLY


def require_permission(user: Optional[UserRecord], action: str) -> None:
    if not check_permission(user, action):
        raise PermissionError(
            f'user {user.name!r} (role {user.role}) may not {action}')


# -- per-workspace bindings (parity: sky/users/permission.py's
# workspace-scoped casbin policies) -----------------------------------------

# binding role -> workspace actions it grants
_WS_GRANTS = {
    'viewer': frozenset({'view'}),
    'editor': frozenset({'view', 'use'}),
    'admin': frozenset({'view', 'use', 'admin'}),
}


def workspace_role(user: Optional[UserRecord],
                   workspace: str) -> Optional[str]:
    if user is None:
        return None
    from skypilot_tpu.users import users_db
    return users_db.get_workspace_role(workspace, user.name)


def check_workspace_access(user: Optional[UserRecord], workspace: str,
                           action: str = 'use') -> bool:
    """True when `user` may perform `action` ('view'|'use'|'admin') in
    `workspace`.

    A workspace with NO bindings is open to every authenticated user
    (the pre-bindings behavior — bindings are opt-in per workspace); the
    moment any binding exists, membership is required. Global admins
    always pass; ``None`` user = auth disabled = allow.
    """
    if user is None:
        return True
    if user.role == ROLE_ADMIN:
        return True
    from skypilot_tpu.users import users_db
    bindings = users_db.list_workspace_roles(workspace)
    if not bindings:
        return True
    role = users_db.get_workspace_role(workspace, user.name)
    if role is None:
        return False
    return action in _WS_GRANTS.get(role, frozenset())


def require_workspace_access(user: Optional[UserRecord], workspace: str,
                             action: str = 'use') -> None:
    if not check_workspace_access(user, workspace, action):
        raise PermissionError(
            f'user {user.name!r} has no {action!r} access to workspace '
            f'{workspace!r} (ask a workspace admin for a role binding)')
