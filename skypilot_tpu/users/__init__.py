"""Users, service-account tokens, and role-based access control.

Parity: ``sky/users/`` (permission.py:44 PermissionService casbin
enforcer, rbac.py roles, token_service.py). Rebuilt small: a sqlite users
table with salted-hash bearer tokens and a two-role model
(admin/user) enforced in the API server -- no casbin, the policy matrix
is a dict.
"""
from skypilot_tpu.users.users_db import (ROLE_ADMIN, ROLE_USER, UserRecord,
                                         authenticate, create_token,
                                         create_user, delete_user, get_user,
                                         list_users, set_role)
from skypilot_tpu.users.rbac import check_permission

__all__ = [
    'ROLE_ADMIN', 'ROLE_USER', 'UserRecord', 'authenticate', 'check_permission',
    'create_token', 'create_user', 'delete_user', 'get_user', 'list_users',
    'set_role',
]
