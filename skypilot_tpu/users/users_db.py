"""User + service-account-token store (sqlite).

Parity: ``sky/users/token_service.py`` (token mint/verify) and the users
table of ``sky/global_user_state.py``. Tokens are ``skyt_<id>_<secret>``;
only a salted SHA-256 of the secret is stored, verification is
constant-time. A token authenticates as its owning user; roles gate
mutating routes (rbac.py).
"""
from __future__ import annotations

import dataclasses
import hashlib
import hmac
import os
import secrets
import sqlite3
import threading
import time
from typing import List, Optional

ROLE_ADMIN = 'admin'
ROLE_USER = 'user'
# Service accounts (parity: sky/users/token_service.py SA tokens):
# machine principals — tokens may carry an expiry, and they never hold
# admin rights regardless of bindings.
ROLE_SERVICE = 'service'
_ROLES = (ROLE_ADMIN, ROLE_USER, ROLE_SERVICE)

# Per-workspace binding roles (parity: sky/users/permission.py's
# casbin policies keyed on workspace).
WS_ROLE_ADMIN = 'admin'
WS_ROLE_EDITOR = 'editor'
WS_ROLE_VIEWER = 'viewer'
_WS_ROLES = (WS_ROLE_ADMIN, WS_ROLE_EDITOR, WS_ROLE_VIEWER)

TOKEN_PREFIX = 'skyt'


def _state_dir() -> str:
    return os.environ.get('SKYT_STATE_DIR',
                          os.path.expanduser('~/.skyt'))


_local = threading.local()


def _db() -> sqlite3.Connection:
    path = os.path.join(_state_dir(), 'users.db')
    conn = getattr(_local, 'conn', None)
    if conn is not None and getattr(_local, 'path', None) == path:
        return conn
    os.makedirs(_state_dir(), exist_ok=True)
    conn = sqlite3.connect(path, timeout=10)
    conn.row_factory = sqlite3.Row
    from skypilot_tpu.utils import pg as _pg_lib
    _pg_lib.enable_wal(conn)
    conn.executescript("""
        CREATE TABLE IF NOT EXISTS users (
            name TEXT PRIMARY KEY,
            role TEXT NOT NULL,
            created_at REAL NOT NULL
        );
        CREATE TABLE IF NOT EXISTS tokens (
            token_id TEXT PRIMARY KEY,
            user_name TEXT NOT NULL,
            salt TEXT NOT NULL,
            secret_hash TEXT NOT NULL,
            label TEXT,
            created_at REAL NOT NULL,
            last_used_at REAL
        );
        CREATE TABLE IF NOT EXISTS workspace_roles (
            workspace TEXT NOT NULL,
            user_name TEXT NOT NULL,
            role TEXT NOT NULL,
            PRIMARY KEY (workspace, user_name)
        );
    """)
    try:  # migration: token expiry (column added after first release)
        conn.execute('ALTER TABLE tokens ADD COLUMN expires_at REAL')
    except sqlite3.OperationalError:
        pass
    conn.commit()
    _local.conn = conn
    _local.path = path
    return conn


@dataclasses.dataclass
class UserRecord:
    name: str
    role: str
    created_at: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def create_user(name: str, role: str = ROLE_USER) -> UserRecord:
    if role not in _ROLES:
        raise ValueError(f'unknown role {role!r} (expected one of {_ROLES})')
    # '|' is the session-cookie payload delimiter (sessions.py) — an
    # ambiguous encoding must never be signed.
    if not name or '/' in name or '|' in name:
        raise ValueError(f'invalid user name {name!r}')
    if name == 'operator':
        # Reserved: the static deployment token's synthetic admin
        # identity — a DB row with this name would let its session
        # cookie escalate to admin.
        raise ValueError("'operator' is a reserved name (the static "
                         'deployment token identity)')
    conn = _db()
    now = time.time()
    try:
        conn.execute(
            'INSERT INTO users (name, role, created_at) VALUES (?, ?, ?)',
            (name, role, now))
    except sqlite3.IntegrityError as e:
        # The failed INSERT opened a write transaction on this
        # thread's connection; release the write lock before raising.
        conn.rollback()
        raise ValueError(f'user {name!r} already exists') from e
    conn.commit()
    return UserRecord(name=name, role=role, created_at=now)


def get_user(name: str) -> Optional[UserRecord]:
    row = _db().execute('SELECT * FROM users WHERE name = ?',
                        (name,)).fetchone()
    if row is None:
        return None
    return UserRecord(name=row['name'], role=row['role'],
                      created_at=row['created_at'])


def list_users() -> List[UserRecord]:
    rows = _db().execute('SELECT * FROM users ORDER BY name').fetchall()
    return [UserRecord(name=r['name'], role=r['role'],
                       created_at=r['created_at']) for r in rows]


def set_role(name: str, role: str) -> None:
    if role not in _ROLES:
        raise ValueError(f'unknown role {role!r}')
    conn = _db()
    cur = conn.execute('UPDATE users SET role = ? WHERE name = ?',
                       (role, name))
    if cur.rowcount == 0:
        # The no-op UPDATE still opened a transaction — close it
        # before raising or the write lock outlives the call.
        conn.rollback()
        raise ValueError(f'no user {name!r}')
    conn.commit()


def delete_user(name: str) -> None:
    conn = _db()
    conn.execute('DELETE FROM users WHERE name = ?', (name,))
    conn.execute('DELETE FROM tokens WHERE user_name = ?', (name,))
    conn.commit()


def _hash(secret: str, salt: str) -> str:
    return hashlib.sha256(f'{salt}:{secret}'.encode()).hexdigest()


def create_token(user_name: str, label: str = '',
                 expires_seconds: Optional[float] = None) -> str:
    """Mint a bearer token for a user; the cleartext is returned ONCE.

    ``expires_seconds`` bounds the token's life (service-account
    hygiene); None = no expiry (human tokens, revocable by id).
    """
    if get_user(user_name) is None:
        raise ValueError(f'no user {user_name!r}')
    token_id = secrets.token_hex(4)
    secret = secrets.token_urlsafe(24)
    salt = secrets.token_hex(8)
    expires_at = (time.time() + expires_seconds
                  if expires_seconds else None)
    conn = _db()
    conn.execute(
        'INSERT INTO tokens (token_id, user_name, salt, secret_hash, label, '
        'created_at, expires_at) VALUES (?, ?, ?, ?, ?, ?, ?)',
        (token_id, user_name, salt, _hash(secret, salt), label,
         time.time(), expires_at))
    conn.commit()
    return f'{TOKEN_PREFIX}_{token_id}_{secret}'


def create_service_account(name: str, label: str = '',
                           expires_seconds: Optional[float] = None
                           ) -> tuple:
    """(UserRecord, token): a machine principal + its bearer token in
    one step (parity: sky/users/token_service.py service accounts)."""
    user = get_user(name)
    if user is None:
        user = create_user(name, ROLE_SERVICE)
    elif user.role != ROLE_SERVICE:
        raise ValueError(f'{name!r} exists and is not a service account')
    token = create_token(name, label or 'service-account',
                         expires_seconds)
    return user, token


def authenticate(token: str) -> Optional[UserRecord]:
    """Token -> user, or None. Constant-time secret comparison;
    expired tokens never authenticate."""
    parts = token.split('_', 2)
    if len(parts) != 3 or parts[0] != TOKEN_PREFIX:
        return None
    _, token_id, secret = parts
    conn = _db()
    row = conn.execute('SELECT * FROM tokens WHERE token_id = ?',
                       (token_id,)).fetchone()
    if row is None:
        return None
    if not hmac.compare_digest(_hash(secret, row['salt']),
                               row['secret_hash']):
        return None
    expires_at = row['expires_at'] if 'expires_at' in row.keys() else None
    if expires_at is not None and time.time() > expires_at:
        return None
    conn.execute('UPDATE tokens SET last_used_at = ? WHERE token_id = ?',
                 (time.time(), token_id))
    conn.commit()
    return get_user(row['user_name'])


def list_tokens(user_name: Optional[str] = None) -> List[dict]:
    q = 'SELECT token_id, user_name, label, created_at, last_used_at FROM tokens'
    args: tuple = ()
    if user_name:
        q += ' WHERE user_name = ?'
        args = (user_name,)
    return [dict(r) for r in _db().execute(q, args).fetchall()]


def revoke_token(token_id: str) -> bool:
    conn = _db()
    cur = conn.execute('DELETE FROM tokens WHERE token_id = ?', (token_id,))
    conn.commit()
    return cur.rowcount > 0


# -- per-workspace role bindings -------------------------------------------

def set_workspace_role(workspace: str, user_name: str, role: str) -> None:
    if role not in _WS_ROLES:
        raise ValueError(
            f'unknown workspace role {role!r} (expected {_WS_ROLES})')
    user = get_user(user_name)
    if user is None:
        raise ValueError(f'no user {user_name!r}')
    if user.role == ROLE_SERVICE and role == WS_ROLE_ADMIN:
        # Machine principals never administer workspaces (they could
        # then grant/revoke human bindings).
        raise ValueError(
            f'service account {user_name!r} cannot be a workspace '
            "admin (use 'editor' or 'viewer')")
    conn = _db()
    # Portable upsert (skylint SKYT007): ON CONFLICT .. DO UPDATE
    # needs sqlite >= 3.24 — the same runner class that PR 2's
    # UPDATE..RETURNING outage hit. UPDATE, INSERT on miss, and if a
    # concurrent writer wins the INSERT race, re-UPDATE so both
    # callers succeed (matching the old upsert's no-error semantics).
    cur = conn.execute(
        'UPDATE workspace_roles SET role = ? WHERE workspace = ? '
        'AND user_name = ?', (role, workspace, user_name))
    if cur.rowcount == 0:
        try:
            conn.execute(
                'INSERT INTO workspace_roles (workspace, user_name, '
                'role) VALUES (?, ?, ?)', (workspace, user_name, role))
        except sqlite3.IntegrityError:
            conn.execute(
                'UPDATE workspace_roles SET role = ? WHERE '
                'workspace = ? AND user_name = ?',
                (role, workspace, user_name))
    conn.commit()


def remove_workspace_role(workspace: str, user_name: str) -> bool:
    conn = _db()
    cur = conn.execute(
        'DELETE FROM workspace_roles WHERE workspace = ? AND '
        'user_name = ?', (workspace, user_name))
    conn.commit()
    return cur.rowcount > 0


def get_workspace_role(workspace: str, user_name: str) -> Optional[str]:
    row = _db().execute(
        'SELECT role FROM workspace_roles WHERE workspace = ? AND '
        'user_name = ?', (workspace, user_name)).fetchone()
    return row['role'] if row else None


def list_workspace_roles(workspace: Optional[str] = None) -> List[dict]:
    q = 'SELECT workspace, user_name, role FROM workspace_roles'
    args: tuple = ()
    if workspace:
        q += ' WHERE workspace = ?'
        args = (workspace,)
    q += ' ORDER BY workspace, user_name'
    return [dict(r) for r in _db().execute(q, args).fetchall()]
