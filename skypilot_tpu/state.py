"""Global user state: cluster records + events in sqlite OR Postgres.

Parity: ``sky/global_user_state.py`` (SQLAlchemy over sqlite/postgres,
tables at :68-103). No ORM dependency in the image: the default backend
is plain sqlite3; setting ``SKYT_DB_URL=postgres://user:pw@host/db``
switches to a shared Postgres (utils/pg.py stdlib wire client) so
multiple API-server replicas can serve one deployment (the HA story the
helm chart's single-PVC mode can't give). The ``?``-placeholder SQL
here is written in the common dialect; ``utils/pg.PgSqliteAdapter``
translates the few sqlite-isms (AUTOINCREMENT, PRAGMA) on the way out.
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import events


class ClusterStatus(enum.Enum):
    """Cluster lifecycle (design parity: sky/design_docs/cluster_status.md)."""
    INIT = 'INIT'          # provisioning or unhealthy
    UP = 'UP'              # all hosts running, runtime healthy
    STOPPED = 'STOPPED'    # instances stopped, disks kept


def _state_dir() -> str:
    return os.environ.get('SKYT_STATE_DIR',
                          os.path.expanduser('~/.skyt'))


_local = threading.local()
# (url, pid) pairs whose shared-DB schema this process already ensured.
_pg_schema_ready: set = set()


def db_url() -> Optional[str]:
    """Postgres DSN when the deployment uses a shared DB, else None."""
    return os.environ.get('SKYT_DB_URL') or None


def _db():
    """Per-thread dual-backend connection (sqlite default, shared
    Postgres via SKYT_DB_URL) — utils/pg.connect_dual_backend holds the
    caching/fork/schema-gate logic shared with jobs/state."""
    from skypilot_tpu.utils import pg

    def init_schema(conn) -> None:
        from skypilot_tpu.utils import pg as _pg_lib
        _pg_lib.enable_wal(conn)
        conn.executescript("""
            CREATE TABLE IF NOT EXISTS clusters (
                name TEXT PRIMARY KEY,
                status TEXT NOT NULL,
                cloud TEXT,
                region TEXT,
                zone TEXT,
                resources TEXT,            -- Resources yaml-config JSON
                handle TEXT,               -- serialized ClusterInfo JSON
                num_nodes INTEGER DEFAULT 1,
                autostop TEXT,
                launched_at REAL,
                last_use REAL,
                owner TEXT,
                hourly_cost REAL DEFAULT 0,
                workspace TEXT DEFAULT 'default'
            );
            CREATE TABLE IF NOT EXISTS cluster_events (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                cluster_name TEXT NOT NULL,
                ts REAL NOT NULL,
                event TEXT NOT NULL,
                detail TEXT
            );
            CREATE TABLE IF NOT EXISTS storage (
                name TEXT PRIMARY KEY,
                store_type TEXT,
                source TEXT,
                status TEXT,
                created_at REAL
            );
            CREATE TABLE IF NOT EXISTS volumes (
                name TEXT PRIMARY KEY,
                type TEXT NOT NULL,
                cloud TEXT,
                region TEXT,
                zone TEXT,
                size_gb INTEGER,
                status TEXT,
                config TEXT,               -- provider-specific JSON
                attached_to TEXT,          -- JSON list of cluster names
                created_at REAL,
                last_attached REAL
            );
        """)
        cols = {r['name'] for r in
                conn.execute('PRAGMA table_info(clusters)')}
        if 'workspace' not in cols:  # pre-existing older DB
            common_utils.add_column_if_missing(
                conn, "ALTER TABLE clusters ADD COLUMN workspace TEXT "
                "DEFAULT 'default'")
        conn.commit()

    return pg.connect_dual_backend(
        _local, _pg_schema_ready, url=db_url(),
        sqlite_path=os.path.join(_state_dir(), 'state.db'),
        init_schema=init_schema)


def change_signal() -> 'events.ExternalSignal | None':
    """Cross-process change signal for the cluster state DB: managed-job
    controllers wake on preemption/health/teardown writes made by other
    processes (the fake provider's chaos hooks, request children, peer
    controllers) within milliseconds instead of their poll interval."""
    return events.external_signal(
        db_url(), os.path.join(_state_dir(), 'state.db'), events.CLUSTERS)


class ClusterRecord:
    """A row of the clusters table, attribute-accessible."""

    def __init__(self, row: sqlite3.Row) -> None:
        self.name: str = row['name']
        self.status = ClusterStatus(row['status'])
        self.cloud: Optional[str] = row['cloud']
        self.region: Optional[str] = row['region']
        self.zone: Optional[str] = row['zone']
        self.resources: Dict[str, Any] = json.loads(row['resources'] or '{}')
        self.handle: Dict[str, Any] = json.loads(row['handle'] or '{}')
        self.num_nodes: int = row['num_nodes']
        self.autostop: Dict[str, Any] = json.loads(row['autostop'] or '{}')
        self.launched_at: Optional[float] = row['launched_at']
        self.last_use: Optional[float] = row['last_use']
        self.owner: Optional[str] = row['owner']
        self.hourly_cost: float = row['hourly_cost'] or 0.0
        self.workspace: str = row['workspace'] or 'default'

    def to_dict(self) -> Dict[str, Any]:
        return {
            'name': self.name,
            'status': self.status.value,
            'cloud': self.cloud,
            'region': self.region,
            'zone': self.zone,
            'resources': self.resources,
            'num_nodes': self.num_nodes,
            'autostop': self.autostop,
            'launched_at': self.launched_at,
            'last_use': self.last_use,
            'owner': self.owner,
            'hourly_cost': self.hourly_cost,
            'workspace': self.workspace,
        }


def volumes_db() -> sqlite3.Connection:
    """The shared state DB, exposed for the volumes table (volumes.py)."""
    return _db()


def add_or_update_cluster(name: str,
                          *,
                          status: ClusterStatus,
                          cloud: Optional[str] = None,
                          region: Optional[str] = None,
                          zone: Optional[str] = None,
                          resources: Optional[Dict[str, Any]] = None,
                          handle: Optional[Dict[str, Any]] = None,
                          num_nodes: Optional[int] = None,
                          autostop: Optional[Dict[str, Any]] = None,
                          hourly_cost: Optional[float] = None,
                          touch: bool = True) -> None:
    db = _db()
    existing = db.execute('SELECT * FROM clusters WHERE name=?',
                          (name,)).fetchone()
    now = time.time()
    if existing is None:
        from skypilot_tpu import workspaces
        db.execute(
            'INSERT INTO clusters (name, status, cloud, region, zone, '
            'resources, handle, num_nodes, autostop, launched_at, last_use, '
            'owner, hourly_cost, workspace) '
            'VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?)',
            (name, status.value, cloud, region, zone,
             json.dumps(resources or {}), json.dumps(handle or {}),
             num_nodes or 1, json.dumps(autostop or {}), now, now,
             common_utils.get_user(), hourly_cost or 0.0,
             workspaces.active_workspace()))
    else:
        updates: Dict[str, Any] = {'status': status.value}
        if cloud is not None:
            updates['cloud'] = cloud
        if region is not None:
            updates['region'] = region
        if zone is not None:
            updates['zone'] = zone
        if resources is not None:
            updates['resources'] = json.dumps(resources)
        if handle is not None:
            updates['handle'] = json.dumps(handle)
        if num_nodes is not None:
            updates['num_nodes'] = num_nodes
        if autostop is not None:
            updates['autostop'] = json.dumps(autostop)
        if hourly_cost is not None:
            updates['hourly_cost'] = hourly_cost
        if touch:
            updates['last_use'] = now
        sets = ', '.join(f'{k}=?' for k in updates)
        db.execute(f'UPDATE clusters SET {sets} WHERE name=?',
                   (*updates.values(), name))
    db.commit()
    events.publish(events.CLUSTERS, conn=db)


def get_cluster(name: str) -> Optional[ClusterRecord]:
    row = _db().execute('SELECT * FROM clusters WHERE name=?',
                        (name,)).fetchone()
    return ClusterRecord(row) if row else None


def get_clusters(workspace: Optional[str] = None) -> List[ClusterRecord]:
    """All clusters, optionally scoped to one workspace."""
    if workspace is None:
        rows = _db().execute(
            'SELECT * FROM clusters ORDER BY launched_at DESC').fetchall()
    else:
        rows = _db().execute(
            'SELECT * FROM clusters WHERE workspace=? '
            'ORDER BY launched_at DESC', (workspace,)).fetchall()
    return [ClusterRecord(r) for r in rows]


def remove_cluster(name: str) -> None:
    db = _db()
    db.execute('DELETE FROM clusters WHERE name=?', (name,))
    db.commit()
    events.publish(events.CLUSTERS, conn=db)


def set_cluster_status(name: str, status: ClusterStatus) -> None:
    db = _db()
    db.execute('UPDATE clusters SET status=? WHERE name=?',
               (status.value, name))
    db.commit()
    events.publish(events.CLUSTERS, conn=db)


def touch_cluster(name: str) -> None:
    db = _db()
    db.execute('UPDATE clusters SET last_use=? WHERE name=?',
               (time.time(), name))
    db.commit()


def add_cluster_event(name: str, event: str, detail: str = '') -> None:
    """Parity: global_user_state.add_cluster_event (execution.py:582)."""
    db = _db()
    db.execute(
        'INSERT INTO cluster_events (cluster_name, ts, event, detail) '
        'VALUES (?,?,?,?)', (name, time.time(), event, detail))
    db.commit()
    # PREEMPTED/CAPACITY events are how providers signal health changes;
    # controllers waiting on the CLUSTERS topic react in milliseconds.
    events.publish(events.CLUSTERS, conn=db)


def get_cluster_events(name: str) -> List[Dict[str, Any]]:
    rows = _db().execute(
        'SELECT ts, event, detail FROM cluster_events WHERE cluster_name=? '
        'ORDER BY ts', (name,)).fetchall()
    return [dict(r) for r in rows]


def cluster_events_after(after_id: int,
                         event: Optional[str] = None
                         ) -> List[Dict[str, Any]]:
    """Cluster events past an id cursor, across ALL clusters, joined
    with the owning cluster's cloud — the O(new)-per-scrape read behind
    the /api/metrics provision histogram (the per-cluster
    get_cluster_events walk re-read full history every render). The
    LEFT JOIN keeps events of since-deleted clusters (cloud None)."""
    sql = ('SELECT e.id, e.cluster_name, e.ts, e.event, e.detail, '
           'c.cloud FROM cluster_events e '
           'LEFT JOIN clusters c ON c.name = e.cluster_name '
           'WHERE e.id > ?')
    args: List[Any] = [int(after_id)]
    if event is not None:
        sql += ' AND e.event = ?'
        args.append(event)
    rows = _db().execute(sql + ' ORDER BY e.id', args).fetchall()
    return [dict(r) for r in rows]
