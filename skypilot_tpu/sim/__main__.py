"""CLI: run/list/validate simulation scenarios.

    python -m skypilot_tpu.sim list
    python -m skypilot_tpu.sim run region_outage --seed 7
    python -m skypilot_tpu.sim run path/to/scenario.yaml --scale 0.1
    python -m skypilot_tpu.sim validate path/to/scenario.yaml

``run`` prints the run artifact (summary, digest, invariant verdicts)
as JSON and exits non-zero if any declared invariant fails — a
scenario file IS a regression test.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

from skypilot_tpu.sim.runner import run_scenario
from skypilot_tpu.sim.scenario import (Scenario, library_names,
                                       load_library)
from skypilot_tpu.utils import env_registry


def _load(ref: str) -> Scenario:
    if os.path.exists(ref):
        return Scenario.from_file(ref)
    return load_library(ref)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog='python -m skypilot_tpu.sim')
    sub = parser.add_subparsers(dest='cmd', required=True)

    run_p = sub.add_parser('run', help='run a scenario (file or '
                           'library name)')
    run_p.add_argument('scenario')
    run_p.add_argument('--seed', type=int, default=None)
    run_p.add_argument('--scale', type=float, default=None,
                       help='proportional fleet/traffic scale '
                       '(default SKYT_SIM_SCALE)')
    run_p.add_argument('--store', default=None,
                       help='TSDB directory to export metrics into')

    sub.add_parser('list', help='list library scenarios')

    val_p = sub.add_parser('validate', help='parse + validate a '
                           'scenario file')
    val_p.add_argument('scenario')

    args = parser.parse_args(argv)

    if args.cmd == 'list':
        for name in library_names():
            print(name)
        return 0

    if args.cmd == 'validate':
        scenario = _load(args.scenario)
        print(f'ok: {scenario.name} '
              f'(duration {scenario.duration_s}s, '
              f'tick {scenario.tick_s}s, seed {scenario.seed})')
        return 0

    scenario = _load(args.scenario)
    scale = (args.scale if args.scale is not None else
             env_registry.get_float('SKYT_SIM_SCALE'))
    if scale != 1.0:
        scenario = scenario.scale(scale)
    started = time.monotonic()
    report = run_scenario(scenario, seed=args.seed,
                          store_root=args.store)
    wall_s = time.monotonic() - started
    verdicts = report.check_invariants(scenario.invariants)
    artifact = report.to_dict()
    artifact['wall_seconds'] = round(wall_s, 3)
    artifact['invariants'] = verdicts
    json.dump(artifact, sys.stdout, indent=2)
    print()
    failed = [v for v in verdicts if not v['ok']]
    for verdict in failed:
        print(f"# INVARIANT FAILED: {verdict['invariant']} "
              f"bound={verdict['bound']} actual={verdict['actual']}",
              file=sys.stderr)
    return 1 if failed else 0


if __name__ == '__main__':
    sys.exit(main())
