"""Scenario runner: wire kernel + fleet + faults + report, run, check.

``run_scenario`` is the one entry point everything shares — tier-1
invariant tests, ``bench_sim.py``, and the
``python -m skypilot_tpu.sim`` CLI — so they cannot drift apart on
setup details that would break reproducibility.

Env knobs (see docs/env_vars.md):

* ``SKYT_SIM_SEED`` — overrides the scenario's seed when >= 0;
* ``SKYT_SIM_SCALE`` — proportional fleet/traffic scale factor
  applied by the CLI and bench (1.0 = as written);
* ``SKYT_SIM_TELEMETRY_EXPORT`` — when set, every run exports its
  metric stream into this TSDB directory (then queryable via
  ``/api/metrics/query`` by pointing ``SKYT_TELEMETRY_DIR`` at it).
"""
from __future__ import annotations

import os
from typing import Optional

from skypilot_tpu.sim.kernel import EventLoop
from skypilot_tpu.sim.report import SimReport
from skypilot_tpu.sim.scenario import Scenario
from skypilot_tpu.utils import env_registry, fault_injection

__all__ = ['run_scenario']


def run_scenario(scenario: Scenario,
                 seed: Optional[int] = None,
                 store_root: Optional[str] = None) -> SimReport:
    """Run one scenario to its horizon; returns the populated report.

    ``seed`` overrides the scenario's (explicit arg > SKYT_SIM_SEED
    env > scenario file). ``store_root`` (or the
    SKYT_SIM_TELEMETRY_EXPORT env) exports the metric stream into a
    TSDB directory after the run.
    """
    if seed is None:
        env_seed = env_registry.get_int('SKYT_SIM_SEED')
        seed = env_seed if env_seed >= 0 else scenario.seed
    if store_root is None:
        store_root = env_registry.get_str(
            'SKYT_SIM_TELEMETRY_EXPORT') or None

    # A scenario's fault_spec timeline mutates SKYT_FAULT_SPEC for its
    # window; snapshot + restore so an exception mid-run (or a window
    # outliving the horizon) can't leak chaos into the caller.
    fault_env_before = os.environ.get(fault_injection.SPEC_ENV)
    from skypilot_tpu.sim.fleet import FleetSim
    loop = EventLoop(seed=seed)
    report = SimReport(scenario.name, seed)
    fleet = FleetSim(scenario, loop, report)
    fleet.install()
    try:
        loop.run_until(scenario.duration_s)
    finally:
        if fault_env_before is None:
            os.environ.pop(fault_injection.SPEC_ENV, None)
        else:
            os.environ[fault_injection.SPEC_ENV] = fault_env_before
        fault_injection.reset()

    report.summary = fleet.summary()
    report.summary['events_fired'] = loop.fired
    report.summary['duration_s'] = scenario.duration_s
    if store_root:
        report.to_store(store_root)
    return report
