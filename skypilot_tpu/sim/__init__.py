"""simkit: deterministic fleet-in-a-process simulation.

A discrete-event harness where the serve control loop (SLO autoscaler +
forecaster, mix policy, LB policy), replica lifecycle, provider model
(provision latency, spot preemption), tenant traffic generators, and
fault injection all share ONE virtual clock and ONE seeded RNG — so a
10k-replica / multi-region day of traffic runs in seconds in a single
process, bit-reproducible from a declarative scenario file.

The pieces:

* :mod:`skypilot_tpu.sim.kernel` — ``SimClock`` / ``SimRng`` /
  ``EventLoop``: the event heap and the ``at``/``after``/``every``
  primitives. No real threads on the hot path.
* :mod:`skypilot_tpu.sim.scenario` — the declarative ``Scenario``
  spec (YAML: fleet, tenant mixes, arrival processes, fault timeline,
  invariant bounds) plus the in-tree scenario library.
* :mod:`skypilot_tpu.sim.traffic` — arrival processes (diurnal,
  burst, flood, constant) and seeded Poisson sampling.
* :mod:`skypilot_tpu.sim.fleet` — the fleet model: drives the REAL
  autoscaler classes (``SLOAutoscaler``/``RequestRateAutoscaler`` +
  ``mix_policy.plan_mix`` + the registered LB policies) against a
  ground-truth latency-concurrency fleet with provision/resume delays
  and domain-correlated preemptions.
* :mod:`skypilot_tpu.sim.faults` — the scenario fault timeline
  (region outage, correlated spot reclamation, provision slowdown,
  recorded ``SKYT_FAULT_SPEC`` replay).
* :mod:`skypilot_tpu.sim.report` — ``SimReport``: canonical event
  log (digestable), metric stream (exportable into the r14 telemetry
  TSDB so sim output is queryable via ``/api/metrics/query``), and
  per-scenario invariant evaluation.
* :mod:`skypilot_tpu.sim.runner` — ``run_scenario()`` and the
  ``python -m skypilot_tpu.sim`` CLI.

Determinism contract (docs/simulation.md): a run is a pure function of
``(scenario file, seed)``. Identical inputs produce byte-identical
event logs and metric series; different seeds diverge.
"""
from skypilot_tpu.sim.kernel import EventLoop, SimClock, SimRng
from skypilot_tpu.sim.report import SimReport
from skypilot_tpu.sim.runner import run_scenario
from skypilot_tpu.sim.scenario import (Scenario, library_names,
                                       load_library)

__all__ = ['EventLoop', 'Scenario', 'SimClock', 'SimReport', 'SimRng',
           'library_names', 'load_library', 'run_scenario']
