"""Fleet model: the REAL serve decision stack over a virtual fleet.

This is deliberately not a mock of the autoscaler — it IS the
autoscaler. Each controller tick the sim builds ``LoadStats`` from the
ground-truth fleet and calls ``Autoscaler.evaluate`` (which for the
SLO arm runs the real forecaster, latency model, hysteresis window,
and ``mix_policy.plan_mix``), then applies the returned ``Decision``
list to simulated replicas whose lifecycle (provision delay, warm
resume, preemption, readiness) plays out on the virtual clock. The
r11 autoscale bench's hand-rolled trace loop is this model's direct
ancestor (and now a caller — see ``bench_serve_autoscale.py``).

Ground truth is the same linear latency–concurrency fleet the bench
used: one replica's p99 is ``base + slope*c`` with Little's-law
concurrency, capacity per replica at the SLO boundary has the closed
form ``1000*(target-base)/(slope*target)``, and demand above fleet
capacity accumulates in a fluid queue whose conservation law
(``arrived == served + queued + shed``) is asserted every tick.

Everything random draws from named :class:`~.kernel.SimRng` streams
(``traffic.<tenant>``, ``faults``, ``lb``), so runs are bit-
reproducible and adding a consumer never perturbs the others.
"""
from __future__ import annotations

from bisect import bisect_left
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from skypilot_tpu.data.fanout import bucket_lease_bound
from skypilot_tpu.serve.autoscalers import (Autoscaler, DecisionOp,
                                            LoadStats)
from skypilot_tpu.serve.serve_state import (REPLICA_TERMINAL_STATUSES,
                                            ReplicaStatus)
from skypilot_tpu.serve.service_spec import ServiceSpec
from skypilot_tpu.serve.spot_placer import Domain, DomainSpotPlacer
from skypilot_tpu.sim import traffic as traffic_lib
from skypilot_tpu.sim.kernel import EventLoop
from skypilot_tpu.sim.report import SimReport
from skypilot_tpu.sim.scenario import Scenario

# Price defaults for the $-weighted replica-hours metric (override per
# scenario via fleet.od_price_hr / per-domain `price`).
OD_PRICE_HR = 4.0

# Behavioral LB probe bounds: the fluid model owns throughput; the
# probe only exercises the real policy's pick distribution, so it runs
# over a bounded replica subsample and a bounded request sample.
_LB_REPLICA_SAMPLE = 128
_LB_REQUEST_SAMPLE = 32

# Per-tick adapter draw bound (fleet.lora): the LRU model samples at
# most this many request->adapter draws per tick and scales the
# hit/miss estimate to the tick's arrivals, keeping a 20k-qps tick
# O(1) like everything else in the loop.
_LORA_REQUEST_SAMPLE = 256

# Tick-loop status sets: membership tests, not method calls — these
# run once per replica per tick across a 10k-replica fleet.
_PENDING = frozenset({ReplicaStatus.PROVISIONING, ReplicaStatus.STARTING})
_BILLABLE = frozenset({ReplicaStatus.READY, ReplicaStatus.PROVISIONING,
                       ReplicaStatus.STARTING})


class SimReplicaRecord:
    """Duck-types ``serve_state.ReplicaRecord`` for everything the
    decision layer touches (``_alive``/``victim_order``/``plan_mix``
    are attribute-only)."""

    __slots__ = ('service_name', 'replica_id', 'cluster_name', 'status',
                 'endpoint', 'is_spot', 'is_fallback', 'zone',
                 'launched_at', 'ready_at', 'consecutive_failures',
                 'lb_ewma_ms', 'lb_ejected', 'lb_ejected_until', 'cloud',
                 'region', 'warm_since', 'ready_eta', '_domain', 'role',
                 'weights_ready', 'weights_eta', 'weights_src',
                 'weights_wait_since')

    def __init__(self, replica_id: int, now: float, *, is_spot: bool,
                 is_fallback: bool = False,
                 domain: Optional[Domain] = None,
                 provision_delay: float = 0.0,
                 role: str = '') -> None:
        self.service_name = 'sim'
        self.replica_id = replica_id
        self.cluster_name = f'sim-{replica_id}'
        self.status = (ReplicaStatus.READY if provision_delay <= 0
                       else ReplicaStatus.PROVISIONING)
        self.endpoint = None
        self.is_spot = is_spot
        self.is_fallback = is_fallback
        self.cloud = domain.cloud if domain else None
        self.region = domain.region if domain else None
        self.zone = domain.zone if domain else None
        self.launched_at = now
        self.ready_at = now if provision_delay <= 0 else None
        self.consecutive_failures = 0
        self.lb_ewma_ms = None
        self.lb_ejected = False
        self.lb_ejected_until = None
        self.warm_since = None
        # Disaggregated serving fleet ('prefill'/'decode'/'' — the
        # same partition serve_state.add_replica records).
        self.role = role
        # Virtual time at which the pending provision/resume lands.
        self.ready_eta = now + provision_delay
        self._domain = domain
        # Weight fan-out state (fleet.weights scenarios): a replica
        # whose provision landed still gates READY on its weight pull.
        self.weights_ready = provision_delay <= 0
        self.weights_eta = None
        self.weights_src = None
        self.weights_wait_since = None

    def domain(self) -> Domain:
        if self._domain is None:
            self._domain = Domain(self.cloud, self.region, self.zone)
        return self._domain


def _series_p99(xs: List[float]) -> float:
    if not xs:
        return 0.0
    ordered = sorted(xs)
    return ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]


def fleet_point(qps: float, n_ready: int, base_ms: float,
                slope_ms: float, saturated_ms: float):
    """(p99_ms, per-replica concurrency) of the ground-truth fleet at
    offered load ``qps`` — the bench's closed form, parameterized."""
    if n_ready <= 0:
        return saturated_ms, 0.0
    k = 1000.0 * n_ready / max(qps, 1e-9)
    if k <= slope_ms:
        return saturated_ms, saturated_ms / slope_ms
    c = base_ms / (k - slope_ms)
    return base_ms + slope_ms * c, c


class FleetSim:
    """One scenario's fleet, wired onto an :class:`EventLoop`.

    ``install()`` schedules the controller tick and the fault
    timeline; the caller then drives ``loop.run_until(duration)``.
    """

    def __init__(self, scenario: Scenario, loop: EventLoop,
                 report: SimReport) -> None:
        self.scenario = scenario
        self.loop = loop
        self.clock = loop.clock
        self.report = report
        fleet = scenario.fleet
        self.base_ms = float(fleet['base_latency_ms'])
        self.slope_ms = float(fleet['latency_slope_ms'])
        self.provision_delay_s = float(fleet['provision_delay_s'])
        self.resume_delay_s = float(fleet['resume_delay_s'])
        self.spot = bool(fleet['spot'])
        self.max_queue_per_replica = float(fleet['max_queue_per_replica'])
        self.od_price_hr = float(fleet.get('od_price_hr', OD_PRICE_HR))

        # -- weight distribution (fleet.weights) -----------------------
        # Models the data/fanout.py pull path as fluid slots: a new
        # replica's provision landing does NOT make it READY until its
        # weight pull finishes; pulls ride a peer slot (each weight-
        # complete replica serves `fanout` children) or one of the
        # bucket_lease_bound(N) bucket leases.
        weights_cfg = fleet.get('weights') or {}
        self.weights_enabled = bool(
            weights_cfg.get('enabled', bool(weights_cfg)))
        self.weights_bucket_pull_s = float(
            weights_cfg.get('bucket_pull_s', 60.0))
        self.weights_peer_pull_s = float(
            weights_cfg.get('peer_pull_s', 15.0))
        self.weights_fanout = int(weights_cfg.get('fanout', 2))
        self.weights_bucket_leases = int(
            weights_cfg.get('bucket_leases', 0))

        self.spec = ServiceSpec(**scenario.service)
        # Ground-truth SLO the sim GRADES against (slo_miss_seconds).
        # Defaults to the control target; fleet.slo_target_p99_ms lets
        # an A/B arm whose autoscaler doesn't know the SLO (e.g. a
        # request_rate bench arm) still be graded on the same line.
        slo_target = fleet.get('slo_target_p99_ms',
                               self.spec.target_latency_p99_ms)
        self.slo_target_ms = (float(slo_target)
                              if slo_target is not None else None)

        # -- disaggregated prefill/decode (fleet.disagg) ---------------
        # When present, the fluid model becomes a two-stage pipeline:
        # a prefill queue graded on TTFT and a decode service graded on
        # inter-token latency, feeding the real DisaggSLOAutoscaler.
        # When absent the block is inert — the colocated path below is
        # byte-for-byte what it was.
        disagg_cfg = fleet.get('disagg') or {}
        self.disagg_enabled = bool(disagg_cfg)
        if self.disagg_enabled:
            self._init_disagg(disagg_cfg)
            self.capacity_qps = 0.0  # the two stages own throughput
            self.saturated_ms = self.pre_saturated_ms
        else:
            cap = fleet.get('capacity_qps_per_replica')
            if cap is None:
                if self.slo_target_ms is None:
                    raise ValueError(
                        'scenario needs fleet.capacity_qps_per_replica, '
                        'fleet.slo_target_p99_ms, or '
                        'service.target_latency_p99_ms to size capacity')
                cap = 1000.0 * (self.slo_target_ms - self.base_ms) / (
                    self.slope_ms * self.slo_target_ms)
            self.capacity_qps = float(cap)
            self.saturated_ms = 4.0 * (
                self.slo_target_ms if self.slo_target_ms is not None else
                self.base_ms + self.slope_ms * self.max_queue_per_replica)

        # -- paged multi-LoRA serving (fleet.lora) ---------------------
        # When present, requests carry Zipf-popular adapter ids served
        # from a fleet-wide paged LRU; cold fetches delay first tokens
        # and burn replica capacity. When absent the block is inert —
        # the flow below is byte-for-byte what it was.
        lora_cfg = fleet.get('lora') or {}
        self.lora_enabled = bool(lora_cfg)
        if self.lora_enabled:
            self._init_lora(lora_cfg)

        # -- live-sync RL rollout pipeline (fleet.rl) ------------------
        # When present, READY replicas double as a GRPO rollout fleet
        # feeding a fluid learner: delta weight refreshes, the
        # max_staleness backpressure valve, and the ack/requeue batch
        # queue are modeled on the virtual clock. When absent the
        # block is inert — serving flow is untouched either way (the
        # rollout fleet generates training tokens, not user traffic).
        rl_cfg = fleet.get('rl') or {}
        self.rl_enabled = bool(rl_cfg)
        if self.rl_enabled:
            self._init_rl(rl_cfg)

        # -- placement domains ----------------------------------------
        self.domains: List[Domain] = []
        self.domain_price: Dict[Domain, float] = {}
        for entry in fleet['domains']:
            domain = Domain(entry.get('cloud'), entry['region'],
                            entry['zone'])
            self.domains.append(domain)
            self.domain_price[domain] = float(entry.get('price', 1.0))
        self.placer = DomainSpotPlacer(self.domains,
                                       clock=self.clock.now)
        self.down_regions: set = set()
        self._od_rr = 0

        # -- the real decision stack ----------------------------------
        overrides = scenario.to_dict().get('autoscaler', {})
        if 'kind' in overrides:
            # Force a registry arm (bench A/B runs pit e.g. the plain
            # request_rate scaler against what from_spec would pick).
            from skypilot_tpu.utils.registry import AUTOSCALER_REGISTRY
            self.scaler = AUTOSCALER_REGISTRY.get(
                overrides['kind'])(self.spec)
        else:
            self.scaler = Autoscaler.from_spec(self.spec)
        # Both the monotonic hysteresis clock and the wall clock the
        # warm-pool TTL ages against are the ONE virtual clock.
        self.scaler._clock = self.clock.now
        self.scaler._wall_clock = self.clock.now
        for knob in ('warm_pool_size', 'warm_ttl', 'horizon',
                     'idle_seconds', 'spot_wanted'):
            if knob in overrides and hasattr(self.scaler, knob):
                setattr(self.scaler, knob, overrides[knob])
        if hasattr(self.scaler, 'spot_wanted') and \
                'spot_wanted' not in overrides:
            self.scaler.spot_wanted = self.spot
        if 'seasonal_period_s' in overrides and \
                hasattr(self.scaler, 'forecaster'):
            from skypilot_tpu.serve.forecast import SeasonalRingForecaster
            self.scaler.forecaster = SeasonalRingForecaster(
                period_seconds=float(overrides['seasonal_period_s']),
                buckets=int(overrides.get('seasonal_buckets', 72)))

        # -- LB behavioral probe --------------------------------------
        self.lb_policy = None
        if scenario.lb_policy:
            from skypilot_tpu.serve import load_balancing_policies as lbp
            self.lb_policy = lbp.LoadBalancingPolicy.make(
                scenario.lb_policy)
            if hasattr(self.lb_policy, '_rng'):
                self.lb_policy._rng = loop.rng.stream('lb')
        self.lb_max_share = 0.0

        # -- tenants ---------------------------------------------------
        self.tenants = []
        for tenant in scenario.tenants:
            self.tenants.append(
                (tenant['name'], traffic_lib.make_rate(tenant['rate']),
                 loop.rng.stream(f'traffic.{tenant["name"]}')))

        # -- fleet state ----------------------------------------------
        self.replicas: List[SimReplicaRecord] = []
        self._next_id = 0
        if self.disagg_enabled:
            roles = (['prefill'] * self.pre_initial +
                     ['decode'] * self.dec_initial)
        else:
            roles = [''] * int(fleet['initial_replicas'])
        for index, role in enumerate(roles):
            record = self._new_replica(
                is_spot=self.spot and index >= (
                    self.spec.base_ondemand_fallback_replicas),
                provision_delay=0.0, role=role)
            record.status = ReplicaStatus.READY
        if roles:
            self.scaler._target = len(roles)
            if hasattr(self.scaler, '_tracks'):
                # Seed each hysteresis track at its fleet's warm start
                # so t=0 isn't graded as a cold scale-from-min.
                self.scaler._tracks['prefill']._target = self.pre_initial
                self.scaler._tracks['decode']._target = self.dec_initial

        # -- counters --------------------------------------------------
        self.queue = 0.0
        self.arrived_total = 0
        self.served_total = 0.0
        self.shed_total = 0.0
        self.slo_miss_s = 0.0
        self.replica_hours = 0.0
        self.dollar_hours = 0.0
        self.warm_hours = 0.0
        self.warm_resumes = 0
        self.preemptions = 0
        self.provision_failures = 0
        self.controller_faults = 0
        self.target_flips = 0
        self._last_target = self._scaler_target()
        self._last_direction = 0
        self.ticks = 0
        self._provision_factor = 1.0
        self.max_bucket_readers = 0
        self.bucket_pulls = 0
        self.peer_pulls = 0
        self._bucket_inflight = 0
        self._peer_inflight = 0
        self.weights_times: List[float] = []

    def _init_disagg(self, cfg: Dict) -> None:
        """Parse the fleet.disagg block (docs/disaggregated_serving.md).

        Prefill capacity comes from the TTFT closed form; decode
        capacity from Little's law with sojourn = tokens_per_request ×
        inter-token latency, so a replica that streams 64 tokens at the
        SLO boundary admits far fewer requests/s than a prefill replica
        with the same latency line — the asymmetry the tentpole's
        two-inversion autoscaler exists to express."""
        pre = dict(cfg.get('prefill') or {})
        dec = dict(cfg.get('decode') or {})
        ttft_t = self.spec.target_ttft_p99_ms
        itl_t = self.spec.target_intertoken_p99_ms
        if ttft_t is None or itl_t is None:
            raise ValueError(
                'fleet.disagg needs service.target_ttft_p99_ms and '
                'service.target_intertoken_p99_ms (the pair that '
                'selects the disagg_slo autoscaler)')
        self.pre_base_ms = float(pre.get('base_ttft_ms', 80.0))
        self.pre_slope_ms = float(pre.get('ttft_slope_ms', 20.0))
        self.pre_initial = int(pre.get('initial_replicas', 0))
        self.dec_base_ms = float(dec.get('base_intertoken_ms', 10.0))
        self.dec_slope_ms = float(dec.get('intertoken_slope_ms', 1.0))
        self.dec_initial = int(dec.get('initial_replicas', 0))
        self.tokens_per_request = float(
            dec.get('tokens_per_request', 64.0))
        if self.pre_base_ms >= ttft_t or self.dec_base_ms >= itl_t:
            raise ValueError(
                'fleet.disagg base latency at or above its SLO target '
                'is unattainable at any fleet size')
        if self.dec_slope_ms <= 0:
            raise ValueError('fleet.disagg decode intertoken_slope_ms '
                             'must be > 0')
        cap = pre.get('capacity_qps_per_replica')
        self.pre_capacity_qps = (
            float(cap) if cap is not None else
            1000.0 * (ttft_t - self.pre_base_ms) / (
                self.pre_slope_ms * ttft_t))
        cap = dec.get('capacity_qps_per_replica')
        self.dec_capacity_qps = (
            float(cap) if cap is not None else
            1000.0 * (itl_t - self.dec_base_ms) / (
                self.tokens_per_request * self.dec_slope_ms * itl_t))
        # A decode replica's concurrency is slot-bounded (paged KV
        # pool): past the ceiling, extra requests QUEUE (delaying their
        # first token) instead of inflating running streams' itl — so
        # the itl ceiling is base + slope*c_max, not open-ended.
        c_at_target = (itl_t - self.dec_base_ms) / self.dec_slope_ms
        self.dec_max_conc = float(
            dec.get('max_concurrency', 2.0 * c_at_target))
        self.pre_saturated_ms = 4.0 * ttft_t
        self.dec_saturated_ms = (self.dec_base_ms +
                                 self.dec_slope_ms * self.dec_max_conc)
        # Optional generation-length shift: tokens_per_request ×factor
        # for [at, at+duration_s) — decode demand changes with NO qps
        # change, invisible to any single-model autoscaler.
        self.tokens_shift = cfg.get('tokens_shift') or None
        if self.tokens_shift is not None:
            for key in ('at', 'duration_s', 'factor'):
                if key not in self.tokens_shift:
                    raise ValueError(
                        f'fleet.disagg.tokens_shift needs {key!r}')
        self.pre_queue = 0.0
        self.dec_queue = 0.0
        self.ttft_samples: List[float] = []
        self.itl_samples: List[float] = []
        self._disagg_last: Dict[str, float] = {}

    def _init_lora(self, cfg: Dict) -> None:
        """Parse the fleet.lora block (docs/multi_lora_serving.md).

        Fluid model of the serve layer's paged-adapter runtime. The
        LB's adapter-affinity routing keeps each adapter resident on
        ~one replica, so the fleet's distinct-adapter working set is a
        single LRU with capacity ``pages_per_replica * n_ready``.
        Requests draw their adapter from a Zipf(s) popularity whose
        head ROTATES by ``hot_set`` ids every ``hot_rotate_period_s``
        — the churn drill — and every cold adapter both delays its
        request's first token by ``cold_fetch_ms`` and burns the fetch
        time as lost serving capacity, which is exactly the contention
        path base (page-0) traffic feels while adapters churn."""
        self.lora_n_adapters = int(cfg['n_adapters'])
        self.lora_pages_per_replica = int(cfg['pages_per_replica'])
        if self.lora_n_adapters < 1 or self.lora_pages_per_replica < 1:
            raise ValueError('fleet.lora n_adapters and '
                             'pages_per_replica must be >= 1')
        self.lora_adapter_fraction = float(
            cfg.get('adapter_fraction', 1.0))
        self.lora_hot_set = int(cfg.get('hot_set', 8))
        self.lora_rotate_s = float(cfg.get('hot_rotate_period_s', 0.0))
        self.lora_cold_fetch_ms = float(cfg.get('cold_fetch_ms', 250.0))
        # Base-traffic inter-token line over per-replica concurrency —
        # same shape as the disagg decode stage's, colocated here.
        self.lora_itl_base_ms = float(
            cfg.get('base_intertoken_ms', 10.0))
        self.lora_itl_slope_ms = float(
            cfg.get('intertoken_slope_ms', 1.0))
        weights = traffic_lib.zipf_weights(
            self.lora_n_adapters, float(cfg.get('zipf_s', 1.1)))
        cum, acc = [], 0.0
        for w in weights:
            acc += w
            cum.append(acc)
        cum[-1] = 1.0
        self._lora_cum = cum
        self._lora_cache: 'OrderedDict[int, bool]' = OrderedDict()
        self._lora_rng = self.loop.rng.stream('lora')
        self.lora_hits = 0.0
        self.lora_misses = 0.0
        self.lora_evictions = 0
        self.cold_ttft_samples: List[float] = []
        self.base_itl_samples: List[float] = []

    def _lora_tick(self, t: float, arrived: int, n_ready: int):
        """One tick of the adapter-LRU model. Returns (per-request
        miss estimate, replica-seconds consumed by cold fetches). The
        hit/miss split comes from a bounded sample of adapter draws
        scaled to the tick's arrivals; fetch time is charged per
        DISTINCT cold adapter observed (a miss admits the page once —
        queued requests behind the same fetch share it), unscaled."""
        adapter_reqs = int(round(arrived * self.lora_adapter_fraction))
        if adapter_reqs <= 0:
            return 0.0, 0.0
        offset = 0
        if self.lora_rotate_s > 0:
            # The head rotates INTO the previously-deepest tail (the
            # LRU's evicted region), so each period's fresh hot set
            # really is cold and must be paged in — rotating forward
            # by hot_set would land on near-head (still-resident)
            # adapters and churn nothing.
            offset = -int(t // self.lora_rotate_s) * self.lora_hot_set
            offset %= self.lora_n_adapters
        capacity = self.lora_pages_per_replica * max(n_ready, 1)
        cache = self._lora_cache
        while len(cache) > capacity:    # the fleet shrank under the set
            cache.popitem(last=False)
            self.lora_evictions += 1
        sample = min(adapter_reqs, _LORA_REQUEST_SAMPLE)
        rng = self._lora_rng
        cum = self._lora_cum
        hits = fetches = 0
        for _ in range(sample):
            rank = bisect_left(cum, rng.random())
            adapter = (rank + offset) % self.lora_n_adapters
            if adapter in cache:
                cache.move_to_end(adapter)
                hits += 1
            else:
                fetches += 1
                cache[adapter] = True
                if len(cache) > capacity:
                    cache.popitem(last=False)
                    self.lora_evictions += 1
        scale = adapter_reqs / sample
        self.lora_hits += hits * scale
        miss_est = fetches * scale
        self.lora_misses += miss_est
        return miss_est, fetches * self.lora_cold_fetch_ms / 1000.0

    def _init_rl(self, cfg: Dict) -> None:
        """Parse the fleet.rl block (docs/rl_pipeline.md).

        Fluid model of ``jobs/rl_pipeline.py``: every READY replica
        produces rollout waves of ``wave_tokens`` tokens at
        ``tokens_per_replica_s``; a singleton learner consumes one
        batch per ``learn_step_s`` and bumps the policy version; a
        replica whose version lags refreshes for ``refresh_s``,
        staggered ``refresh_concurrency`` at a time ('step' mode keeps
        producing through the swap, 'drain' holds admission — the
        stop-the-world per-replica baseline). Production gates on the
        projected-staleness valve and the bounded batch queue, exactly
        the real pipeline's invariant:
        staleness-at-consume = lag + queue depth + in-flight, which
        consumption leaves unchanged and only a refresh lowers."""
        self.rl_wave_tokens = float(cfg.get('wave_tokens', 2048.0))
        self.rl_tokens_per_replica_s = float(
            cfg.get('tokens_per_replica_s', 512.0))
        self.rl_learn_step_s = float(cfg.get('learn_step_s', 0.5))
        self.rl_refresh_s = float(cfg.get('refresh_s', 5.0))
        self.rl_refresh_mode = str(cfg.get('refresh_mode', 'step'))
        self.rl_refresh_concurrency = int(
            cfg.get('refresh_concurrency', 1))
        self.rl_max_staleness = int(cfg.get('max_staleness', 4))
        self.rl_queue_batches = float(cfg.get('queue_batches', 2.0))
        if min(self.rl_wave_tokens, self.rl_tokens_per_replica_s,
               self.rl_learn_step_s, self.rl_refresh_s) <= 0:
            raise ValueError('fleet.rl rates and latencies must be > 0')
        if self.rl_refresh_mode not in ('step', 'drain'):
            raise ValueError("fleet.rl refresh_mode must be 'step' or "
                             "'drain'")
        if self.rl_refresh_concurrency < 1 or \
                self.rl_queue_batches < 1 or self.rl_max_staleness < 1:
            raise ValueError('fleet.rl refresh_concurrency, '
                             'queue_batches and max_staleness must '
                             'be >= 1')
        self.rl_learner_version = 0
        # FIFO cohorts of [policy_version, batches] (fluid amounts).
        self._rl_queue: 'deque[List[float]]' = deque()
        self._rl_inflight: Optional[List[float]] = None  # [ver, eta]
        self._rl_learn_free_at = 0.0
        self._rl_replica_version: Dict[int, int] = {}
        self._rl_refreshing: Dict[int, float] = {}  # id -> eta
        self.rl_learner_down_until: Optional[float] = None
        self.rl_batches_produced = 0.0
        self.rl_batches_consumed = 0
        self.rl_batches_requeued = 0
        self.rl_refreshes = 0
        self.rl_tokens_total = 0.0
        self._rl_potential_tokens = 0.0
        self.rl_staleness_max = 0
        self.rl_valve_wait_s = 0.0

    def rl_learner_preempt(self, t: float, down_s: float) -> int:
        """Learner preemption (the ``learner_preempt`` fault): no
        consumption and no version bumps until ``t + down_s``; the
        in-flight batch goes back to the FRONT of the queue — the
        ack/requeue contract that makes lost batches impossible."""
        self.rl_learner_down_until = t + down_s
        requeued = 0
        if self._rl_inflight is not None:
            ver, _eta = self._rl_inflight
            self._rl_queue.appendleft([float(ver), 1.0])
            self._rl_inflight = None
            self.rl_batches_requeued += 1
            requeued = 1
        return requeued

    def _rl_tick(self, t: float, dt: float, ready: List) -> None:
        versions = self._rl_replica_version
        refreshing = self._rl_refreshing
        ready_ids = {r.replica_id for r in ready}
        # Departed replicas (preempted, scaled down, mid-refresh or
        # not) drop out of the fleet version map; a victim mid-refresh
        # frees its stagger slot — the engine-shutdown semaphore
        # release in the real pipeline.
        for rid in list(versions):
            if rid not in ready_ids:
                versions.pop(rid)
                refreshing.pop(rid, None)
        lv = self.rl_learner_version
        for record in ready:
            if record.replica_id not in versions:
                # A freshly landed replica pulls the committed policy
                # as part of its start (the full-manifest cold pull).
                versions[record.replica_id] = lv

        # Refresh completions, then staggered starts.
        for rid in sorted(refreshing):
            if t >= refreshing[rid]:
                versions[rid] = lv
                del refreshing[rid]
                self.rl_refreshes += 1
        slots = self.rl_refresh_concurrency - len(refreshing)
        if slots > 0:
            lagging = sorted(rid for rid, ver in versions.items()
                             if ver < lv and rid not in refreshing)
            for rid in lagging[:slots]:
                refreshing[rid] = t + self.rl_refresh_s

        # Learner: commit in-flight batches whose step finished, pop
        # the next — possibly several per tick when learn_step_s < dt.
        if self.rl_learner_down_until is not None and \
                t >= self.rl_learner_down_until:
            self.rl_learner_down_until = None
            self._rl_learn_free_at = t
        if self.rl_learner_down_until is None:
            while True:
                if self._rl_inflight is not None:
                    ver, eta = self._rl_inflight
                    if eta > t:
                        break
                    self.rl_learner_version += 1
                    self.rl_batches_consumed += 1
                    self._rl_learn_free_at = eta
                    self._rl_inflight = None
                    continue
                if sum(c[1] for c in self._rl_queue) < 1.0 - 1e-9:
                    break
                take, oldest = 1.0, None
                while take > 1e-9:
                    cohort = self._rl_queue[0]
                    if oldest is None:
                        oldest = int(cohort[0])
                    amount = min(take, cohort[1])
                    cohort[1] -= amount
                    take -= amount
                    if cohort[1] <= 1e-9:
                        self._rl_queue.popleft()
                stale = self.rl_learner_version - oldest
                self.rl_staleness_max = max(self.rl_staleness_max,
                                            stale)
                start = max(self._rl_learn_free_at, t - dt)
                self._rl_inflight = [float(oldest),
                                     start + self.rl_learn_step_s]
            lv = self.rl_learner_version

        # Production: valve + bounded queue gate each replica's tick.
        wave_s = self.rl_wave_tokens / self.rl_tokens_per_replica_s
        rate = dt / wave_s
        qtotal = sum(c[1] for c in self._rl_queue)
        inflight_n = 0 if self._rl_inflight is None else 1
        for record in sorted(ready, key=lambda r: r.replica_id):
            rid = record.replica_id
            self._rl_potential_tokens += rate * self.rl_wave_tokens
            if rid in refreshing and self.rl_refresh_mode == 'drain':
                continue    # admission held while the swap drains
            projected = (lv - versions[rid]) + qtotal + inflight_n
            if projected >= self.rl_max_staleness or \
                    qtotal >= self.rl_queue_batches - 1e-9:
                self.rl_valve_wait_s += dt
                continue
            amount = min(rate, self.rl_queue_batches - qtotal)
            ver = versions[rid]
            if self._rl_queue and int(self._rl_queue[-1][0]) == ver:
                self._rl_queue[-1][1] += amount
            else:
                self._rl_queue.append([float(ver), amount])
            qtotal += amount
            self.rl_batches_produced += amount
            self.rl_tokens_total += amount * self.rl_wave_tokens

    def _scaler_target(self) -> int:
        """The decision stack's current total target: per-role tracks
        summed for the disagg scaler, the scalar for everyone else."""
        tracks = getattr(self.scaler, '_tracks', None)
        if tracks:
            return sum(track._target for track in tracks.values())
        return self.scaler._target

    # -- wiring --------------------------------------------------------

    def install(self) -> None:
        from skypilot_tpu.sim.faults import install_faults
        self.loop.every(self.scenario.tick_s, self.tick)
        install_faults(self, self.scenario.faults)

    # -- replica lifecycle ---------------------------------------------

    def _new_replica(self, *, is_spot: bool, is_fallback: bool = False,
                     provision_delay: Optional[float] = None,
                     role: str = '') -> SimReplicaRecord:
        self._next_id += 1
        now = self.clock.now()
        if provision_delay is None:
            provision_delay = self.provision_delay_s * \
                self._provision_factor
        domain = self._place(is_spot)
        record = SimReplicaRecord(self._next_id, now, is_spot=is_spot,
                                  is_fallback=is_fallback, domain=domain,
                                  provision_delay=provision_delay,
                                  role=role)
        self.replicas.append(record)
        return record

    def _place(self, is_spot: bool) -> Optional[Domain]:
        up = [d for d in self.domains
              if d.region not in self.down_regions]
        if not up:
            up = self.domains
        if is_spot:
            def price(domain: Domain) -> float:
                if domain.region in self.down_regions:
                    return 1e18     # still selectable, never preferred
                return self.domain_price.get(domain, float('inf'))
            return self.placer.select(price)
        choice = up[self._od_rr % len(up)]
        self._od_rr += 1
        return choice

    def preempt(self, record: SimReplicaRecord, reason: str) -> None:
        self._release_weights_slot(record)
        record.status = ReplicaStatus.PREEMPTED
        record.warm_since = None
        self.preemptions += 1
        self.placer.handle_preemption(record.domain())

    # -- weight distribution -------------------------------------------

    def _assign_weight_sources(self, pending: List[SimReplicaRecord],
                               t: float, n_ready: int) -> None:
        """FIFO source assignment for replicas whose provision landed
        but whose weight pull hasn't started. Peer slots go first (the
        binary-tree rendezvous collapsed to a fluid slot count: every
        weight-complete replica serves ``fanout`` children); the
        bucket accepts at most ``bucket_lease_bound(N)`` concurrent
        readers — the same lease rule the controller enforces."""
        live = sum(1 for r in self.replicas
                   if not r.status.is_terminal())
        bound = self.weights_bucket_leases or bucket_lease_bound(live)
        peer_free = n_ready * self.weights_fanout - self._peer_inflight
        for record in pending:
            if peer_free > 0:
                peer_free -= 1
                self._peer_inflight += 1
                self.peer_pulls += 1
                record.weights_src = 'peer'
                record.weights_eta = t + self.weights_peer_pull_s
            elif self._bucket_inflight < bound:
                self._bucket_inflight += 1
                self.bucket_pulls += 1
                record.weights_src = 'bucket'
                record.weights_eta = t + self.weights_bucket_pull_s
            # else: every slot is busy — wait for the next tick.
        if self._bucket_inflight > self.max_bucket_readers:
            self.max_bucket_readers = self._bucket_inflight

    def _finish_weights(self, record: SimReplicaRecord,
                        t: float) -> None:
        if record.weights_src == 'bucket':
            self._bucket_inflight -= 1
        elif record.weights_src == 'peer':
            self._peer_inflight -= 1
        record.weights_ready = True
        record.weights_eta = None
        record.weights_src = None
        if record.weights_wait_since is not None:
            self.weights_times.append(t - record.weights_wait_since)
            record.weights_wait_since = None

    def _release_weights_slot(self, record: SimReplicaRecord) -> None:
        """A replica died mid-pull (preemption, failed provision):
        free its transfer slot so the convoy doesn't leak capacity."""
        if record.weights_ready or record.weights_eta is None:
            return
        if record.weights_src == 'bucket':
            self._bucket_inflight -= 1
        elif record.weights_src == 'peer':
            self._peer_inflight -= 1
        record.weights_eta = None
        record.weights_src = None

    def _weights_p99(self) -> float:
        return _series_p99(self.weights_times)

    # -- the controller tick -------------------------------------------

    def tick(self) -> None:
        t = self.clock.now()
        dt = self.scenario.tick_s
        self.ticks += 1

        # 1. readiness: pending provisions/resumes land (or fail, if
        # their region went down while they were in flight). One pass
        # also collects the READY set — the fleet scan is the hot loop.
        # With fleet.weights, a landed provision holds in STARTING
        # until its weight pull completes (warm resumes keep their
        # weights — the delta-refresh path — so they skip the gate).
        ready = []
        weights_pending = []
        for record in self.replicas:
            status = record.status
            if status in _PENDING and t >= record.ready_eta:
                if record.region in self.down_regions:
                    record.status = ReplicaStatus.FAILED_PROVISION
                    self.provision_failures += 1
                    self._release_weights_slot(record)
                    continue
                if self.weights_enabled and not record.weights_ready:
                    record.status = ReplicaStatus.STARTING
                    if record.weights_eta is not None and \
                            t >= record.weights_eta:
                        self._finish_weights(record, t)
                    else:
                        if record.weights_wait_since is None:
                            record.weights_wait_since = t
                        if record.weights_eta is None:
                            weights_pending.append(record)
                        continue
                record.status = status = ReplicaStatus.READY
                record.ready_at = t
            if status is ReplicaStatus.READY:
                ready.append(record)
        n_ready = len(ready)
        if weights_pending:
            self._assign_weight_sources(weights_pending, t, n_ready)

        # 2. arrivals (seeded Poisson per tenant).
        arrived = 0
        offered_qps = 0.0
        for _name, rate, rng in self.tenants:
            lam = rate(t)
            offered_qps += lam
            arrived += traffic_lib.poisson_count(rng, lam * dt)
        self.arrived_total += arrived

        # 3./4. fluid flow + ground-truth latency. Disaggregated
        # scenarios run the two-stage pipeline (prefill queue feeding a
        # decode service); colocated scenarios keep the single queue.
        demand_qps = arrived / dt
        if self.disagg_enabled:
            stats, p99, conc = self._flow_disagg(t, dt, ready, arrived)
        else:
            capacity = n_ready * self.capacity_qps * dt
            lora_miss = 0.0
            if self.lora_enabled:
                lora_miss, fetch_secs = self._lora_tick(
                    t, arrived, n_ready)
                # A cold fetch holds its decode slot without serving
                # tokens: the fetch seconds come straight out of tick
                # capacity — churn contends with base traffic.
                capacity = max(0.0,
                               capacity - fetch_secs * self.capacity_qps)
            backlog = self.queue + arrived
            served = min(backlog, capacity)
            self.queue = backlog - served
            queue_cap = self.max_queue_per_replica * max(n_ready, 1)
            shed = max(0.0, self.queue - queue_cap)
            self.queue -= shed
            self.served_total += served
            self.shed_total += shed
            self._assert_conservation(t)

            # Queue backlog saturates the fleet.
            p99, conc = fleet_point(demand_qps, n_ready, self.base_ms,
                                    self.slope_ms, self.saturated_ms)
            if self.queue > 1.0:
                p99 = self.saturated_ms
                conc = self.queue / max(n_ready, 1)

            target_ms = self.slo_target_ms
            if target_ms is not None and \
                    (demand_qps > 1e-9 or (self.queue > 1.0)) and \
                    (p99 > target_ms + 1e-9 or n_ready == 0):
                self.slo_miss_s += dt

            if self.lora_enabled:
                # Ground truth the churn invariants grade: a cold
                # adapter's first token waits out the fleet's p99 PLUS
                # its page fetch; base traffic's inter-token latency is
                # the concurrency line (fetch stalls already pushed
                # conc up through the capacity charge above).
                if demand_qps > 1e-9 or self.queue > 1.0:
                    self.base_itl_samples.append(
                        self.lora_itl_base_ms +
                        self.lora_itl_slope_ms * conc)
                if lora_miss > 0:
                    self.cold_ttft_samples.append(
                        p99 + self.lora_cold_fetch_ms)

            latency_ms = {r.replica_id: p99 for r in ready}
            stats = LoadStats(qps=demand_qps,
                              queue_length=conc * n_ready,
                              window_seconds=dt,
                              replica_latency_ms=latency_ms)

        # 5. the real decision stack (may be felled by injected chaos —
        # a crashed controller tick skips decisions, not the world).
        live = [r for r in self.replicas
                if r.status not in REPLICA_TERMINAL_STATUSES]
        try:
            from skypilot_tpu.utils import fault_injection
            fault_injection.inject('sim.controller.tick')
            decisions = self.scaler.evaluate(stats, live)
        except Exception as exc:  # injected chaos only
            self.controller_faults += 1
            self.report.event(t, 'controller_fault',
                              error=type(exc).__name__)
            decisions = []
        self._apply(decisions, t)

        target = self._scaler_target()
        if target != self._last_target:
            direction = 1 if target > self._last_target else -1
            if direction == -self._last_direction:
                self.target_flips += 1
            self._last_direction = direction
            self._last_target = target

        # 6. accounting + compaction in one pass (terminal rows drop
        # out so the scan stays O(live fleet) across a churny day).
        billed = 0
        warm = 0
        dollars = 0.0
        survivors = []
        for record in self.replicas:
            status = record.status
            if status in REPLICA_TERMINAL_STATUSES:
                continue
            survivors.append(record)
            if status in _BILLABLE:
                billed += 1
                if record.is_spot:
                    dollars += self.domain_price.get(
                        record._domain, 1.0)
                else:
                    dollars += self.od_price_hr
            elif status is ReplicaStatus.WARM:
                # Stopped, unbilled — tracked so cost benches can show
                # warm-pool occupancy next to paid replica-hours.
                warm += 1
        self.replicas = survivors
        self.replica_hours += billed * dt / 3600.0
        self.dollar_hours += dollars * dt / 3600.0
        self.warm_hours += warm * dt / 3600.0

        # 7. behavioral LB probe (bounded sample through the real
        # policy; the fluid model owns throughput).
        if self.lb_policy is not None and n_ready > 0 and arrived > 0:
            self._lb_probe(ready, min(arrived, _LB_REQUEST_SAMPLE))

        # 7b. RL rollout pipeline (its own fluid block: learner
        # consumption, staggered refreshes, valve-gated production).
        if self.rl_enabled:
            self._rl_tick(t, dt, ready)

        # 8. emit the tick's metric points.
        report = self.report
        report.metric('sim_qps_offered', t, offered_qps)
        report.metric('sim_qps_arrived', t, demand_qps)
        report.metric('sim_ready_replicas', t, float(n_ready))
        report.metric('sim_target_replicas', t, float(target))
        report.metric('sim_p99_ms', t, p99)
        report.metric('sim_queue', t, self.queue)
        report.metric('sim_shed_total', t, self.shed_total)
        report.metric('sim_slo_miss_seconds', t, self.slo_miss_s)
        if self.weights_enabled:
            report.metric('sim_bucket_readers', t,
                          float(self._bucket_inflight))
            report.metric('sim_peer_pulls_inflight', t,
                          float(self._peer_inflight))
        if self.lora_enabled:
            report.metric('sim_lora_misses_total', t, self.lora_misses)
            report.metric('sim_lora_evictions_total', t,
                          float(self.lora_evictions))
            report.metric('sim_lora_resident', t,
                          float(len(self._lora_cache)))
        if self.rl_enabled:
            report.metric('sim_rl_learner_version', t,
                          float(self.rl_learner_version))
            report.metric('sim_rl_queue_batches', t,
                          sum(c[1] for c in self._rl_queue))
            report.metric('sim_rl_tokens_total', t,
                          self.rl_tokens_total)
            report.metric('sim_rl_refreshing', t,
                          float(len(self._rl_refreshing)))
            report.metric('sim_rl_staleness_max', t,
                          float(self.rl_staleness_max))
        if self.disagg_enabled:
            last = self._disagg_last
            report.metric('sim_ttft_p99_ms', t, last['ttft_ms'])
            report.metric('sim_intertoken_p99_ms', t, last['itl_ms'])
            report.metric('sim_prefill_ready', t, last['n_pre'])
            report.metric('sim_decode_ready', t, last['n_dec'])
            report.metric('sim_prefill_queue', t, self.pre_queue)
            report.metric('sim_decode_queue', t, self.dec_queue)

    def _assert_conservation(self, t: float) -> None:
        conservation = (self.arrived_total -
                        (self.served_total + self.queue +
                         self.shed_total))
        if abs(conservation) > 1e-6 * max(1.0, self.arrived_total):
            raise AssertionError(
                f'request conservation violated at t={t}: '
                f'residual {conservation}')

    def _flow_disagg(self, t: float, dt: float,
                     ready: List[SimReplicaRecord], arrived: int):
        """One tick of the two-stage pipeline. Requests queue at
        prefill (TTFT = the prefill stage's base+slope*c line,
        saturating when its queue builds), then hand off to decode.
        Decode replicas serve at a bounded per-replica concurrency —
        the paged-KV slot cap — so a saturated decode fleet degrades
        inter-token latency only to its ceiling while the overflow
        queues; TTFT stays a pure function of the prefill fleet. That
        separation is exactly what disagg_saturation.yaml's
        max_ttft_p99_s invariant pins."""
        pre_ready = [r for r in ready if r.role == 'prefill']
        dec_ready = [r for r in ready if r.role != 'prefill']
        n_pre, n_dec = len(pre_ready), len(dec_ready)

        tokens = self.tokens_per_request
        shift = self.tokens_shift
        if shift is not None and \
                shift['at'] <= t < shift['at'] + shift['duration_s']:
            tokens *= float(shift['factor'])
        # Longer generations shrink per-replica decode admission
        # (sojourn = tokens * itl) with no change in offered qps.
        dec_cap_qps = self.dec_capacity_qps * (
            self.tokens_per_request / tokens)

        # Prefill stage: serve up to capacity, shed past the cap.
        backlog = self.pre_queue + arrived
        prefilled = min(backlog, n_pre * self.pre_capacity_qps * dt)
        self.pre_queue = backlog - prefilled
        pre_shed = max(0.0, self.pre_queue -
                       self.max_queue_per_replica * max(n_pre, 1))
        self.pre_queue -= pre_shed

        # Decode stage: prefilled requests enter the decode service.
        backlog = self.dec_queue + prefilled
        served = min(backlog, n_dec * dec_cap_qps * dt)
        self.dec_queue = backlog - served
        dec_shed = max(0.0, self.dec_queue -
                       self.max_queue_per_replica * max(n_dec, 1))
        self.dec_queue -= dec_shed

        self.queue = self.pre_queue + self.dec_queue
        self.served_total += served
        self.shed_total += pre_shed + dec_shed
        self._assert_conservation(t)

        # Ground truth. TTFT saturates on prefill backlog; decode
        # concurrency is Little's law with the token-scaled sojourn
        # (fleet_point over qps*tokens — same closed form), capped at
        # the slot ceiling.
        demand_qps = arrived / dt
        ttft_ms, pre_conc = fleet_point(
            demand_qps, n_pre, self.pre_base_ms, self.pre_slope_ms,
            self.pre_saturated_ms)
        if self.pre_queue > 1.0:
            ttft_ms = self.pre_saturated_ms
            pre_conc = self.pre_queue / max(n_pre, 1)
        _, dec_conc = fleet_point(
            (prefilled / dt) * tokens, n_dec, self.dec_base_ms,
            self.dec_slope_ms, self.dec_saturated_ms)
        if self.dec_queue > 1.0 or n_dec == 0:
            dec_conc = self.dec_max_conc
        dec_conc = min(dec_conc, self.dec_max_conc)
        itl_ms = self.dec_base_ms + self.dec_slope_ms * dec_conc

        active = demand_qps > 1e-9 or self.queue > 1.0
        if active:
            self.ttft_samples.append(ttft_ms)
            self.itl_samples.append(itl_ms)
            if (ttft_ms > self.spec.target_ttft_p99_ms + 1e-9 or
                    itl_ms > self.spec.target_intertoken_p99_ms + 1e-9
                    or n_pre == 0 or n_dec == 0):
                self.slo_miss_s += dt

        # Per-role telemetry shaped exactly like the serve LB's:
        # TTFB EWMAs for prefill, streamed inter-chunk EWMAs + slot
        # occupancy for decode.
        latency_ms = {r.replica_id: ttft_ms for r in pre_ready}
        intertoken_ms = {r.replica_id: itl_ms for r in dec_ready}
        # Integer slots per replica, but quantized so the FLEET sum is
        # exact — the autoscaler fits on summed occupancy, and naive
        # per-replica rounding injects up to 0.5*n of noise into it.
        in_flight: Dict[int, int] = {}
        for members, conc in ((pre_ready, pre_conc),
                              (dec_ready, dec_conc)):
            if not members:
                continue
            whole, extra = divmod(int(round(conc * len(members))),
                                  len(members))
            for index, record in enumerate(members):
                in_flight[record.replica_id] = whole + (
                    1 if index < extra else 0)
        stats = LoadStats(qps=demand_qps,
                          queue_length=(pre_conc * n_pre +
                                        dec_conc * n_dec),
                          window_seconds=dt,
                          replica_latency_ms=latency_ms,
                          replica_in_flight=in_flight,
                          replica_intertoken_ms=intertoken_ms)
        self._disagg_last = {'ttft_ms': ttft_ms, 'itl_ms': itl_ms,
                             'n_pre': float(n_pre),
                             'n_dec': float(n_dec)}
        return stats, ttft_ms, pre_conc

    def _apply(self, decisions, t: float) -> None:
        ups = downs = warm_stops = resumes = 0
        by_id = None
        for decision in decisions:
            if decision.op == DecisionOp.SCALE_UP:
                if decision.resume_replica_id is not None:
                    if by_id is None:
                        by_id = {r.replica_id: r for r in self.replicas}
                    record = by_id.get(decision.resume_replica_id)
                    if record is not None and \
                            record.status == ReplicaStatus.WARM:
                        record.status = ReplicaStatus.PROVISIONING
                        record.warm_since = None
                        record.ready_eta = t + self.resume_delay_s
                        self.warm_resumes += 1
                        resumes += 1
                    continue
                for _ in range(max(1, decision.count)):
                    use_spot = decision.use_spot
                    if use_spot is None:
                        use_spot = self.spot
                    self._new_replica(is_spot=use_spot,
                                      is_fallback=decision.is_fallback,
                                      role=decision.role)
                    ups += 1
            else:
                if by_id is None:
                    by_id = {r.replica_id: r for r in self.replicas}
                record = by_id.get(decision.replica_id)
                if record is None or record.status.is_terminal():
                    continue
                if decision.warm:
                    record.status = ReplicaStatus.WARM
                    record.warm_since = t
                    warm_stops += 1
                else:
                    record.status = ReplicaStatus.TERMINATED
                    record.warm_since = None
                    downs += 1
        if ups or downs or warm_stops or resumes:
            self.report.event(t, 'decisions', up=ups, down=downs,
                              warm_stop=warm_stops, resume=resumes)

    def _lb_probe(self, ready: List[SimReplicaRecord],
                  n_requests: int) -> None:
        sample = ready[:_LB_REPLICA_SAMPLE]
        self.lb_policy.set_replicas(
            [(r.replica_id, '', 1.0) for r in sample])
        in_flight: Dict[int, int] = {}
        picks: Dict[int, int] = {}
        for _ in range(n_requests):
            entry = self.lb_policy.select(in_flight)
            if entry is None:
                break
            rid = entry[0]
            in_flight[rid] = in_flight.get(rid, 0) + 1
            picks[rid] = picks.get(rid, 0) + 1
        if picks:
            share = max(picks.values()) * len(sample) / max(
                1, sum(picks.values()))
            self.lb_max_share = max(self.lb_max_share, share)

    # -- results -------------------------------------------------------

    def summary(self) -> Dict[str, float]:
        out = {
            'ticks': self.ticks,
            'arrived_total': self.arrived_total,
            'served_total': round(self.served_total, 1),
            'shed_total': round(self.shed_total, 1),
            'final_queue': round(self.queue, 1),
            'slo_miss_seconds': round(self.slo_miss_s, 1),
            'replica_hours': round(self.replica_hours, 2),
            'dollar_weighted_replica_hours': round(self.dollar_hours, 2),
            'warm_pool_hours': round(self.warm_hours, 2),
            'warm_resumes': self.warm_resumes,
            'preemptions': self.preemptions,
            'provision_failures': self.provision_failures,
            'controller_faults': self.controller_faults,
            'target_flips': self.target_flips,
            'final_ready': sum(
                1 for r in self.replicas
                if r.status == ReplicaStatus.READY),
            'final_target': self.scaler._target,
            'lb_max_share': round(self.lb_max_share, 2),
            'max_bucket_readers': self.max_bucket_readers,
            'bucket_pulls': self.bucket_pulls,
            'peer_pulls': self.peer_pulls,
            'time_to_weights_p99_s': round(self._weights_p99(), 1),
        }
        if self.lora_enabled:
            # Run-level p99s the adapter-churn invariants grade
            # (max_adapter_cold_ttft_p99_ms /
            # max_base_intertoken_p99_ms in report.py).
            total = self.lora_hits + self.lora_misses
            out['lora_hits'] = round(self.lora_hits, 1)
            out['lora_misses'] = round(self.lora_misses, 1)
            out['lora_evictions'] = self.lora_evictions
            out['lora_hit_fraction'] = round(
                self.lora_hits / max(1.0, total), 4)
            out['adapter_cold_ttft_p99_ms'] = round(
                _series_p99(self.cold_ttft_samples), 2)
            out['base_intertoken_p99_ms'] = round(
                _series_p99(self.base_itl_samples), 2)
        if self.rl_enabled:
            # The numbers the RL pipeline invariants grade
            # (max_rollout_staleness_steps /
            # min_rollout_throughput_fraction /
            # max_lost_rollout_batches in report.py).
            qtotal = sum(c[1] for c in self._rl_queue)
            inflight_n = 0 if self._rl_inflight is None else 1
            lost = (self.rl_batches_produced - self.rl_batches_consumed
                    - qtotal - inflight_n)
            out['rl_learner_version'] = self.rl_learner_version
            out['rl_batches_produced'] = round(
                self.rl_batches_produced, 2)
            out['rl_batches_consumed'] = self.rl_batches_consumed
            out['rl_batches_requeued'] = self.rl_batches_requeued
            out['rl_lost_batches'] = round(max(0.0, lost), 2)
            out['rl_refreshes'] = self.rl_refreshes
            out['rl_staleness_max'] = self.rl_staleness_max
            out['rl_valve_wait_s'] = round(self.rl_valve_wait_s, 1)
            out['rl_tokens_total'] = round(self.rl_tokens_total, 1)
            out['rl_throughput_fraction'] = round(
                self.rl_tokens_total /
                max(1.0, self._rl_potential_tokens), 4)
        if self.disagg_enabled:
            # Run-level p99 over per-tick ground truth — the numbers
            # the max_ttft_p99_s / max_intertoken_p99_ms invariants
            # grade (report.py).
            out['ttft_p99_s'] = round(
                _series_p99(self.ttft_samples) / 1000.0, 3)
            out['intertoken_p99_ms'] = round(
                _series_p99(self.itl_samples), 2)
            out['final_prefill_ready'] = sum(
                1 for r in self.replicas
                if r.status == ReplicaStatus.READY
                and r.role == 'prefill')
            out['final_decode_ready'] = sum(
                1 for r in self.replicas
                if r.status == ReplicaStatus.READY
                and r.role != 'prefill')
        return out
