"""SimReport: canonical event log, metric stream, invariant verdicts.

Determinism is *proven* here, not assumed: the event log and metric
stream serialize to canonical JSON (sorted keys, fixed float
formatting, no timestamps from the host), so two runs of the same
scenario+seed produce byte-identical bytes and equal sha256 digests —
the property ``tests/test_sim.py`` pins.

``to_store()`` exports the metric stream into a
:class:`skypilot_tpu.utils.tsdb.TSDB` directory at the sim's VIRTUAL
timestamps. Point an API server's ``SKYT_TELEMETRY_DIR`` at that
directory and the run is queryable through the production
``/api/metrics/query`` surface — one Grafana-shaped pane of glass for
real fleets and simulated ones.
"""
from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional, Tuple

__all__ = ['SimReport']

# Invariant keys a scenario may assert (docs/simulation.md):
#   no_lost_requests: true        -> shed_total == 0
#   max_shed_requests: N          -> shed_total <= N
#   max_slo_miss_seconds: S       -> slo_miss_seconds <= S
#   max_target_flips: N           -> autoscaler direction reversals <= N
#   max_final_queue: N            -> backlog drained by scenario end
#   min_served_fraction: f        -> served_total/arrived_total >= f
#   max_controller_faults: N      -> injected tick crashes tolerated
#   max_bucket_readers: N         -> weight convoy stayed inside the
#                                    bucket lease bound (fleet.weights)
#   max_time_to_weights_p99_s: S  -> p99 landed-to-weights latency
#   max_ttft_p99_s: S             -> run-level p99 time-to-first-token
#                                    (fleet.disagg prefill stage)
#   max_intertoken_p99_ms: M      -> run-level p99 inter-token latency
#                                    (fleet.disagg decode stage)
#   max_adapter_cold_ttft_p99_ms: M -> p99 first-token latency of
#                                    requests whose adapter page was
#                                    cold (fleet.lora runs)
#   max_base_intertoken_p99_ms: M -> p99 inter-token latency of base
#                                    (page-0) traffic while adapters
#                                    churn (fleet.lora runs)
#   min_adapter_hit_fraction: f   -> adapter page hit rate floor
#                                    (fleet.lora runs)
#   max_rollout_staleness_steps: N -> max learner-versions-behind any
#                                    consumed rollout batch was
#                                    (fleet.rl runs; the valve bound)
#   min_rollout_throughput_fraction: f -> rollout tokens produced over
#                                    tokens the READY fleet could have
#                                    produced — per-replica normalized,
#                                    so elastic shrink doesn't fail it
#                                    (fleet.rl runs)
#   max_lost_rollout_batches: N   -> batches produced but neither
#                                    consumed, queued, nor in flight
#                                    at scenario end (fleet.rl runs;
#                                    ack/requeue conservation)
_INVARIANT_KEYS = ('no_lost_requests', 'max_shed_requests',
                   'max_slo_miss_seconds', 'max_target_flips',
                   'max_final_queue', 'min_served_fraction',
                   'max_controller_faults', 'max_bucket_readers',
                   'max_time_to_weights_p99_s', 'max_ttft_p99_s',
                   'max_intertoken_p99_ms',
                   'max_adapter_cold_ttft_p99_ms',
                   'max_base_intertoken_p99_ms',
                   'min_adapter_hit_fraction',
                   'max_rollout_staleness_steps',
                   'min_rollout_throughput_fraction',
                   'max_lost_rollout_batches')


class SimReport:
    """Accumulates one run's events + metrics; owns serialization,
    digests, invariant evaluation, and the TSDB export."""

    def __init__(self, scenario_name: str, seed: int) -> None:
        self.scenario_name = scenario_name
        self.seed = seed
        self.events: List[Dict[str, Any]] = []
        # name -> [(t, value)]; insertion order is deterministic
        # (fleet emits in a fixed order every tick).
        self.metrics: Dict[str, List[Tuple[float, float]]] = {}
        self.summary: Dict[str, Any] = {}

    # -- accumulation --------------------------------------------------

    def event(self, t: float, kind: str, **fields: Any) -> None:
        entry = {'t': round(float(t), 6), 'kind': kind}
        entry.update(fields)
        self.events.append(entry)

    def metric(self, name: str, t: float, value: float) -> None:
        self.metrics.setdefault(name, []).append(
            (round(float(t), 6), float(value)))

    # -- canonical serialization ---------------------------------------

    def event_log_bytes(self) -> bytes:
        """Canonical JSON-lines event log (sorted keys, repr floats)."""
        lines = [json.dumps(e, sort_keys=True, separators=(',', ':'))
                 for e in self.events]
        return ('\n'.join(lines) + '\n').encode()

    def metric_stream_bytes(self) -> bytes:
        """Canonical metric stream: one JSON line per series."""
        lines = [
            json.dumps({'name': name, 'points': self.metrics[name]},
                       sort_keys=True, separators=(',', ':'))
            for name in sorted(self.metrics)
        ]
        return ('\n'.join(lines) + '\n').encode()

    def digest(self) -> str:
        """sha256 over event log + metric stream — the one number two
        runs must agree on for the scenario to count as reproducible."""
        h = hashlib.sha256()
        h.update(self.event_log_bytes())
        h.update(b'\x00')
        h.update(self.metric_stream_bytes())
        return h.hexdigest()

    # -- invariants ----------------------------------------------------

    def check_invariants(self, invariants: Dict[str, Any]
                         ) -> List[Dict[str, Any]]:
        """Evaluate a scenario's invariant block against the run
        summary. Returns one verdict dict per declared invariant;
        unknown keys fail loudly (a typo must not pass vacuously)."""
        s = self.summary
        verdicts = []
        for key, bound in invariants.items():
            if key not in _INVARIANT_KEYS:
                raise ValueError(
                    f'unknown invariant {key!r}; one of '
                    f'{_INVARIANT_KEYS}')
            if key == 'no_lost_requests':
                ok = (not bound) or s['shed_total'] == 0
                actual = s['shed_total']
            elif key == 'max_shed_requests':
                actual = s['shed_total']
                ok = actual <= bound
            elif key == 'max_slo_miss_seconds':
                actual = s['slo_miss_seconds']
                ok = actual <= bound
            elif key == 'max_target_flips':
                actual = s['target_flips']
                ok = actual <= bound
            elif key == 'max_final_queue':
                actual = s['final_queue']
                ok = actual <= bound
            elif key == 'min_served_fraction':
                actual = (s['served_total'] /
                          max(1, s['arrived_total']))
                ok = actual >= bound
            elif key == 'max_bucket_readers':
                actual = s['max_bucket_readers']
                ok = actual <= bound
            elif key == 'max_time_to_weights_p99_s':
                actual = s['time_to_weights_p99_s']
                ok = actual <= bound
            elif key == 'max_ttft_p99_s':
                actual = s['ttft_p99_s']
                ok = actual <= bound
            elif key == 'max_intertoken_p99_ms':
                actual = s['intertoken_p99_ms']
                ok = actual <= bound
            elif key == 'max_adapter_cold_ttft_p99_ms':
                actual = s['adapter_cold_ttft_p99_ms']
                ok = actual <= bound
            elif key == 'max_base_intertoken_p99_ms':
                actual = s['base_intertoken_p99_ms']
                ok = actual <= bound
            elif key == 'min_adapter_hit_fraction':
                actual = s['lora_hit_fraction']
                ok = actual >= bound
            elif key == 'max_rollout_staleness_steps':
                actual = s['rl_staleness_max']
                ok = actual <= bound
            elif key == 'min_rollout_throughput_fraction':
                actual = s['rl_throughput_fraction']
                ok = actual >= bound
            elif key == 'max_lost_rollout_batches':
                actual = s['rl_lost_batches']
                ok = actual <= bound
            else:  # max_controller_faults
                actual = s['controller_faults']
                ok = actual <= bound
            verdicts.append({'invariant': key, 'bound': bound,
                             'actual': actual, 'ok': bool(ok)})
        return verdicts

    def failed_invariants(self, invariants: Dict[str, Any]
                          ) -> List[Dict[str, Any]]:
        return [v for v in self.check_invariants(invariants)
                if not v['ok']]

    # -- TSDB export ---------------------------------------------------

    def to_store(self, root: str,
                 labels: Optional[Dict[str, str]] = None) -> int:
        """Write the metric stream into a TSDB directory at the sim's
        virtual timestamps; returns points written. Retention is set
        far past any virtual day so small virtual timestamps are never
        reclaimed against the wall clock at flush time."""
        from skypilot_tpu.utils import tsdb
        labels = dict(labels or {})
        labels.setdefault('scenario', self.scenario_name)
        labels.setdefault('seed', str(self.seed))
        store = tsdb.TSDB(root,
                          raw_retention_s=365 * 86400.0,
                          rollup_retention_s=365 * 86400.0)
        written = 0
        for name in sorted(self.metrics):
            for t, value in self.metrics[name]:
                store.ingest(name, labels, value, ts=t)
                written += 1
        store.flush(force=True)
        return written

    # -- full artifact -------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            'scenario': self.scenario_name,
            'seed': self.seed,
            'summary': dict(self.summary),
            'digest': self.digest(),
            'events': len(self.events),
            'metric_series': sorted(self.metrics),
        }
