"""Simulation kernel: virtual clock, seeded RNG streams, event heap.

The whole point of simkit is that every component — controller tick,
autoscaler hysteresis, traffic generator, fault timeline, provider
delays — reads time from ONE :class:`SimClock` and randomness from ONE
:class:`SimRng`, and advances only through the :class:`EventLoop`'s
heap. No real threads touch the hot path, no wall clock leaks in, so a
run is a pure function of (scenario, seed): FoundationDB's simulation
discipline (Zhou et al., SIGMOD '21) in ~200 lines.

Determinism rules enforced here:

* Events fire in ``(time, seq)`` order — ``seq`` is a global schedule
  counter, so two events at the same virtual instant fire in the order
  they were scheduled, never in hash or heap-internal order.
* :class:`SimRng` hands out named child streams derived from
  ``sha256(seed, name)``. Consumers draw from *their own* stream, so
  adding a new consumer (or reordering draws inside one) never shifts
  the sequence another consumer sees — the classic simulation-rng
  pitfall where one extra ``random()`` call reshuffles the whole run.
* Cancellation is a tombstone (``Event.cancelled``), not a heap
  removal — O(1), and the pop loop skips tombstones, so cancelling
  never perturbs sibling ordering.
"""
from __future__ import annotations

import hashlib
import heapq
import random
from typing import Callable, Dict, List, Optional

__all__ = ['Event', 'EventLoop', 'SimClock', 'SimRng']


class SimClock:
    """Monotonic virtual clock. ``now()`` is the drop-in for
    ``time.monotonic`` / ``time.time`` on sim-reachable code paths —
    pass ``clock.now`` wherever a component takes an injectable clock.
    Only the :class:`EventLoop` advances it."""

    def __init__(self, start: float = 0.0) -> None:
        self._now = float(start)

    def now(self) -> float:
        return self._now

    # The loop is the sole writer; components never set time.
    def _advance_to(self, t: float) -> None:
        if t < self._now:
            raise ValueError(
                f'virtual clock cannot go backwards: {self._now} -> {t}')
        self._now = t

    def __call__(self) -> float:
        # Convenience: a SimClock instance itself is a valid ``clock``
        # callable (`scaler._clock = sim.clock`).
        return self._now


class SimRng:
    """Root of a tree of named, deterministic RNG streams.

    ``rng.stream('traffic.tenant0')`` always returns the same
    ``random.Random`` state for a given ``(seed, name)`` — derived via
    sha256, not ``seed + hash(name)``, so streams are independent and
    stable across Python hash randomization.
    """

    def __init__(self, seed: int) -> None:
        self.seed = int(seed)
        self._streams: Dict[str, random.Random] = {}

    def stream(self, name: str) -> random.Random:
        rng = self._streams.get(name)
        if rng is None:
            digest = hashlib.sha256(
                f'{self.seed}/{name}'.encode()).digest()
            rng = random.Random(int.from_bytes(digest[:8], 'big'))
            self._streams[name] = rng
        return rng


class Event:
    """A scheduled callback. ``cancel()`` tombstones it in place."""

    __slots__ = ('time', 'seq', 'fn', 'cancelled')

    def __init__(self, time: float, seq: int,
                 fn: Callable[[], None]) -> None:
        self.time = time
        self.seq = seq
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def __lt__(self, other: 'Event') -> bool:
        return (self.time, self.seq) < (other.time, other.seq)


class EventLoop:
    """Discrete-event loop over a binary heap.

    Primitives:

    * ``at(t, fn)`` — fire ``fn`` at absolute virtual time ``t``;
    * ``after(dt, fn)`` — relative form (the sim spelling of
      ``sleep``);
    * ``every(dt, fn, start=None)`` — periodic; ``fn`` may return
      ``False`` to stop the series; returns the *handle* whose
      ``cancel()`` stops future firings.

    ``run_until(t)`` pops events in ``(time, seq)`` order, advancing
    the clock to each event's stamp, until the heap drains or the next
    event lies beyond ``t`` (the clock then rests exactly at ``t``).
    Callbacks run inline and may schedule more events, including at the
    current instant (they get a later seq, so they still fire this
    instant, after already-queued same-time events).
    """

    def __init__(self, clock: Optional[SimClock] = None,
                 rng: Optional[SimRng] = None, seed: int = 0) -> None:
        self.clock = clock or SimClock()
        self.rng = rng or SimRng(seed)
        self._heap: List[Event] = []
        self._seq = 0
        self.fired = 0          # events executed (throughput metric)

    # -- scheduling ----------------------------------------------------

    def at(self, t: float, fn: Callable[[], None]) -> Event:
        if t < self.clock.now():
            raise ValueError(
                f'cannot schedule at {t} < now {self.clock.now()}')
        event = Event(t, self._seq, fn)
        self._seq += 1
        heapq.heappush(self._heap, event)
        return event

    def after(self, dt: float, fn: Callable[[], None]) -> Event:
        if dt < 0:
            raise ValueError(f'negative delay {dt}')
        return self.at(self.clock.now() + dt, fn)

    def every(self, dt: float, fn: Callable[[], object],
              start: Optional[float] = None) -> Event:
        """Periodic series. The returned handle's ``cancel()`` stops
        the series (each firing re-arms through the handle, which is
        mutated in place to point at the next occurrence)."""
        if dt <= 0:
            raise ValueError(f'period must be > 0, got {dt}')
        first = self.clock.now() + dt if start is None else start
        # The handle never enters the heap; it only carries the
        # ``cancelled`` tombstone every firing checks before running.
        handle = Event(first, -1, lambda: None)

        def tick() -> None:
            if handle.cancelled:
                return
            if fn() is False:
                handle.cancelled = True
                return
            if not handle.cancelled:      # fn() may have cancelled us
                nxt = self.at(self.clock.now() + dt, tick)
                handle.time = nxt.time

        self.at(first, tick)
        return handle

    # -- running -------------------------------------------------------

    def run_until(self, t: float) -> int:
        """Run events with stamp <= ``t``; leave the clock at ``t``.
        Returns the number of events fired."""
        fired_before = self.fired
        heap = self._heap
        while heap:
            event = heap[0]
            if event.time > t:
                break
            heapq.heappop(heap)
            if event.cancelled:
                continue
            self.clock._advance_to(event.time)
            event.fn()
            self.fired += 1
        self.clock._advance_to(max(t, self.clock.now()))
        return self.fired - fired_before

    def run(self) -> int:
        """Drain the heap completely (bounded scenarios only)."""
        fired_before = self.fired
        heap = self._heap
        while heap:
            event = heapq.heappop(heap)
            if event.cancelled:
                continue
            self.clock._advance_to(event.time)
            event.fn()
            self.fired += 1
        return self.fired - fired_before

    def pending(self) -> int:
        return sum(1 for e in self._heap if not e.cancelled)
