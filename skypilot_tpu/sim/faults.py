"""Scenario fault timeline: virtual-time failure injection.

Fault kinds (scenario ``faults:`` entries, all with an ``at`` virtual
time):

* ``region_outage`` — every replica in ``region`` dies, the region is
  unplaceable for ``duration_s`` (in-flight provisions into it fail
  when they land), and each of its domains takes a preemption
  cooldown.
* ``spot_reclaim`` — correlated spot reclamation: ``fraction`` of the
  live spot replicas in ``zone`` (or the whole fleet when no zone) are
  preempted at one instant, sampled from the ``faults`` RNG stream.
* ``provision_slowdown`` — cold-provision latency multiplied by
  ``factor`` for ``duration_s`` (capacity crunch: the autoscaler's
  horizon is suddenly too short).
* ``rollout`` — a weight rollout: rolling restart of the fleet in
  ``batch``-sized waves every ``interval_s``, each wave NOT READY for
  ``restart_s`` (generalizes the weight-rollout-during-surge drill).
* ``learner_preempt`` — the RL pipeline's learner (``fleet.rl``
  scenarios) is preempted for ``down_s``: no batch consumption, no
  policy-version bumps; its in-flight batch is requeued at the front
  (the no-lost-batches drill).
* ``fault_spec`` — replay a recorded ``SKYT_FAULT_SPEC`` value for
  ``duration_s``: the sim's controller tick runs
  ``fault_injection.inject('sim.controller.tick')``, so a clause like
  ``sim.controller.tick:OperationalError:p=0.3:seed=7`` crashes a
  deterministic subsequence of ticks — the same chaos grammar the
  real control plane is drilled with, on the virtual clock.
"""
from __future__ import annotations

import math
import os
from typing import Dict, List

from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.utils import fault_injection

__all__ = ['install_faults']


def install_faults(fleet: 'FleetSim', faults: List[Dict]) -> None:
    """Schedule every scenario fault on the fleet's event loop."""
    for fault in faults:
        kind = fault['kind']
        at = float(fault['at'])
        if kind == 'region_outage':
            _install_region_outage(fleet, at, fault)
        elif kind == 'spot_reclaim':
            _install_spot_reclaim(fleet, at, fault)
        elif kind == 'provision_slowdown':
            _install_provision_slowdown(fleet, at, fault)
        elif kind == 'rollout':
            _install_rollout(fleet, at, fault)
        elif kind == 'learner_preempt':
            _install_learner_preempt(fleet, at, fault)
        elif kind == 'fault_spec':
            _install_fault_spec(fleet, at, fault)
        else:  # scenario validation already rejected this
            raise ValueError(f'unknown fault kind {kind!r}')


def _install_region_outage(fleet, at: float, fault: Dict) -> None:
    region = fault['region']
    duration = float(fault.get('duration_s', 3600.0))

    def start() -> None:
        fleet.down_regions.add(region)
        killed = 0
        for record in fleet.replicas:
            if record.region == region and \
                    not record.status.is_terminal():
                fleet.preempt(record, 'region_outage')
                killed += 1
        for domain in fleet.domains:
            if domain.region == region:
                fleet.placer.handle_preemption(domain)
        fleet.report.event(fleet.clock.now(), 'region_outage_start',
                           region=region, killed=killed)

    def end() -> None:
        fleet.down_regions.discard(region)
        fleet.report.event(fleet.clock.now(), 'region_outage_end',
                           region=region)

    fleet.loop.at(at, start)
    fleet.loop.at(at + duration, end)


def _install_spot_reclaim(fleet, at: float, fault: Dict) -> None:
    zone = fault.get('zone')
    fraction = float(fault.get('fraction', 0.5))

    def reclaim() -> None:
        rng = fleet.loop.rng.stream('faults')
        victims = [r for r in fleet.replicas
                   if r.is_spot and not r.status.is_terminal() and
                   r.status != ReplicaStatus.WARM and
                   (zone is None or r.zone == zone)]
        count = int(math.ceil(len(victims) * fraction))
        # Deterministic sample: stable order in, seeded draw out.
        victims.sort(key=lambda r: r.replica_id)
        chosen = rng.sample(victims, count) if count < len(victims) \
            else victims
        for record in chosen:
            fleet.preempt(record, 'spot_reclaim')
        fleet.report.event(fleet.clock.now(), 'spot_reclaim',
                           zone=zone or '*', reclaimed=len(chosen))

    fleet.loop.at(at, reclaim)


def _install_provision_slowdown(fleet, at: float, fault: Dict) -> None:
    factor = float(fault.get('factor', 4.0))
    duration = float(fault.get('duration_s', 3600.0))

    def start() -> None:
        fleet._provision_factor = factor
        fleet.report.event(fleet.clock.now(), 'provision_slowdown_start',
                           factor=factor)

    def end() -> None:
        fleet._provision_factor = 1.0
        fleet.report.event(fleet.clock.now(), 'provision_slowdown_end')

    fleet.loop.at(at, start)
    fleet.loop.at(at + duration, end)


def _install_rollout(fleet, at: float, fault: Dict) -> None:
    batch = int(fault.get('batch', 1))
    interval = float(fault.get('interval_s', 60.0))
    restart_s = float(fault.get('restart_s', 30.0))
    pending: List[int] = []

    def start() -> None:
        # Snapshot the fleet to roll: replicas launched later already
        # run the new weights.
        pending.extend(sorted(
            r.replica_id for r in fleet.replicas
            if r.status == ReplicaStatus.READY))
        fleet.report.event(fleet.clock.now(), 'rollout_start',
                           replicas=len(pending))
        wave()

    def wave() -> None:
        if not pending:
            fleet.report.event(fleet.clock.now(), 'rollout_done')
            return
        by_id = {r.replica_id: r for r in fleet.replicas}
        rolled = 0
        while pending and rolled < batch:
            record = by_id.get(pending.pop(0))
            if record is None or \
                    record.status != ReplicaStatus.READY:
                continue    # preempted/scaled down since the snapshot
            record.status = ReplicaStatus.STARTING
            record.ready_eta = fleet.clock.now() + restart_s
            rolled += 1
        fleet.loop.after(interval, wave)

    fleet.loop.at(at, start)


def _install_learner_preempt(fleet, at: float, fault: Dict) -> None:
    down_s = float(fault.get('down_s', 120.0))

    def preempt() -> None:
        requeued = fleet.rl_learner_preempt(fleet.clock.now(), down_s)
        fleet.report.event(fleet.clock.now(), 'learner_preempt',
                           down_s=down_s, requeued=requeued)

    fleet.loop.at(at, preempt)


def _install_fault_spec(fleet, at: float, fault: Dict) -> None:
    spec = fault['spec']
    duration = float(fault.get('duration_s', 600.0))

    def start() -> None:
        os.environ[fault_injection.SPEC_ENV] = spec
        fault_injection.reset()
        fleet.report.event(fleet.clock.now(), 'fault_spec_start',
                           spec=spec)

    def end() -> None:
        os.environ.pop(fault_injection.SPEC_ENV, None)
        fault_injection.reset()
        fleet.report.event(fleet.clock.now(), 'fault_spec_end')

    fleet.loop.at(at, start)
    fleet.loop.at(at + duration, end)
