"""Traffic generators: deterministic arrival processes for sim tenants.

A *rate function* maps virtual time to instantaneous demand (QPS).
Scenario tenants declare one of the registered shapes (constant,
diurnal, burst, ramp, flood) or compose several additively; the fleet
model samples per-tick arrival counts from a seeded Poisson stream.

These are also the library the control-plane benches draw from:
``bench_control_scale.py``'s Poisson/paced submitters use
:func:`arrival_gaps` and :func:`zipf_weights` instead of hand-rolled
``random.Random`` loops (r16 dedup satellite).
"""
from __future__ import annotations

import math
import random
from typing import Callable, Dict, List, Sequence

RateFn = Callable[[float], float]

__all__ = ['RateFn', 'arrival_gaps', 'make_rate', 'pick_weighted',
           'poisson_count', 'sum_rates', 'zipf_weights']


# -- rate shapes ------------------------------------------------------------


def constant(qps: float) -> RateFn:
    return lambda t: qps


def diurnal(base_qps: float, amplitude_qps: float,
            period_s: float = 86400.0, phase_s: float = 0.0) -> RateFn:
    """Sinusoidal day shape: ``base + amp * sin(2*pi*(t+phase)/period)``
    clamped at zero (the r11 autoscale bench's ``lam(t)``,
    generalized)."""

    def rate(t: float) -> float:
        return max(0.0, base_qps + amplitude_qps *
                   math.sin(2.0 * math.pi * (t + phase_s) / period_s))

    return rate


def burst(start_s: float, end_s: float, qps: float) -> RateFn:
    """Additive square burst in ``[start, end)`` — compose with a
    baseline via :func:`sum_rates`."""
    return lambda t: qps if start_s <= t < end_s else 0.0


def ramp(start_s: float, end_s: float, to_qps: float,
         from_qps: float = 0.0) -> RateFn:
    """Linear ramp from ``from_qps`` at ``start`` to ``to_qps`` at
    ``end``, holding ``to_qps`` after."""

    def rate(t: float) -> float:
        if t < start_s:
            return from_qps
        if t >= end_s:
            return to_qps
        frac = (t - start_s) / max(end_s - start_s, 1e-9)
        return from_qps + (to_qps - from_qps) * frac

    return rate


def flood(start_s: float, duration_s: float, peak_qps: float,
          attack_s: float = 60.0) -> RateFn:
    """Hot-tenant flood (the r15 trace generalized): near-instant
    attack to ``peak_qps``, sustained for ``duration_s``, then gone."""

    def rate(t: float) -> float:
        if t < start_s or t >= start_s + duration_s:
            return 0.0
        ramp_frac = min(1.0, (t - start_s) / max(attack_s, 1e-9))
        return peak_qps * ramp_frac

    return rate


_SHAPES: Dict[str, Callable[..., RateFn]] = {
    'constant': constant,
    'diurnal': diurnal,
    'burst': burst,
    'ramp': ramp,
    'flood': flood,
}


def make_rate(spec: dict) -> RateFn:
    """Build a rate function from a scenario dict:
    ``{shape: diurnal, base_qps: 300, amplitude_qps: 250}``. A list
    under ``compose`` sums sub-shapes."""
    if 'compose' in spec:
        return sum_rates([make_rate(s) for s in spec['compose']])
    spec = dict(spec)
    shape = spec.pop('shape', 'constant')
    if shape not in _SHAPES:
        raise ValueError(
            f'unknown traffic shape {shape!r}; one of {sorted(_SHAPES)}')
    return _SHAPES[shape](**spec)


def sum_rates(rates: Sequence[RateFn]) -> RateFn:
    rates = list(rates)
    return lambda t: sum(r(t) for r in rates)


# -- sampling ---------------------------------------------------------------


def poisson_count(rng: random.Random, lam: float) -> int:
    """One Poisson(lam) draw. Knuth's product method below ~30 (exact),
    a rounded normal approximation above (lam that large is an
    aggregate count where the approximation error is far below the
    model's own fidelity — and it keeps 10k-replica ticks O(1))."""
    if lam <= 0.0:
        return 0
    if lam < 30.0:
        limit = math.exp(-lam)
        count, product = 0, rng.random()
        while product > limit:
            count += 1
            product *= rng.random()
        return count
    return max(0, int(round(rng.normalvariate(lam, math.sqrt(lam)))))


def arrival_gaps(rng: random.Random, qps: float):
    """Infinite generator of exponential inter-arrival gaps (seconds)
    for a Poisson process at ``qps`` — the primitive the control-plane
    bench's open-loop submitters pace themselves with."""
    if qps <= 0:
        raise ValueError(f'qps must be > 0, got {qps}')
    while True:
        yield rng.expovariate(qps)


def zipf_weights(n: int, s: float = 1.1) -> List[float]:
    """Zipf(s) popularity weights over ``n`` items (heavy-head tenant
    mix; item 0 is the hot tenant)."""
    if n <= 0:
        raise ValueError(f'n must be > 0, got {n}')
    weights = [1.0 / (rank ** s) for rank in range(1, n + 1)]
    total = sum(weights)
    return [w / total for w in weights]


def pick_weighted(rng: random.Random, weights: Sequence[float]) -> int:
    """Index draw from a normalized weight vector."""
    roll = rng.random()
    acc = 0.0
    for index, weight in enumerate(weights):
        acc += weight
        if roll < acc:
            return index
    return len(weights) - 1
