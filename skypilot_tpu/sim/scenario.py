"""Declarative scenario spec + the in-tree scenario library.

A scenario is a YAML document (or plain dict) that fully determines a
simulation run::

    name: region_outage
    seed: 42
    duration_s: 86400          # one simulated day
    tick_s: 60                 # controller cadence
    service:                   # ServiceSpec kwargs (service_spec.py)
      min_replicas: 8
      max_replicas: 12000
      target_latency_p99_ms: 200
      forecaster: seasonal
    fleet:
      initial_replicas: 10000  # warm-started READY fleet at t=0
      base_latency_ms: 40      # ground-truth p99 ~= base + slope*c
      latency_slope_ms: 8
      provision_delay_s: 120
      resume_delay_s: 20
      spot: true
      max_queue_per_replica: 200
      domains:                 # placement/failure domains
        - {cloud: gcp, region: us-central1, zone: a, price: 1.2}
    lb_policy: p2c_ewma        # behavioral probe (bounded sample)
    tenants:
      - name: base
        rate: {shape: diurnal, base_qps: 300, amplitude_qps: 250}
    faults:                    # virtual-time fault timeline
      - {at: 30000, kind: region_outage, region: us-central1,
         duration_s: 3600}
    invariants:
      no_lost_requests: true
      max_slo_miss_seconds: 1800
      max_target_flips: 40

Everything is data: the same file drives tier-1 invariant tests,
``bench_sim.py``, and ``python -m skypilot_tpu.sim run <file>``.
``Scenario.scale(f)`` shrinks/grows a scenario (fleet, traffic, queue
caps) so the 10k-replica library scenarios double as fast smoke tests.
"""
from __future__ import annotations

import copy
import os
from typing import Any, Dict, List, Optional

__all__ = ['Scenario', 'library_dir', 'library_names', 'load_library']

_FAULT_KINDS = ('region_outage', 'spot_reclaim', 'provision_slowdown',
                'rollout', 'learner_preempt', 'fault_spec')

_FLEET_DEFAULTS = {
    'initial_replicas': 0,
    'base_latency_ms': 40.0,
    'latency_slope_ms': 8.0,
    'provision_delay_s': 120.0,
    'resume_delay_s': 20.0,
    'spot': False,
    'max_queue_per_replica': 200.0,
    'domains': [{'cloud': 'gcp', 'region': 'us-central1', 'zone': 'a',
                 'price': 1.0}],
}


class Scenario:
    """Validated scenario spec. Construct via :meth:`from_dict`,
    :meth:`from_file`, or :func:`load_library`."""

    def __init__(self, data: Dict[str, Any],
                 source: Optional[str] = None) -> None:
        self._data = copy.deepcopy(data)
        self.source = source
        self._validate()

    # -- accessors -----------------------------------------------------

    @property
    def name(self) -> str:
        return self._data['name']

    @property
    def seed(self) -> int:
        return int(self._data.get('seed', 0))

    @property
    def duration_s(self) -> float:
        return float(self._data['duration_s'])

    @property
    def tick_s(self) -> float:
        return float(self._data.get('tick_s', 10.0))

    @property
    def service(self) -> Dict[str, Any]:
        return dict(self._data.get('service', {}))

    @property
    def fleet(self) -> Dict[str, Any]:
        merged = dict(_FLEET_DEFAULTS)
        merged.update(self._data.get('fleet', {}))
        return merged

    @property
    def lb_policy(self) -> Optional[str]:
        return self._data.get('lb_policy')

    @property
    def tenants(self) -> List[Dict[str, Any]]:
        return [dict(t) for t in self._data.get('tenants', [])]

    @property
    def faults(self) -> List[Dict[str, Any]]:
        return [dict(f) for f in self._data.get('faults', [])]

    @property
    def invariants(self) -> Dict[str, Any]:
        return dict(self._data.get('invariants', {}))

    def to_dict(self) -> Dict[str, Any]:
        return copy.deepcopy(self._data)

    # -- construction --------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any],
                  source: Optional[str] = None) -> 'Scenario':
        return cls(data, source=source)

    @classmethod
    def from_file(cls, path: str) -> 'Scenario':
        import yaml
        with open(path, encoding='utf-8') as f:
            data = yaml.safe_load(f)
        if not isinstance(data, dict):
            raise ValueError(f'scenario file {path} is not a mapping')
        return cls(data, source=path)

    def with_overrides(self, **overrides: Any) -> 'Scenario':
        """Copy with top-level keys replaced (``seed=...`` etc.)."""
        data = self.to_dict()
        data.update(overrides)
        return Scenario(data, source=self.source)

    def scale(self, factor: float) -> 'Scenario':
        """Shrink (factor < 1) or grow a scenario proportionally:
        fleet size, replica bounds, and every tenant's traffic scale
        together so per-replica load — and therefore the emergent
        behavior under test — is preserved."""
        if factor <= 0:
            raise ValueError(f'scale factor must be > 0, got {factor}')
        data = self.to_dict()
        fleet = data.setdefault('fleet', {})
        base = self.fleet
        fleet['initial_replicas'] = max(
            0, int(round(base['initial_replicas'] * factor)))
        for role in ('prefill', 'decode'):
            block = (fleet.get('disagg') or {}).get(role)
            if block and block.get('initial_replicas'):
                # Per-role warm starts scale with the fleet; latency
                # lines and tokens_per_request are per-replica and
                # therefore scale-invariant.
                block['initial_replicas'] = max(
                    1, int(round(block['initial_replicas'] * factor)))
        lora = fleet.get('lora')
        if lora and lora.get('n_adapters'):
            # The adapter population scales with the fleet so
            # per-replica page pressure (distinct working set over
            # n_ready * pages_per_replica page capacity) — and
            # therefore the hit/eviction behavior under test — is
            # preserved.
            lora['n_adapters'] = max(
                1, int(round(lora['n_adapters'] * factor)))
            if lora.get('hot_set'):
                # Rotation churn (cold fetches per period) also
                # scales, keeping per-replica fetch pressure fixed.
                lora['hot_set'] = max(
                    1, int(round(lora['hot_set'] * factor)))
        rl = fleet.get('rl')
        if rl:
            # Rollout production scales with the fleet; the learner's
            # consumption rate must scale WITH it or a shrunk smoke
            # run becomes learner-rich (valve never closes) and a
            # grown one learner-bound (valve always closed) — either
            # would change the behavior under test.
            rl['learn_step_s'] = (
                float(rl.get('learn_step_s', 0.5)) / factor)
        service = data.setdefault('service', {})
        for key in ('min_replicas', 'max_replicas',
                    'base_ondemand_fallback_replicas'):
            if service.get(key):
                service[key] = max(1, int(round(service[key] * factor)))
        for tenant in data.get('tenants', []):
            tenant['rate'] = _scale_rate(tenant.get('rate', {}), factor)
        for fault in data.get('faults', []):
            # Count-valued fault knobs (rollout wave size) scale with
            # the fleet; fraction-valued ones are scale-invariant.
            if 'batch' in fault:
                fault['batch'] = max(1, int(round(fault['batch'] *
                                                  factor)))
        for inv in ('max_shed_requests',):
            if data.get('invariants', {}).get(inv):
                data['invariants'][inv] = int(
                    round(data['invariants'][inv] * factor))
        return Scenario(data, source=self.source)

    # -- validation ----------------------------------------------------

    def _validate(self) -> None:
        data = self._data
        for key in ('name', 'duration_s'):
            if key not in data:
                raise ValueError(f'scenario missing required key {key!r}')
        if float(data['duration_s']) <= 0:
            raise ValueError('duration_s must be > 0')
        if self.tick_s <= 0:
            raise ValueError('tick_s must be > 0')
        for tenant in data.get('tenants', []):
            if 'name' not in tenant or 'rate' not in tenant:
                raise ValueError(
                    f'tenant entry {tenant!r} needs name and rate')
            # Fail at load, not mid-run: build (and discard) the rate.
            from skypilot_tpu.sim import traffic
            traffic.make_rate(tenant['rate'])
        for fault in data.get('faults', []):
            kind = fault.get('kind')
            if kind not in _FAULT_KINDS:
                raise ValueError(
                    f'unknown fault kind {kind!r}; one of {_FAULT_KINDS}')
            if 'at' not in fault:
                raise ValueError(f'fault {fault!r} needs an `at` time')
            if kind == 'learner_preempt' and \
                    not self.fleet.get('rl'):
                raise ValueError(
                    'learner_preempt faults need a fleet.rl block '
                    '(there is no learner to preempt otherwise)')
            if kind == 'fault_spec':
                # Parse at load, not mid-run: a malformed spec would
                # otherwise raise inside every controller tick and be
                # mistaken for injected chaos.
                from skypilot_tpu.utils import fault_injection
                fault_injection.parse_spec(fault['spec'])
        lora = self.fleet.get('lora')
        if lora:
            if self.fleet.get('disagg'):
                raise ValueError(
                    'fleet.lora and fleet.disagg cannot be combined '
                    '(the adapter LRU models the colocated decode '
                    'path)')
            for key in ('n_adapters', 'pages_per_replica'):
                if not lora.get(key):
                    raise ValueError(f'fleet.lora needs {key!r}')
        rl = self.fleet.get('rl')
        if rl:
            for key in ('wave_tokens', 'tokens_per_replica_s',
                        'learn_step_s', 'refresh_s'):
                if key in rl and float(rl[key]) <= 0:
                    raise ValueError(f'fleet.rl {key} must be > 0')
            mode = rl.get('refresh_mode', 'step')
            if mode not in ('step', 'drain'):
                raise ValueError(
                    "fleet.rl refresh_mode must be 'step' or 'drain'")
        if self.fleet.get('disagg'):
            service = data.get('service', {})
            if service.get('target_ttft_p99_ms') is None or \
                    service.get('target_intertoken_p99_ms') is None:
                raise ValueError(
                    'fleet.disagg scenarios need service.'
                    'target_ttft_p99_ms and service.'
                    'target_intertoken_p99_ms (the pair selects the '
                    'disagg_slo autoscaler)')
        domains = self.fleet['domains']
        if not domains:
            raise ValueError('fleet.domains must be non-empty')
        for domain in domains:
            if 'region' not in domain or 'zone' not in domain:
                raise ValueError(
                    f'domain {domain!r} needs region and zone')


def _scale_rate(rate: Dict[str, Any], factor: float) -> Dict[str, Any]:
    rate = copy.deepcopy(rate)
    if 'compose' in rate:
        rate['compose'] = [_scale_rate(r, factor)
                           for r in rate['compose']]
        return rate
    for key in ('qps', 'base_qps', 'amplitude_qps', 'to_qps',
                'from_qps', 'peak_qps'):
        if key in rate:
            rate[key] = rate[key] * factor
    return rate


# -- scenario library -------------------------------------------------------


def library_dir() -> str:
    return os.path.join(os.path.dirname(__file__), 'scenarios')


def library_names() -> List[str]:
    return sorted(
        os.path.splitext(f)[0] for f in os.listdir(library_dir())
        if f.endswith('.yaml'))


def load_library(name: str) -> Scenario:
    """Load a library scenario by stem name (``region_outage``)."""
    path = os.path.join(library_dir(), f'{name}.yaml')
    if not os.path.exists(path):
        raise FileNotFoundError(
            f'no library scenario {name!r}; available: '
            f'{library_names()}')
    return Scenario.from_file(path)
