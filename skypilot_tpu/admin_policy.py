"""Admin policy: a user-pluggable request mutation/validation hook.

Parity: ``sky/admin_policy.py`` (AdminPolicy :188, UserRequest :64).
Deployments point the config key ``admin_policy`` at a
``module.path.ClassName``; every launch-shaped request is passed through
``validate_and_mutate`` before execution, letting an operator enforce
labels, forbid clouds, cap resources, or rewrite tasks centrally.

Example::

    # ~/.skyt/config.yaml
    admin_policy: mycompany.policies.EnforceSpotPolicy
"""
from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu.spec.task import Task


@dataclasses.dataclass
class UserRequest:
    """What the policy sees: the task plus request metadata."""
    task: Task
    operation: str                      # 'launch' | 'jobs.launch' | ...
    request_options: Dict[str, Any] = dataclasses.field(
        default_factory=dict)


@dataclasses.dataclass
class MutatedUserRequest:
    task: Task


class AdminPolicy:
    """Subclass and override; raise RejectedByPolicy to deny."""

    def validate_and_mutate(self,
                            user_request: UserRequest
                            ) -> MutatedUserRequest:
        return MutatedUserRequest(task=user_request.task)


class RejectedByPolicy(exceptions.SkytError):
    """The admin policy rejected the request."""


def _load_policy() -> Optional[AdminPolicy]:
    path = config_lib.get_nested(('admin_policy',))
    if not path:
        return None
    module_name, _, class_name = str(path).rpartition('.')
    if not module_name:
        raise exceptions.InvalidSpecError(
            f'admin_policy must be module.path.ClassName, got {path!r}')
    try:
        cls = getattr(importlib.import_module(module_name), class_name)
    except (ImportError, AttributeError) as e:
        raise exceptions.InvalidSpecError(
            f'Cannot load admin policy {path!r}: {e}') from e
    policy = cls()
    if not isinstance(policy, AdminPolicy):
        raise exceptions.InvalidSpecError(
            f'{path!r} is not an AdminPolicy subclass')
    return policy


# Plugin-registered policies, chained after the configured one
# (plugins.PluginContext.register_admin_policy).
_plugin_policies: list = []


def register_policy(fn) -> None:
    """fn(UserRequest) -> MutatedUserRequest, chained per request."""
    _plugin_policies.append(fn)


def apply(task: Task, operation: str,
          request_options: Optional[Dict[str, Any]] = None) -> Task:
    """Run the configured policy over the task (no-op when unset).

    Applied exactly once per user request: controller-side relaunches
    (managed-job recovery, serve replicas) carry tasks already stamped
    ``policy_applied`` and pass through unchanged.
    """
    if task.policy_applied:
        return task
    policy = _load_policy()
    if policy is None and not _plugin_policies:
        return task
    request = UserRequest(task=task, operation=operation,
                          request_options=dict(request_options or {}))
    chain = (([policy.validate_and_mutate] if policy else []) +
             list(_plugin_policies))
    for step in chain:
        mutated = step(request)
        if not isinstance(mutated, MutatedUserRequest):
            raise exceptions.InvalidSpecError(
                'admin policy must return a MutatedUserRequest')
        request = UserRequest(task=mutated.task, operation=operation,
                              request_options=request.request_options)
    request.task.policy_applied = True
    return request.task
