"""Cluster-local job table (parity: ``sky/skylet/job_lib.py``:
JobStatus :156, JobScheduler :278 -- sqlite-backed).

All functions take the runtime dir explicitly so the same code runs (a) in
the backend process for local-style clusters, (b) under the on-node daemon,
and (c) via the `job_cli` shim over SSH.
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import events

DEFAULT_RUNTIME_DIR = '~/.skyt_runtime'


class JobStatus(enum.Enum):
    PENDING = 'PENDING'
    SETTING_UP = 'SETTING_UP'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                        JobStatus.CANCELLED)


TERMINAL_STATUSES = [s for s in JobStatus if s.is_terminal()]


def _db(runtime_dir: str) -> sqlite3.Connection:
    runtime_dir = os.path.expanduser(runtime_dir)
    os.makedirs(runtime_dir, exist_ok=True)
    conn = sqlite3.connect(os.path.join(runtime_dir, 'jobs.db'), timeout=10)
    conn.row_factory = sqlite3.Row
    conn.execute("""
        CREATE TABLE IF NOT EXISTS jobs (
            job_id INTEGER PRIMARY KEY AUTOINCREMENT,
            name TEXT,
            status TEXT NOT NULL,
            submitted_at REAL,
            started_at REAL,
            ended_at REAL,
            num_hosts INTEGER DEFAULT 1,
            exit_code INTEGER,
            metadata TEXT,
            pids TEXT
        )""")
    conn.commit()
    return conn


def add_job(runtime_dir: str, name: Optional[str],
            num_hosts: int = 1,
            metadata: Optional[Dict[str, Any]] = None,
            status: JobStatus = JobStatus.PENDING) -> int:
    conn = _db(runtime_dir)
    cur = conn.execute(
        'INSERT INTO jobs (name, status, submitted_at, num_hosts, metadata) '
        'VALUES (?,?,?,?,?)',
        (name, status.value, time.time(), num_hosts,
         json.dumps(metadata or {})))
    conn.commit()
    job_id = cur.lastrowid
    conn.close()
    # Wakes the channel server's table watcher (same process for
    # channel-submitted jobs; the on-node daemon's writes reach it via
    # the jobs.db data_version signal).
    events.publish(events.RUNTIME_JOBS)
    return job_id


def set_status(runtime_dir: str, job_id: int, status: JobStatus,
               exit_code: Optional[int] = None) -> None:
    conn = _db(runtime_dir)
    updates = {'status': status.value}
    if status == JobStatus.RUNNING:
        updates['started_at'] = time.time()
    if status.is_terminal():
        updates['ended_at'] = time.time()
    if exit_code is not None:
        updates['exit_code'] = exit_code
    sets = ', '.join(f'{k}=?' for k in updates)
    conn.execute(f'UPDATE jobs SET {sets} WHERE job_id=?',
                 (*updates.values(), job_id))
    conn.commit()
    conn.close()
    events.publish(events.RUNTIME_JOBS)


def set_pids(runtime_dir: str, job_id: int, pids: List[int]) -> None:
    conn = _db(runtime_dir)
    conn.execute('UPDATE jobs SET pids=? WHERE job_id=?',
                 (json.dumps(pids), job_id))
    conn.commit()
    conn.close()


def get_job(runtime_dir: str, job_id: int) -> Optional[Dict[str, Any]]:
    conn = _db(runtime_dir)
    row = conn.execute('SELECT * FROM jobs WHERE job_id=?',
                       (job_id,)).fetchone()
    conn.close()
    return _row_to_dict(row) if row else None


def list_jobs(runtime_dir: str,
              statuses: Optional[List[JobStatus]] = None
              ) -> List[Dict[str, Any]]:
    conn = _db(runtime_dir)
    rows = conn.execute(
        'SELECT * FROM jobs ORDER BY job_id DESC').fetchall()
    conn.close()
    jobs = [_row_to_dict(r) for r in rows]
    if statuses is not None:
        wanted = {s.value for s in statuses}
        jobs = [j for j in jobs if j['status'] in wanted]
    return jobs


def _row_to_dict(row: sqlite3.Row) -> Dict[str, Any]:
    d = dict(row)
    d['metadata'] = json.loads(d.get('metadata') or '{}')
    d['pids'] = json.loads(d['pids']) if d.get('pids') else []
    return d


def last_activity_time(runtime_dir: str) -> float:
    """Latest job submit/end time -- the autostop idleness clock
    (parity: autostop_lib idleness tracking)."""
    conn = _db(runtime_dir)
    row = conn.execute(
        'SELECT MAX(COALESCE(ended_at, submitted_at, 0)) AS t, '
        'SUM(CASE WHEN status IN (?,?,?) THEN 1 ELSE 0 END) AS active '
        'FROM jobs',
        (JobStatus.PENDING.value, JobStatus.SETTING_UP.value,
         JobStatus.RUNNING.value)).fetchone()
    conn.close()
    if row is None or row['t'] is None:
        return 0.0
    if row['active']:
        return time.time()  # active job: never idle
    return float(row['t'])


def job_log_dir(runtime_dir: str, job_id: int) -> str:
    return os.path.join(os.path.expanduser(runtime_dir), 'jobs',
                        str(job_id))


def cancel_job(runtime_dir: str, job_id: int) -> bool:
    """Mark cancelled + SIGTERM recorded pids (gang kill: a TPU program
    hangs rather than crashes on lost peers)."""
    from skypilot_tpu.utils.subprocess_utils import kill_process_tree
    job = get_job(runtime_dir, job_id)
    if job is None or JobStatus(job['status']).is_terminal():
        return False
    for pid in job['pids']:
        kill_process_tree(pid)
    set_status(runtime_dir, job_id, JobStatus.CANCELLED)
    return True
