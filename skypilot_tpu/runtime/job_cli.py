"""Remote job-table shim: the backend drives a cluster's job queue by
running this module ON the head node over SSH.

Parity: ``sky/skylet/job_lib.py:1161 JobLibCodeGen`` -- the reference
generates Python snippets executed over SSH (its newer path is skylet
gRPC, ``cloud_vm_ray_backend.py:2884``); here the shim is a real CLI
shipped with the runtime (backend/runtime_setup.py), invoked as::

    PYTHONPATH=~/.skyt_runtime/runtime python3 -m \\
        skypilot_tpu.runtime.job_cli --runtime-dir ~/.skyt_runtime <cmd>

Every command prints ONE JSON document on stdout (except ``tail``, which
streams raw log lines), so the backend-side client
(runtime/job_client.py RemoteJobTable) parses the last line.

``submit`` reads a base64'd JSON payload argument containing all rank
scripts and performs the full submission protocol atomically on-head:
job row at SETTING_UP -> write every rank script -> flip to PENDING (the
daemon polls every second and must never observe a partial script set).
"""
from __future__ import annotations

import argparse
import base64
import json
import os
import sys
import time

# stdlib-only imports at module level: this runs on cluster hosts where
# only the shipped runtime package is guaranteed importable.
from skypilot_tpu.runtime import job_lib
from skypilot_tpu.utils import env_registry


def _touch_last_use(runtime_dir: str) -> None:
    path = os.path.join(os.path.expanduser(runtime_dir), 'last_use')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        f.write(str(time.time()))


def cmd_submit(runtime_dir: str, payload_b64: str) -> dict:
    payload = json.loads(base64.b64decode(payload_b64).decode('utf-8'))
    job_id = job_lib.add_job(runtime_dir, payload.get('name'),
                             num_hosts=int(payload.get('num_hosts', 1)),
                             metadata=payload.get('metadata'),
                             status=job_lib.JobStatus.SETTING_UP)
    log_dir = job_lib.job_log_dir(runtime_dir, job_id)
    os.makedirs(log_dir, exist_ok=True)
    for rank, script in payload['scripts'].items():
        path = os.path.join(log_dir, f'rank_{int(rank)}.sh')
        with open(path, 'w', encoding='utf-8') as f:
            f.write(script)
    job_lib.set_status(runtime_dir, job_id, job_lib.JobStatus.PENDING)
    _touch_last_use(runtime_dir)
    return {'job_id': job_id}


def cmd_add(runtime_dir: str, name: str, num_hosts: int,
            status: str) -> dict:
    job_id = job_lib.add_job(runtime_dir, name or None,
                             num_hosts=num_hosts,
                             status=job_lib.JobStatus(status))
    _touch_last_use(runtime_dir)
    return {'job_id': job_id}


def cmd_set_status(runtime_dir: str, job_id: int, status: str,
                   exit_code) -> dict:
    job_lib.set_status(runtime_dir, job_id, job_lib.JobStatus(status),
                       exit_code=exit_code)
    return {'ok': True}


def cmd_list(runtime_dir: str) -> list:
    return job_lib.list_jobs(runtime_dir)


def cmd_get(runtime_dir: str, job_id: int) -> dict:
    job = job_lib.get_job(runtime_dir, job_id)
    return job if job is not None else {'error': 'not_found'}


def cmd_cancel(runtime_dir: str, job_id: int) -> dict:
    return {'cancelled': job_lib.cancel_job(runtime_dir, job_id)}


def cmd_set_autostop(runtime_dir: str, config_b64: str) -> dict:
    from skypilot_tpu.runtime import cluster_spec
    config = json.loads(base64.b64decode(config_b64).decode('utf-8'))
    cluster_spec.set_autostop(runtime_dir, config)
    _touch_last_use(runtime_dir)
    return {'ok': True}


def cmd_daemon_status(runtime_dir: str) -> dict:
    path = os.path.join(os.path.expanduser(runtime_dir),
                        'daemon_heartbeat')
    if not os.path.exists(path):
        return {'alive': False}
    try:
        with open(path, encoding='utf-8') as f:
            hb = json.load(f)
    except (OSError, ValueError):
        return {'alive': False}
    alive = time.time() - hb.get('ts', 0) < 30
    pid = hb.get('pid')
    if alive and pid is not None:
        # A heartbeat outlives its writer: a daemon killed seconds ago
        # (teardown + immediate re-provision of the same host) reads as
        # alive for up to 30s — long enough to skip the new daemon's
        # start and strand every submitted job in PENDING. Only ESRCH
        # means dead: EPERM (daemon under another uid) is proof of life.
        try:
            os.kill(int(pid), 0)
        except ProcessLookupError:
            alive = False
        except PermissionError:
            pass
        except (OSError, ValueError):
            pass  # inconclusive probe: trust the fresh heartbeat
    return {'alive': alive, **hb}


def follow_stop_condition(runtime_dir: str, job_id: int):
    """``stop_when`` for follow-tails, shared by every transport
    (job_cli tail, DirectJobTable, channel_server): stop on a terminal
    job, and stop on a DEAD daemon — a non-terminal job nobody
    supervises never finishes, so following it hangs the client
    forever. The grace covers a daemon still starting up."""
    grace = env_registry.get_float('SKYT_TAIL_DAEMON_GRACE')
    stream_started = time.time()

    def job_done() -> bool:
        job = job_lib.get_job(runtime_dir, job_id)
        if job is None or job_lib.JobStatus(job['status']).is_terminal():
            return True
        if time.time() - stream_started < grace:
            return False
        return not cmd_daemon_status(runtime_dir).get('alive', False)

    return job_done


def cmd_tail(runtime_dir: str, job_id: int, follow: bool) -> int:
    """Stream the rank-0 log to stdout; exits when the job is terminal."""
    from skypilot_tpu.runtime import log_lib
    job = job_lib.get_job(runtime_dir, job_id)
    if job is None:
        print(f'No job {job_id} on cluster', file=sys.stderr)
        return 3
    log_path = os.path.join(job_lib.job_log_dir(runtime_dir, job_id),
                            'rank_0.log')
    if not follow and not os.path.exists(log_path):
        print(f'No logs for job {job_id}', file=sys.stderr)
        return 3
    for line in log_lib.tail_file(
            log_path, follow=follow,
            stop_when=follow_stop_condition(runtime_dir, job_id)):
        sys.stdout.write(line)
        sys.stdout.flush()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog='job_cli')
    parser.add_argument('--runtime-dir',
                        default=job_lib.DEFAULT_RUNTIME_DIR)
    sub = parser.add_subparsers(dest='cmd', required=True)
    p = sub.add_parser('submit')
    p.add_argument('payload_b64')
    p = sub.add_parser('add')
    p.add_argument('--name', default='')
    p.add_argument('--num-hosts', type=int, default=1)
    p.add_argument('--status', default='PENDING')
    p = sub.add_parser('set-status')
    p.add_argument('job_id', type=int)
    p.add_argument('status')
    p.add_argument('--exit-code', type=int, default=None)
    sub.add_parser('list')
    p = sub.add_parser('get')
    p.add_argument('job_id', type=int)
    p = sub.add_parser('cancel')
    p.add_argument('job_id', type=int)
    p = sub.add_parser('set-autostop')
    p.add_argument('config_b64')
    sub.add_parser('daemon-status')
    p = sub.add_parser('tail')
    p.add_argument('job_id', type=int)
    p.add_argument('--follow', action='store_true')
    args = parser.parse_args(argv)

    rt = args.runtime_dir
    if args.cmd == 'submit':
        out = cmd_submit(rt, args.payload_b64)
    elif args.cmd == 'add':
        out = cmd_add(rt, args.name, args.num_hosts, args.status)
    elif args.cmd == 'set-status':
        out = cmd_set_status(rt, args.job_id, args.status, args.exit_code)
    elif args.cmd == 'list':
        out = cmd_list(rt)
    elif args.cmd == 'get':
        out = cmd_get(rt, args.job_id)
    elif args.cmd == 'cancel':
        out = cmd_cancel(rt, args.job_id)
    elif args.cmd == 'set-autostop':
        out = cmd_set_autostop(rt, args.config_b64)
    elif args.cmd == 'daemon-status':
        out = cmd_daemon_status(rt)
    elif args.cmd == 'tail':
        return cmd_tail(rt, args.job_id, args.follow)
    else:  # pragma: no cover - argparse enforces choices
        return 2
    print(json.dumps(out))
    return 0


if __name__ == '__main__':
    sys.exit(main())
