"""Log capture/tailing (parity: ``sky/skylet/log_lib.py``)."""
from __future__ import annotations

import os
import time
from typing import IO, Iterator, Optional

# SSH ranks echo this as their first line so the head-side daemon can
# observe remote liveness (daemon.RANK_STARTED_MARKER); it is framework
# plumbing, not job output, so reads drop it.
_RANK_STARTED_MARKER = '__SKYT_RANK_STARTED__'


def tail_file(path: str,
              *,
              follow: bool = False,
              from_start: bool = True,
              poll_interval: float = 0.2,
              stop_when: Optional[callable] = None) -> Iterator[str]:
    """Yield lines from a (possibly still-growing) log file.

    `stop_when()` is polled when no new data is available; return True to
    end following (e.g. when the job reached a terminal status).
    """
    path = os.path.expanduser(path)
    # Wait for the file to appear (a queued job may sit behind another
    # job for arbitrarily long): governed by stop_when, not a fixed
    # deadline. Without follow, don't wait at all.
    while not os.path.exists(path):
        if not follow:
            return
        if stop_when is not None and stop_when():
            if not os.path.exists(path):
                return
            break
        time.sleep(poll_interval)
    with open(path, encoding='utf-8', errors='replace') as f:
        if not from_start:
            f.seek(0, os.SEEK_END)
        while True:
            line = f.readline()
            if line:
                if line.strip() != _RANK_STARTED_MARKER:
                    yield line
                continue
            if not follow:
                return
            if stop_when is not None and stop_when():
                # drain anything written between the check and now
                rest = f.read()
                if _RANK_STARTED_MARKER in rest:
                    rest = '\n'.join(
                        ln for ln in rest.split('\n')
                        if ln.strip() != _RANK_STARTED_MARKER)
                if rest:
                    yield rest
                return
            time.sleep(poll_interval)


def stream_to(lines: Iterator[str], out: IO[str]) -> str:
    buf = []
    for line in lines:
        out.write(line)
        out.flush()
        buf.append(line)
    return ''.join(buf)
