"""Runtime daemon: the skylet equivalent, one per cluster head.

Parity: ``sky/skylet/skylet.py`` (EVENTS :31, main :126) +
``events.py:36-193``:

* **JobSchedulerEvent** -- starts PENDING jobs (gang-spawns one rank
  process per host with the submitted script), supervises RUNNING jobs
  (a TPU program *hangs* on lost peers, so any rank failure kills the
  whole gang), finalizes status with the worst exit code.
* **AutostopEvent** -- tracks idleness from the job table; stops or downs
  the cluster via its provider.
* **Heartbeat** -- liveness timestamp for status reconciliation.

The daemon is driven ONLY by ``<runtime_dir>/cluster.json``
(runtime/cluster_spec.py), so the same code runs (a) backend-side for
local-style clusters, where every "host" is a private root directory on
this machine, and (b) ON the head node of a real SSH cluster, where rank 0
runs locally and ranks 1+ are reached over SSH using the cluster-internal
key shipped at runtime-setup time (replacing the reference's Ray worker
agents; gang start/kill parity: RayCodeGen placement groups,
task_codegen.py:301).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import time
from typing import Dict, List, Optional

import psutil

from skypilot_tpu.runtime import cluster_spec as spec_lib
from skypilot_tpu.runtime import job_lib
from skypilot_tpu.utils import env_registry, log
from skypilot_tpu.utils.subprocess_utils import kill_process_tree

logger = log.init_logger(__name__)

# Daemon loop cadence. Injectable so tests (and latency-sensitive local
# deployments) can run the scheduler at 10-50 ms instead of 1 Hz.
EVENT_PERIOD_SECONDS = env_registry.get_float('SKYT_DAEMON_PERIOD')

# First line an SSH rank prints once its remote shell is up (stdout is the
# head-side rank log, so the head can observe remote liveness without an
# extra SSH round trip). log_lib strips it from user-facing reads.
RANK_STARTED_MARKER = '__SKYT_RANK_STARTED__'

# A rank that has not reached 'started' within this budget is a straggler
# (SSH spawn hang): the gang is killed and the job FAILs (SURVEY §7
# hard-parts bullet 3 — a TPU gang with a missing rank hangs forever).
DEFAULT_GANG_START_DEADLINE = 60.0

# Admission cap across ALL concurrently running jobs (TPU jobs are
# additionally exclusive among themselves; CPU-only jobs share freely).
DEFAULT_MAX_CONCURRENT_JOBS = 16


class RankProc:
    """One rank of a running gang."""

    def __init__(self, rank: int, proc: subprocess.Popen) -> None:
        self.rank = rank
        self.proc = proc

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def started(self) -> bool:
        """Local ranks are started the moment Popen returns a pid."""
        return True

    def kill(self, sig: int = signal.SIGTERM) -> None:
        if self.proc.poll() is None:
            kill_process_tree(self.proc.pid, sig)

    def wait(self, timeout: float) -> None:
        self.proc.wait(timeout=timeout)


class SshRankProc(RankProc):
    """A rank running on another host, driven over an SSH connection.

    The remote command records its own pid before exec'ing the script so a
    gang kill reaches the remote process tree even though killing the
    local ssh client alone would only drop the connection.
    """

    def __init__(self, rank: int, proc: subprocess.Popen,
                 ssh_base: List[str], pid_file: str,
                 log_path: Optional[str] = None) -> None:
        super().__init__(rank, proc)
        self._ssh_base = ssh_base
        self._pid_file = pid_file
        self._log_path = log_path
        self._started = False

    def started(self) -> bool:
        """True once the remote shell echoed the start marker into the
        head-side rank log (i.e. SSH connected AND the remote process
        exists). A hung SSH spawn never produces it."""
        if self._started:
            return True
        if self._log_path is None:
            return True
        try:
            with open(self._log_path, 'rb') as f:
                head = f.read(65536)
        except OSError:
            return False
        self._started = RANK_STARTED_MARKER.encode() in head
        return self._started

    def kill(self, sig: int = signal.SIGTERM) -> None:
        sig_name = 'KILL' if sig == signal.SIGKILL else 'TERM'
        remote = (f'pid=$(cat {self._pid_file} 2>/dev/null); '
                  f'if [ -n "$pid" ]; then '
                  f'kill -{sig_name} -- -$pid 2>/dev/null || '
                  f'kill -{sig_name} $pid 2>/dev/null; fi; true')
        try:
            subprocess.run(self._ssh_base + [remote], timeout=60,
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL, check=False)
        except subprocess.TimeoutExpired:
            logger.warning('Remote kill timed out for rank %d', self.rank)
        if self.proc.poll() is None:
            kill_process_tree(self.proc.pid, sig)


class JobSupervisor:
    """Gang lifecycle of one running job."""

    def __init__(self, job_id: int, procs: List[RankProc],
                 uses_tpu: bool = True,
                 start_deadline: Optional[float] = None) -> None:
        self.job_id = job_id
        self.procs = procs
        self.uses_tpu = uses_tpu
        self.failure_message: Optional[str] = None
        self._gang_started = False
        # Monotonic: an NTP step mid-spawn must not shrink (or stretch)
        # the gang-start budget.
        self._start_deadline = (time.monotonic() + start_deadline
                                if start_deadline else None)

    def poll(self) -> Optional[int]:
        """None while running; else worst exit code (gang-kill on first
        failure or on a gang-start straggler)."""
        if not self._gang_started:
            missing = [p.rank for p in self.procs if not p.started()]
            if not missing:
                self._gang_started = True
            elif (self._start_deadline is not None
                  and time.monotonic() > self._start_deadline):
                self.failure_message = (
                    f'rank(s) {missing} never started (no remote '
                    f'liveness within the gang-start deadline); '
                    f'gang killed')
                self.kill_all()
                return 1
        codes = [p.poll() for p in self.procs]
        failed = [c for c in codes if c is not None and c != 0]
        if failed:
            self.kill_all()
            return max(failed)
        if all(c is not None for c in codes):
            return 0
        return None

    def kill_all(self) -> None:
        # kill remaining ranks: TPU programs hang on lost peers
        for proc in self.procs:
            if proc.poll() is None:
                proc.kill(signal.SIGTERM)
        for proc in self.procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill(signal.SIGKILL)


class Daemon:
    def __init__(self, runtime_dir: str) -> None:
        self.runtime_dir = os.path.expanduser(runtime_dir)
        os.makedirs(self.runtime_dir, exist_ok=True)
        self.spec = spec_lib.read_spec(self.runtime_dir)
        if self.spec is None:
            raise RuntimeError(f'No cluster spec in {self.runtime_dir}')
        self.cluster_name = self.spec.cluster_name
        self.supervisors: Dict[int, JobSupervisor] = {}
        self.started_at = time.time()
        self.gang_start_deadline = env_registry.get_float(
            'SKYT_GANG_START_DEADLINE',
            default=DEFAULT_GANG_START_DEADLINE)
        self.max_concurrent_jobs = env_registry.get_int(
            'SKYT_MAX_CONCURRENT_JOBS',
            default=DEFAULT_MAX_CONCURRENT_JOBS)

    # ------------------------------------------------------------------
    # Rank launch
    # ------------------------------------------------------------------

    def _ssh_base(self, host: spec_lib.HostSpec) -> List[str]:
        from skypilot_tpu.utils.command_runner import SSH_OPTIONS
        cmd = ['ssh'] + SSH_OPTIONS + ['-p', str(host.ssh_port)]
        if self.spec.ssh_key:
            cmd += ['-i', os.path.expanduser(self.spec.ssh_key)]
        cmd.append(f'{self.spec.ssh_user}@{host.address}')
        return cmd

    def _start_rank(self, host: spec_lib.HostSpec, job_id: int,
                    script: str, log_dir: str) -> RankProc:
        rank = host.rank
        rank_log = open(os.path.join(log_dir, f'rank_{rank}.log'), 'a',
                        encoding='utf-8')
        try:
            if host.kind == 'local':
                root = os.path.expanduser(host.root or '~')
                env = {**os.environ, 'HOME': root}
                proc = subprocess.Popen(
                    ['bash', script], env=env, cwd=root,
                    stdout=rank_log, stderr=subprocess.STDOUT,
                    stdin=subprocess.DEVNULL, start_new_session=True)
                return RankProc(rank, proc)
            # SSH rank: stream the script over stdin (`bash -s`); the
            # remote shell records its pid first so gang-kill can reach
            # the remote process group.
            remote_job_dir = f'~/.skyt_runtime/jobs/{job_id}'
            pid_file = f'{remote_job_dir}/rank_{rank}.pid'
            remote = (f'mkdir -p {remote_job_dir} && '
                      f'echo $$ > {pid_file} && '
                      f'echo {RANK_STARTED_MARKER} && exec bash -s')
            ssh_base = self._ssh_base(host)
            script_file = open(script, encoding='utf-8')
            try:
                proc = subprocess.Popen(
                    ssh_base + [remote],
                    stdin=script_file,
                    stdout=rank_log, stderr=subprocess.STDOUT,
                    start_new_session=True)
            finally:
                script_file.close()
            return SshRankProc(rank, proc, ssh_base, pid_file,
                               log_path=rank_log.name)
        finally:
            rank_log.close()

    # ------------------------------------------------------------------
    # Job scheduling (parity: JobSchedulerEvent -> job_lib.JobScheduler)
    # ------------------------------------------------------------------

    def _schedule_jobs(self) -> None:
        """Concurrent admission (parity: JobScheduler, job_lib.py:278 —
        jobs run whenever resources allow, not one at a time):

        * TPU jobs are EXCLUSIVE among themselves — one resident TPU
          program per slice; a second would deadlock on the devices.
        * CPU-only jobs (``metadata['uses_tpu'] == False``) share the
          cluster with anything, up to ``max_concurrent_jobs`` total.
        * FIFO within each class: a blocked TPU job does not let a
          younger TPU job jump it, but CPU jobs behind it still run.
        """
        for job_id in list(self.supervisors):
            self._poll_running(job_id)
        pending = job_lib.list_jobs(self.runtime_dir,
                                    [job_lib.JobStatus.PENDING])
        if not pending:
            return
        pending.reverse()  # list is job_id DESC; admit oldest first
        # RUNNING rows without a supervisor here (pre-restart jobs whose
        # ranks this daemon no longer owns) count toward the cap and TPU
        # exclusivity ONLY while their recorded pids are alive — a stale
        # row from a daemon crash would otherwise block TPU admission
        # forever, with nobody left to write its terminal status.
        running = job_lib.list_jobs(self.runtime_dir,
                                    [job_lib.JobStatus.RUNNING])
        foreign = []
        for job in running:
            if job['job_id'] in self.supervisors:
                continue
            if self._foreign_job_dead(job):
                logger.warning(
                    'Job %d: RUNNING row with no live rank process '
                    '(daemon restarted mid-job?); marking FAILED',
                    job['job_id'])
                job_lib.set_status(self.runtime_dir, job['job_id'],
                                   job_lib.JobStatus.FAILED, exit_code=1)
                continue
            foreign.append(job)
        active = len(self.supervisors) + len(foreign)
        tpu_blocked = (
            any(s.uses_tpu for s in self.supervisors.values())
            or any(j['metadata'].get('uses_tpu', True) for j in foreign))
        for job in pending:
            if active >= self.max_concurrent_jobs:
                break
            uses_tpu = job['metadata'].get('uses_tpu', True)
            if uses_tpu and tpu_blocked:
                continue  # younger TPU jobs stay queued too (class FIFO)
            self._start_job(job['job_id'], uses_tpu=uses_tpu)
            active += 1
            tpu_blocked = tpu_blocked or uses_tpu

    @staticmethod
    def _foreign_job_dead(job: dict) -> bool:
        """True when an unsupervised RUNNING row's ranks are all gone.

        Orphan ranks (start_new_session) legitimately outlive a daemon
        restart and still hold the TPU — those keep blocking admission.
        A row with no pids yet is given a grace window: the submitter
        writes pids right after flipping to RUNNING.
        """
        pids = job.get('pids') or []
        if not pids:
            started = job.get('started_at') or job.get('submitted_at')
            return bool(started and time.time() - started > 60.0)
        return not any(psutil.pid_exists(pid) for pid in pids)

    def _start_job(self, job_id: int, uses_tpu: bool = True) -> None:
        log_dir = job_lib.job_log_dir(self.runtime_dir, job_id)
        hosts = self.spec.hosts
        scripts = {
            h.rank: os.path.join(log_dir, f'rank_{h.rank}.sh')
            for h in hosts
            if os.path.exists(os.path.join(log_dir, f'rank_{h.rank}.sh'))
        }
        if not scripts:
            logger.warning('Job %d has no rank scripts; failing', job_id)
            job_lib.set_status(self.runtime_dir, job_id,
                               job_lib.JobStatus.FAILED, exit_code=1)
            return
        procs: List[RankProc] = []
        for host in hosts:
            # a callable run may legitimately skip ranks (None command)
            if host.rank not in scripts:
                continue
            procs.append(self._start_rank(host, job_id, scripts[host.rank],
                                          log_dir))
        job_lib.set_status(self.runtime_dir, job_id,
                           job_lib.JobStatus.RUNNING)
        job_lib.set_pids(self.runtime_dir, job_id,
                         [p.proc.pid for p in procs])
        self.supervisors[job_id] = JobSupervisor(
            job_id, procs, uses_tpu=uses_tpu,
            start_deadline=self.gang_start_deadline)
        logger.info('Job %d started (%d ranks%s)', job_id, len(procs),
                    '' if uses_tpu else ', cpu-only')

    def _poll_running(self, job_id: int) -> None:
        supervisor = self.supervisors[job_id]
        job = job_lib.get_job(self.runtime_dir, job_id)
        if job is None or job['status'] == 'CANCELLED':
            supervisor.kill_all()
            del self.supervisors[job_id]
            return
        code = supervisor.poll()
        if code is None:
            return
        final = (job_lib.JobStatus.SUCCEEDED if code == 0
                 else job_lib.JobStatus.FAILED)
        if supervisor.failure_message:
            # Straggler diagnosis goes into each unstarted rank's log so
            # `skyt logs` shows WHY the gang died (per-rank message).
            log_dir = job_lib.job_log_dir(self.runtime_dir, job_id)
            for proc in supervisor.procs:
                if not proc.started():
                    rank_log = os.path.join(log_dir,
                                            f'rank_{proc.rank}.log')
                    try:
                        with open(rank_log, 'a', encoding='utf-8') as f:
                            f.write(f'[skyt] rank {proc.rank}: never '
                                    f'started within '
                                    f'{self.gang_start_deadline:.0f}s '
                                    f'(SSH spawn hang?); gang killed\n')
                    except OSError:
                        pass
            logger.error('Job %d: %s', job_id, supervisor.failure_message)
        job_lib.set_status(self.runtime_dir, job_id, final,
                           exit_code=code)
        logger.info('Job %d finished: %s (%d)', job_id, final.value, code)
        del self.supervisors[job_id]

    # ------------------------------------------------------------------
    # Autostop (parity: StopEvent -> autostop_lib, skylet/events.py)
    # ------------------------------------------------------------------

    def _check_autostop(self) -> bool:
        """Returns True if the cluster was stopped/downed (daemon exits)."""
        spec = spec_lib.read_spec(self.runtime_dir)
        if spec is None:
            return True  # spec gone: cluster being torn down
        self.spec = spec  # autostop config / host set may have changed
        config = spec.autostop or {}
        if not config:
            return False
        if self.supervisors:
            return False  # active jobs: never idle
        idle_minutes = config.get('idle_minutes', 5)
        last_job = job_lib.last_activity_time(self.runtime_dir)
        last = max(last_job, self.started_at, self._last_use_time())
        if time.time() - last < idle_minutes * 60:
            return False
        down = bool(config.get('down'))
        logger.info('Cluster %s idle for > %s min: %s', self.cluster_name,
                    idle_minutes, 'down' if down else 'stop')
        return self._teardown_cluster(down)

    def _last_use_time(self) -> float:
        """mtime of the `last_use` touch file (bumped by job_cli ops)."""
        path = os.path.join(self.runtime_dir, 'last_use')
        try:
            return os.path.getmtime(path)
        except OSError:
            return 0.0

    def _teardown_cluster(self, down: bool) -> bool:
        """Stop/terminate via the provider; sync the client state DB when
        it is reachable (backend-side daemons). On a real head node the
        state DB is absent -- the server's background reconciler flips the
        record on the next refresh (parity: skylet autostop calls the
        cloud API with the instance's own credentials)."""
        from skypilot_tpu.provision.api import get_provider
        try:
            provider = get_provider(self.spec.cloud or 'fake')
            if down:
                provider.terminate_instances(self.cluster_name)
            else:
                provider.stop_instances(self.cluster_name)
        except Exception as e:  # pylint: disable=broad-except
            logger.error('Autostop provider call failed: %s', e)
            return False
        try:
            from skypilot_tpu import state
            record = state.get_cluster(self.cluster_name)
            if record is not None:
                if down:
                    state.remove_cluster(self.cluster_name)
                    state.add_cluster_event(self.cluster_name,
                                            'TERMINATED', 'autostop: idle')
                else:
                    state.set_cluster_status(self.cluster_name,
                                             state.ClusterStatus.STOPPED)
                    state.add_cluster_event(self.cluster_name, 'STOPPED',
                                            'autostop: idle')
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('State DB sync after autostop failed: %s', e)
        return True

    # ------------------------------------------------------------------

    def _heartbeat(self) -> None:
        path = os.path.join(self.runtime_dir, 'daemon_heartbeat')
        with open(path, 'w', encoding='utf-8') as f:
            json.dump({'ts': time.time(), 'pid': os.getpid()}, f)

    def run_forever(self) -> None:
        logger.info('Daemon for %s up (%d hosts, runtime %s)',
                    self.cluster_name, len(self.spec.hosts),
                    self.runtime_dir)
        with open(os.path.join(self.runtime_dir, 'daemon.pid'), 'w',
                  encoding='utf-8') as f:
            f.write(str(os.getpid()))
        while True:
            # Self-reap check FIRST: if the runtime dir is gone,
            # _schedule_jobs/_heartbeat raise and would skip a check
            # placed after them in the try block — spinning forever.
            if self._superseded():
                logger.info('Runtime dir gone or daemon superseded; '
                            'exiting')
                return
            try:
                self._schedule_jobs()
                self._heartbeat()
                if self._check_autostop():
                    logger.info('Cluster gone/stopped; daemon exiting')
                    return
            except Exception as e:  # pylint: disable=broad-except
                logger.error('Daemon event error: %s', e, exc_info=True)
            time.sleep(EVENT_PERIOD_SECONDS)

    def _superseded(self) -> bool:
        """Self-reap: the runtime dir vanished (torn-down cluster, wiped
        test tmpdir) or another daemon re-claimed it (daemon.pid no
        longer ours). Without this, orphaned daemons spin at 1 Hz
        forever (r2-verdict weakness #8)."""
        pid_path = os.path.join(self.runtime_dir, 'daemon.pid')
        if not os.path.isdir(self.runtime_dir):
            return True
        try:
            with open(pid_path, encoding='utf-8') as f:
                return int(f.read().strip()) != os.getpid()
        except (OSError, ValueError):
            return True  # pid file gone/corrupt: dir being torn down


# ---------------------------------------------------------------------------
# Daemon process management (backend-side helpers, local-style clusters)
# ---------------------------------------------------------------------------

def _pid_file(cluster_name: str) -> str:
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, 'daemons', f'{cluster_name}.pid')


def daemon_alive(cluster_name: str) -> bool:
    path = _pid_file(cluster_name)
    if not os.path.exists(path):
        return False
    try:
        with open(path, encoding='utf-8') as f:
            pid = int(f.read().strip())
        proc = psutil.Process(pid)
        return 'skypilot_tpu.runtime.daemon' in ' '.join(proc.cmdline())
    except (ValueError, psutil.NoSuchProcess, psutil.AccessDenied):
        return False


def start_daemon(cluster_name: str, runtime_dir: str) -> int:
    """Spawn the daemon detached on THIS machine (local-style clusters;
    parity: start_skylet_on_head_node, provision/instance_setup.py:598.
    SSH clusters start theirs over SSH in runtime_setup)."""
    if daemon_alive(cluster_name):
        with open(_pid_file(cluster_name), encoding='utf-8') as f:
            return int(f.read().strip())
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    log_path = os.path.join(state_dir, 'daemons', f'{cluster_name}.log')
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    with open(log_path, 'a', encoding='utf-8') as log_file:
        import sys
        proc = subprocess.Popen(
            [sys.executable, '-u', '-m', 'skypilot_tpu.runtime.daemon',
             '--runtime-dir', runtime_dir],
            stdout=log_file, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL, start_new_session=True)
    with open(_pid_file(cluster_name), 'w', encoding='utf-8') as f:
        f.write(str(proc.pid))
    return proc.pid


def stop_daemon(cluster_name: str) -> None:
    path = _pid_file(cluster_name)
    if not os.path.exists(path):
        return
    try:
        with open(path, encoding='utf-8') as f:
            pid = int(f.read().strip())
        # Autostop runs teardown *from inside the daemon*: killing the
        # recorded pid would SIGTERM ourselves mid-teardown. The daemon
        # exits on its own after _check_autostop returns True.
        if pid != os.getpid():
            kill_process_tree(pid)
    except (ValueError, OSError):
        pass
    try:
        os.remove(path)
    except OSError:
        pass


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--runtime-dir', required=True)
    args = parser.parse_args()
    Daemon(args.runtime_dir).run_forever()


if __name__ == '__main__':
    main()
