"""Runtime daemon: the skylet equivalent, one per cluster head.

Parity: ``sky/skylet/skylet.py`` (EVENTS :31, main :126) +
``events.py:36-193``:

* **JobSchedulerEvent** -- starts PENDING jobs (gang-spawns one rank
  process per host with the submitted script), supervises RUNNING jobs
  (a TPU program *hangs* on lost peers, so any rank failure kills the
  whole gang), finalizes status with the worst exit code.
* **AutostopEvent** -- tracks idleness from the job table; stops or downs
  the cluster via its provider.
* **Heartbeat** -- liveness timestamp for status reconciliation.

The daemon is driven ONLY by ``<runtime_dir>/cluster.json``
(runtime/cluster_spec.py), so the same code runs (a) backend-side for
local-style clusters, where every "host" is a private root directory on
this machine, and (b) ON the head node of a real SSH cluster, where rank 0
runs locally and ranks 1+ are reached over SSH using the cluster-internal
key shipped at runtime-setup time (replacing the reference's Ray worker
agents; gang start/kill parity: RayCodeGen placement groups,
task_codegen.py:301).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import time
from typing import List, Optional

import psutil

from skypilot_tpu.runtime import cluster_spec as spec_lib
from skypilot_tpu.runtime import job_lib
from skypilot_tpu.utils import log
from skypilot_tpu.utils.subprocess_utils import kill_process_tree

logger = log.init_logger(__name__)

EVENT_PERIOD_SECONDS = 1.0


class RankProc:
    """One rank of a running gang."""

    def __init__(self, rank: int, proc: subprocess.Popen) -> None:
        self.rank = rank
        self.proc = proc

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def kill(self, sig: int = signal.SIGTERM) -> None:
        if self.proc.poll() is None:
            kill_process_tree(self.proc.pid, sig)

    def wait(self, timeout: float) -> None:
        self.proc.wait(timeout=timeout)


class SshRankProc(RankProc):
    """A rank running on another host, driven over an SSH connection.

    The remote command records its own pid before exec'ing the script so a
    gang kill reaches the remote process tree even though killing the
    local ssh client alone would only drop the connection.
    """

    def __init__(self, rank: int, proc: subprocess.Popen,
                 ssh_base: List[str], pid_file: str) -> None:
        super().__init__(rank, proc)
        self._ssh_base = ssh_base
        self._pid_file = pid_file

    def kill(self, sig: int = signal.SIGTERM) -> None:
        sig_name = 'KILL' if sig == signal.SIGKILL else 'TERM'
        remote = (f'pid=$(cat {self._pid_file} 2>/dev/null); '
                  f'if [ -n "$pid" ]; then '
                  f'kill -{sig_name} -- -$pid 2>/dev/null || '
                  f'kill -{sig_name} $pid 2>/dev/null; fi; true')
        try:
            subprocess.run(self._ssh_base + [remote], timeout=60,
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL, check=False)
        except subprocess.TimeoutExpired:
            logger.warning('Remote kill timed out for rank %d', self.rank)
        if self.proc.poll() is None:
            kill_process_tree(self.proc.pid, sig)


class JobSupervisor:
    """Gang lifecycle of one running job."""

    def __init__(self, job_id: int, procs: List[RankProc]) -> None:
        self.job_id = job_id
        self.procs = procs

    def poll(self) -> Optional[int]:
        """None while running; else worst exit code (gang-kill on first
        failure)."""
        codes = [p.poll() for p in self.procs]
        failed = [c for c in codes if c is not None and c != 0]
        if failed:
            self.kill_all()
            return max(failed)
        if all(c is not None for c in codes):
            return 0
        return None

    def kill_all(self) -> None:
        # kill remaining ranks: TPU programs hang on lost peers
        for proc in self.procs:
            if proc.poll() is None:
                proc.kill(signal.SIGTERM)
        for proc in self.procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill(signal.SIGKILL)


class Daemon:
    def __init__(self, runtime_dir: str) -> None:
        self.runtime_dir = os.path.expanduser(runtime_dir)
        os.makedirs(self.runtime_dir, exist_ok=True)
        self.spec = spec_lib.read_spec(self.runtime_dir)
        if self.spec is None:
            raise RuntimeError(f'No cluster spec in {self.runtime_dir}')
        self.cluster_name = self.spec.cluster_name
        self.supervisor: Optional[JobSupervisor] = None
        self.started_at = time.time()

    # ------------------------------------------------------------------
    # Rank launch
    # ------------------------------------------------------------------

    def _ssh_base(self, host: spec_lib.HostSpec) -> List[str]:
        from skypilot_tpu.utils.command_runner import SSH_OPTIONS
        cmd = ['ssh'] + SSH_OPTIONS + ['-p', str(host.ssh_port)]
        if self.spec.ssh_key:
            cmd += ['-i', os.path.expanduser(self.spec.ssh_key)]
        cmd.append(f'{self.spec.ssh_user}@{host.address}')
        return cmd

    def _start_rank(self, host: spec_lib.HostSpec, job_id: int,
                    script: str, log_dir: str) -> RankProc:
        rank = host.rank
        rank_log = open(os.path.join(log_dir, f'rank_{rank}.log'), 'a',
                        encoding='utf-8')
        try:
            if host.kind == 'local':
                root = os.path.expanduser(host.root or '~')
                env = {**os.environ, 'HOME': root}
                proc = subprocess.Popen(
                    ['bash', script], env=env, cwd=root,
                    stdout=rank_log, stderr=subprocess.STDOUT,
                    stdin=subprocess.DEVNULL, start_new_session=True)
                return RankProc(rank, proc)
            # SSH rank: stream the script over stdin (`bash -s`); the
            # remote shell records its pid first so gang-kill can reach
            # the remote process group.
            remote_job_dir = f'~/.skyt_runtime/jobs/{job_id}'
            pid_file = f'{remote_job_dir}/rank_{rank}.pid'
            remote = (f'mkdir -p {remote_job_dir} && '
                      f'echo $$ > {pid_file} && exec bash -s')
            ssh_base = self._ssh_base(host)
            script_file = open(script, encoding='utf-8')
            try:
                proc = subprocess.Popen(
                    ssh_base + [remote],
                    stdin=script_file,
                    stdout=rank_log, stderr=subprocess.STDOUT,
                    start_new_session=True)
            finally:
                script_file.close()
            return SshRankProc(rank, proc, ssh_base, pid_file)
        finally:
            rank_log.close()

    # ------------------------------------------------------------------
    # Job scheduling (parity: JobSchedulerEvent -> job_lib.JobScheduler)
    # ------------------------------------------------------------------

    def _schedule_jobs(self) -> None:
        if self.supervisor is not None:
            self._poll_running()
            return
        pending = job_lib.list_jobs(self.runtime_dir,
                                    [job_lib.JobStatus.PENDING])
        if not pending:
            return
        job = pending[-1]  # oldest first (list is DESC)
        self._start_job(job['job_id'])

    def _start_job(self, job_id: int) -> None:
        log_dir = job_lib.job_log_dir(self.runtime_dir, job_id)
        hosts = self.spec.hosts
        scripts = {
            h.rank: os.path.join(log_dir, f'rank_{h.rank}.sh')
            for h in hosts
            if os.path.exists(os.path.join(log_dir, f'rank_{h.rank}.sh'))
        }
        if not scripts:
            logger.warning('Job %d has no rank scripts; failing', job_id)
            job_lib.set_status(self.runtime_dir, job_id,
                               job_lib.JobStatus.FAILED, exit_code=1)
            return
        procs: List[RankProc] = []
        for host in hosts:
            # a callable run may legitimately skip ranks (None command)
            if host.rank not in scripts:
                continue
            procs.append(self._start_rank(host, job_id, scripts[host.rank],
                                          log_dir))
        job_lib.set_status(self.runtime_dir, job_id,
                           job_lib.JobStatus.RUNNING)
        job_lib.set_pids(self.runtime_dir, job_id,
                         [p.proc.pid for p in procs])
        self.supervisor = JobSupervisor(job_id, procs)
        logger.info('Job %d started (%d ranks)', job_id, len(procs))

    def _poll_running(self) -> None:
        assert self.supervisor is not None
        job = job_lib.get_job(self.runtime_dir, self.supervisor.job_id)
        if job is None or job['status'] == 'CANCELLED':
            self.supervisor.kill_all()
            self.supervisor = None
            return
        code = self.supervisor.poll()
        if code is None:
            return
        final = (job_lib.JobStatus.SUCCEEDED if code == 0
                 else job_lib.JobStatus.FAILED)
        job_lib.set_status(self.runtime_dir, self.supervisor.job_id, final,
                           exit_code=code)
        logger.info('Job %d finished: %s (%d)', self.supervisor.job_id,
                    final.value, code)
        self.supervisor = None

    # ------------------------------------------------------------------
    # Autostop (parity: StopEvent -> autostop_lib, skylet/events.py)
    # ------------------------------------------------------------------

    def _check_autostop(self) -> bool:
        """Returns True if the cluster was stopped/downed (daemon exits)."""
        spec = spec_lib.read_spec(self.runtime_dir)
        if spec is None:
            return True  # spec gone: cluster being torn down
        self.spec = spec  # autostop config / host set may have changed
        config = spec.autostop or {}
        if not config:
            return False
        if self.supervisor is not None:
            return False  # active job: never idle
        idle_minutes = config.get('idle_minutes', 5)
        last_job = job_lib.last_activity_time(self.runtime_dir)
        last = max(last_job, self.started_at, self._last_use_time())
        if time.time() - last < idle_minutes * 60:
            return False
        down = bool(config.get('down'))
        logger.info('Cluster %s idle for > %s min: %s', self.cluster_name,
                    idle_minutes, 'down' if down else 'stop')
        return self._teardown_cluster(down)

    def _last_use_time(self) -> float:
        """mtime of the `last_use` touch file (bumped by job_cli ops)."""
        path = os.path.join(self.runtime_dir, 'last_use')
        try:
            return os.path.getmtime(path)
        except OSError:
            return 0.0

    def _teardown_cluster(self, down: bool) -> bool:
        """Stop/terminate via the provider; sync the client state DB when
        it is reachable (backend-side daemons). On a real head node the
        state DB is absent -- the server's background reconciler flips the
        record on the next refresh (parity: skylet autostop calls the
        cloud API with the instance's own credentials)."""
        from skypilot_tpu.provision.api import get_provider
        try:
            provider = get_provider(self.spec.cloud or 'fake')
            if down:
                provider.terminate_instances(self.cluster_name)
            else:
                provider.stop_instances(self.cluster_name)
        except Exception as e:  # pylint: disable=broad-except
            logger.error('Autostop provider call failed: %s', e)
            return False
        try:
            from skypilot_tpu import state
            record = state.get_cluster(self.cluster_name)
            if record is not None:
                if down:
                    state.remove_cluster(self.cluster_name)
                    state.add_cluster_event(self.cluster_name,
                                            'TERMINATED', 'autostop: idle')
                else:
                    state.set_cluster_status(self.cluster_name,
                                             state.ClusterStatus.STOPPED)
                    state.add_cluster_event(self.cluster_name, 'STOPPED',
                                            'autostop: idle')
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('State DB sync after autostop failed: %s', e)
        return True

    # ------------------------------------------------------------------

    def _heartbeat(self) -> None:
        path = os.path.join(self.runtime_dir, 'daemon_heartbeat')
        with open(path, 'w', encoding='utf-8') as f:
            json.dump({'ts': time.time(), 'pid': os.getpid()}, f)

    def run_forever(self) -> None:
        logger.info('Daemon for %s up (%d hosts, runtime %s)',
                    self.cluster_name, len(self.spec.hosts),
                    self.runtime_dir)
        with open(os.path.join(self.runtime_dir, 'daemon.pid'), 'w',
                  encoding='utf-8') as f:
            f.write(str(os.getpid()))
        while True:
            # Self-reap check FIRST: if the runtime dir is gone,
            # _schedule_jobs/_heartbeat raise and would skip a check
            # placed after them in the try block — spinning forever.
            if self._superseded():
                logger.info('Runtime dir gone or daemon superseded; '
                            'exiting')
                return
            try:
                self._schedule_jobs()
                self._heartbeat()
                if self._check_autostop():
                    logger.info('Cluster gone/stopped; daemon exiting')
                    return
            except Exception as e:  # pylint: disable=broad-except
                logger.error('Daemon event error: %s', e, exc_info=True)
            time.sleep(EVENT_PERIOD_SECONDS)

    def _superseded(self) -> bool:
        """Self-reap: the runtime dir vanished (torn-down cluster, wiped
        test tmpdir) or another daemon re-claimed it (daemon.pid no
        longer ours). Without this, orphaned daemons spin at 1 Hz
        forever (r2-verdict weakness #8)."""
        pid_path = os.path.join(self.runtime_dir, 'daemon.pid')
        if not os.path.isdir(self.runtime_dir):
            return True
        try:
            with open(pid_path, encoding='utf-8') as f:
                return int(f.read().strip()) != os.getpid()
        except (OSError, ValueError):
            return True  # pid file gone/corrupt: dir being torn down


# ---------------------------------------------------------------------------
# Daemon process management (backend-side helpers, local-style clusters)
# ---------------------------------------------------------------------------

def _pid_file(cluster_name: str) -> str:
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, 'daemons', f'{cluster_name}.pid')


def daemon_alive(cluster_name: str) -> bool:
    path = _pid_file(cluster_name)
    if not os.path.exists(path):
        return False
    try:
        with open(path, encoding='utf-8') as f:
            pid = int(f.read().strip())
        proc = psutil.Process(pid)
        return 'skypilot_tpu.runtime.daemon' in ' '.join(proc.cmdline())
    except (ValueError, psutil.NoSuchProcess, psutil.AccessDenied):
        return False


def start_daemon(cluster_name: str, runtime_dir: str) -> int:
    """Spawn the daemon detached on THIS machine (local-style clusters;
    parity: start_skylet_on_head_node, provision/instance_setup.py:598.
    SSH clusters start theirs over SSH in runtime_setup)."""
    if daemon_alive(cluster_name):
        with open(_pid_file(cluster_name), encoding='utf-8') as f:
            return int(f.read().strip())
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    log_path = os.path.join(state_dir, 'daemons', f'{cluster_name}.log')
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    with open(log_path, 'a', encoding='utf-8') as log_file:
        import sys
        proc = subprocess.Popen(
            [sys.executable, '-u', '-m', 'skypilot_tpu.runtime.daemon',
             '--runtime-dir', runtime_dir],
            stdout=log_file, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL, start_new_session=True)
    with open(_pid_file(cluster_name), 'w', encoding='utf-8') as f:
        f.write(str(proc.pid))
    return proc.pid


def stop_daemon(cluster_name: str) -> None:
    path = _pid_file(cluster_name)
    if not os.path.exists(path):
        return
    try:
        with open(path, encoding='utf-8') as f:
            pid = int(f.read().strip())
        # Autostop runs teardown *from inside the daemon*: killing the
        # recorded pid would SIGTERM ourselves mid-teardown. The daemon
        # exits on its own after _check_autostop returns True.
        if pid != os.getpid():
            kill_process_tree(pid)
    except (ValueError, OSError):
        pass
    try:
        os.remove(path)
    except OSError:
        pass


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--runtime-dir', required=True)
    args = parser.parse_args()
    Daemon(args.runtime_dir).run_forever()


if __name__ == '__main__':
    main()
