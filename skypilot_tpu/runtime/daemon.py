"""Runtime daemon: the skylet equivalent, one per cluster head.

Parity: ``sky/skylet/skylet.py`` (EVENTS :31, main :126) +
``events.py:36-193``:

* **JobSchedulerEvent** -- starts PENDING jobs (gang-spawns one rank
  process per host with the submitted script), supervises RUNNING jobs
  (a TPU program *hangs* on lost peers, so any rank failure kills the
  whole gang), finalizes status with the worst exit code.
* **AutostopEvent** -- tracks idleness from the job table + cluster
  last_use; stops or downs the cluster via its provider.
* **Heartbeat** -- liveness timestamp for status reconciliation.

For local-style clusters (fake/local providers) every "host" is a private
root directory on this machine, so the daemon gang-starts ranks directly;
on real SSH clusters the daemon runs on the head node and reaches workers
over SSH (wired with host keys at provision time).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import time
from typing import Dict, List, Optional

import psutil

from skypilot_tpu.runtime import job_lib
from skypilot_tpu.utils import log
from skypilot_tpu.utils.subprocess_utils import kill_process_tree

logger = log.init_logger(__name__)

EVENT_PERIOD_SECONDS = 1.0


class JobSupervisor:
    """Gang lifecycle of one running job."""

    def __init__(self, job_id: int, procs: List[subprocess.Popen]) -> None:
        self.job_id = job_id
        self.procs = procs

    def poll(self) -> Optional[int]:
        """None while running; else worst exit code (gang-kill on first
        failure)."""
        codes = [p.poll() for p in self.procs]
        failed = [c for c in codes if c is not None and c != 0]
        if failed:
            # kill remaining ranks: TPU programs hang on lost peers
            for proc in self.procs:
                if proc.poll() is None:
                    kill_process_tree(proc.pid, signal.SIGTERM)
            for proc in self.procs:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    kill_process_tree(proc.pid, signal.SIGKILL)
            return max(failed)
        if all(c is not None for c in codes):
            return 0
        return None


class Daemon:
    def __init__(self, cluster_name: str) -> None:
        self.cluster_name = cluster_name
        self.supervisor: Optional[JobSupervisor] = None
        self._host_roots = self._resolve_host_roots()
        self.head_runtime = os.path.join(self._host_roots[0],
                                         '.skyt_runtime')
        os.makedirs(self.head_runtime, exist_ok=True)

    # ------------------------------------------------------------------

    def _resolve_host_roots(self) -> List[str]:
        """Host root dirs ordered by (node, worker), from cluster state."""
        from skypilot_tpu import state
        from skypilot_tpu.provision.api import ClusterInfo
        from skypilot_tpu.utils.command_runner import runners_for_cluster
        record = state.get_cluster(self.cluster_name)
        if record is None or not record.handle:
            raise RuntimeError(f'No cluster record for {self.cluster_name}')
        info = ClusterInfo.from_dict(record.handle)
        runners = runners_for_cluster(info)
        roots = []
        for runner in runners:
            if hasattr(runner, 'host_root'):
                roots.append(runner.host_root)
            else:
                roots.append(os.path.expanduser('~'))
        return roots

    # ------------------------------------------------------------------
    # Job scheduling (parity: JobSchedulerEvent -> job_lib.JobScheduler)
    # ------------------------------------------------------------------

    def _schedule_jobs(self) -> None:
        if self.supervisor is not None:
            self._poll_running()
            return
        pending = job_lib.list_jobs(self.head_runtime,
                                    [job_lib.JobStatus.PENDING])
        if not pending:
            return
        job = pending[-1]  # oldest first (list is DESC)
        self._start_job(job['job_id'])

    def _start_job(self, job_id: int) -> None:
        log_dir = job_lib.job_log_dir(self.head_runtime, job_id)
        if not any(
                os.path.exists(os.path.join(log_dir, f'rank_{r}.sh'))
                for r in range(len(self._host_roots))):
            logger.warning('Job %d has no rank scripts; failing', job_id)
            job_lib.set_status(self.head_runtime, job_id,
                               job_lib.JobStatus.FAILED, exit_code=1)
            return
        procs: List[subprocess.Popen] = []
        for rank, root in enumerate(self._host_roots):
            script = os.path.join(log_dir, f'rank_{rank}.sh')
            if not os.path.exists(script):
                # a callable run may legitimately skip ranks (None command)
                continue
            rank_log = open(os.path.join(log_dir, f'rank_{rank}.log'), 'a',
                            encoding='utf-8')
            env = {**os.environ, 'HOME': root}
            procs.append(subprocess.Popen(
                ['bash', script], env=env, cwd=root,
                stdout=rank_log, stderr=subprocess.STDOUT,
                stdin=subprocess.DEVNULL, start_new_session=True))
            rank_log.close()
        job_lib.set_status(self.head_runtime, job_id,
                           job_lib.JobStatus.RUNNING)
        job_lib.set_pids(self.head_runtime, job_id,
                         [p.pid for p in procs])
        self.supervisor = JobSupervisor(job_id, procs)
        logger.info('Job %d started (%d ranks)', job_id, len(procs))

    def _poll_running(self) -> None:
        assert self.supervisor is not None
        job = job_lib.get_job(self.head_runtime, self.supervisor.job_id)
        if job is None or job['status'] == 'CANCELLED':
            for proc in self.supervisor.procs:
                kill_process_tree(proc.pid)
            self.supervisor = None
            return
        code = self.supervisor.poll()
        if code is None:
            return
        final = (job_lib.JobStatus.SUCCEEDED if code == 0
                 else job_lib.JobStatus.FAILED)
        job_lib.set_status(self.head_runtime, self.supervisor.job_id, final,
                           exit_code=code)
        logger.info('Job %d finished: %s (%d)', self.supervisor.job_id,
                    final.value, code)
        self.supervisor = None

    # ------------------------------------------------------------------
    # Autostop (parity: StopEvent -> autostop_lib, skylet/events.py)
    # ------------------------------------------------------------------

    def _check_autostop(self) -> bool:
        """Returns True if the cluster was stopped/downed (daemon exits)."""
        from skypilot_tpu import state
        record = state.get_cluster(self.cluster_name)
        if record is None:
            return True  # cluster gone
        config = record.autostop or {}
        if not config:
            return False
        idle_minutes = config.get('idle_minutes', 5)
        last_job = job_lib.last_activity_time(self.head_runtime)
        last = max(last_job, record.last_use or 0, record.launched_at or 0)
        if time.time() - last < idle_minutes * 60:
            return False
        logger.info('Cluster %s idle for > %d min: %s', self.cluster_name,
                    idle_minutes, 'down' if config.get('down') else 'stop')
        from skypilot_tpu.backend.tpu_backend import TpuPodBackend
        try:
            TpuPodBackend().teardown(self.cluster_name,
                                     terminate=bool(config.get('down')))
        except Exception as e:  # pylint: disable=broad-except
            logger.error('Autostop failed: %s', e)
            return False
        return True

    # ------------------------------------------------------------------

    def _heartbeat(self) -> None:
        path = os.path.join(self.head_runtime, 'daemon_heartbeat')
        with open(path, 'w', encoding='utf-8') as f:
            json.dump({'ts': time.time(), 'pid': os.getpid()}, f)

    def run_forever(self) -> None:
        logger.info('Daemon for %s up (roots: %d hosts)', self.cluster_name,
                    len(self._host_roots))
        while True:
            try:
                self._schedule_jobs()
                self._heartbeat()
                if self._check_autostop():
                    logger.info('Cluster gone/stopped; daemon exiting')
                    return
            except Exception as e:  # pylint: disable=broad-except
                logger.error('Daemon event error: %s', e, exc_info=True)
            time.sleep(EVENT_PERIOD_SECONDS)


# ---------------------------------------------------------------------------
# Daemon process management (backend-side helpers)
# ---------------------------------------------------------------------------

def _pid_file(cluster_name: str) -> str:
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, 'daemons', f'{cluster_name}.pid')


def daemon_alive(cluster_name: str) -> bool:
    path = _pid_file(cluster_name)
    if not os.path.exists(path):
        return False
    try:
        with open(path, encoding='utf-8') as f:
            pid = int(f.read().strip())
        proc = psutil.Process(pid)
        return 'skypilot_tpu.runtime.daemon' in ' '.join(proc.cmdline())
    except (ValueError, psutil.NoSuchProcess, psutil.AccessDenied):
        return False


def start_daemon(cluster_name: str) -> int:
    """Spawn the daemon detached (parity: start_skylet_on_head_node,
    provision/instance_setup.py:598)."""
    if daemon_alive(cluster_name):
        with open(_pid_file(cluster_name), encoding='utf-8') as f:
            return int(f.read().strip())
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    log_path = os.path.join(state_dir, 'daemons', f'{cluster_name}.log')
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    with open(log_path, 'a', encoding='utf-8') as log_file:
        import sys
        proc = subprocess.Popen(
            [sys.executable, '-u', '-m', 'skypilot_tpu.runtime.daemon',
             '--cluster', cluster_name],
            stdout=log_file, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL, start_new_session=True)
    with open(_pid_file(cluster_name), 'w', encoding='utf-8') as f:
        f.write(str(proc.pid))
    return proc.pid


def stop_daemon(cluster_name: str) -> None:
    path = _pid_file(cluster_name)
    if not os.path.exists(path):
        return
    try:
        with open(path, encoding='utf-8') as f:
            pid = int(f.read().strip())
        # Autostop runs teardown *from inside the daemon*: killing the
        # recorded pid would SIGTERM ourselves mid-teardown. The daemon
        # exits on its own after _check_autostop returns True.
        if pid != os.getpid():
            kill_process_tree(pid)
    except (ValueError, OSError):
        pass
    try:
        os.remove(path)
    except OSError:
        pass


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--cluster', required=True)
    args = parser.parse_args()
    Daemon(args.cluster).run_forever()


if __name__ == '__main__':
    main()
