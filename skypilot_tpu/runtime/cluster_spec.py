"""On-cluster runtime spec: everything the head-node daemon needs.

The reference's skylet reads cluster facts from Ray + a provision record
baked into the cluster YAML; here the backend writes ONE json file,
``<runtime_dir>/cluster.json``, at runtime-setup time and the daemon is
driven solely by it -- no access to the client's state DB required, so the
same daemon code runs backend-side (local-style clusters) and on a real
SSH-reachable head node (parity: ``sky/skylet/skylet.py`` +
``sky/provision/instance_setup.py:598`` start_skylet_on_head_node).

Hosts are rank-ordered. ``kind``:
* ``local``  -- the rank runs on the daemon's machine with HOME=``root``
  (fake/local providers: one private root dir per simulated host; the real
  head node itself: root='~').
* ``ssh``    -- the rank runs on another host of the cluster, reached from
  the head over SSH (``address``/``ssh_port``/spec.ssh_user/spec.ssh_key).

The autostop policy lives here too (updated in place by `skyt autostop`
through the job_cli shim) so idleness enforcement is cluster-local, like
the reference's autostop_lib (skylet/autostop_lib.py:137).
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional

CLUSTER_SPEC_FILENAME = 'cluster.json'


@dataclasses.dataclass
class HostSpec:
    rank: int
    kind: str                      # 'local' | 'ssh'
    root: Optional[str] = None     # local: host root dir ('~' = real home)
    address: Optional[str] = None  # ssh: address reachable from the head
    ssh_port: int = 22
    node_index: int = 0
    worker_index: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'HostSpec':
        return cls(**d)


@dataclasses.dataclass
class ClusterSpec:
    cluster_name: str
    cloud: Optional[str]
    hosts: List[HostSpec]
    ssh_user: str = 'skyt'
    ssh_key: Optional[str] = None      # path on the head node
    autostop: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({
            'cluster_name': self.cluster_name,
            'cloud': self.cloud,
            'hosts': [h.to_dict() for h in self.hosts],
            'ssh_user': self.ssh_user,
            'ssh_key': self.ssh_key,
            'autostop': self.autostop,
        }, indent=1)

    @classmethod
    def from_json(cls, text: str) -> 'ClusterSpec':
        d = json.loads(text)
        d['hosts'] = [HostSpec.from_dict(h) for h in d['hosts']]
        return cls(**d)


def spec_path(runtime_dir: str) -> str:
    return os.path.join(os.path.expanduser(runtime_dir),
                        CLUSTER_SPEC_FILENAME)


def write_spec(runtime_dir: str, spec: ClusterSpec) -> None:
    path = spec_path(runtime_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + '.tmp'
    with open(tmp, 'w', encoding='utf-8') as f:
        f.write(spec.to_json())
    os.replace(tmp, path)


def read_spec(runtime_dir: str) -> Optional[ClusterSpec]:
    path = spec_path(runtime_dir)
    if not os.path.exists(path):
        return None
    with open(path, encoding='utf-8') as f:
        return ClusterSpec.from_json(f.read())


def set_autostop(runtime_dir: str, config: Dict[str, Any]) -> None:
    """Update the autostop policy in place (daemon re-reads every loop)."""
    spec = read_spec(runtime_dir)
    if spec is None:
        raise FileNotFoundError(
            f'No cluster spec at {spec_path(runtime_dir)}')
    spec.autostop = config
    write_spec(runtime_dir, spec)
