"""On-cluster runtime (the skylet equivalent -- parity: ``sky/skylet/``).

Lives on the head node of every cluster: cluster-local job queue
(`job_lib`), the runtime daemon with scheduling/autostop events
(`daemon`), and log capture/tailing (`log_lib`).
"""
