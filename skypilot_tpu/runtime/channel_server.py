"""Persistent runtime channel: head-side server process.

Parity: the reference skylet serves four gRPC services over ONE
SSH-tunneled channel per cluster (``sky/schemas/proto/jobsv1.proto`` et
al., channel setup ``cloud_vm_ray_backend.py:2395``) so clients don't pay
an SSH exec per job-table op and the server can receive pushes. This is
the same architecture without gRPC (not in the image, and a 60-line
framed protocol carries the identical schema): the backend holds one
``python -m skypilot_tpu.runtime.channel_server`` process per cluster —
spawned through the cluster's transport (local / ssh / kubectl exec) —
and multiplexes requests over its stdin/stdout.

Wire format: 4-byte big-endian length + UTF-8 JSON, both directions.

* request:  ``{"id": N, "op": "...", ...params}``
* response: ``{"id": N, "ok": true, "result": ...}`` or
  ``{"id": N, "ok": false, "error": "..."}``
* stream:   ``{"id": N, "stream": "data", "text": "..."}`` repeated,
  then ``{"id": N, "stream": "end"}`` (used by ``tail``)
* push:     ``{"event": "job", "job_id": J, "status": "...", "ts": T}``
  — unsolicited job-state transitions from the table watcher, the bit
  the one-shot job_cli shim fundamentally cannot do.

Ops are the job_cli command set (the handlers are literally shared); the
server exits when stdin closes, so a dropped transport can never leak a
process.
"""
from __future__ import annotations

import argparse
import json
import os
import struct
import sys
import threading
import time
from typing import Any, Dict

from skypilot_tpu.runtime import job_cli, job_lib, log_lib
from skypilot_tpu.utils import env_registry, events

_LEN = struct.Struct('>I')
MAX_FRAME = 64 << 20

# Degraded-mode cadence: the watcher normally wakes on job-table
# notifications (in-process publishes from the op handlers; a
# data_version signal on jobs.db for the on-node daemon's writes) and
# only diffs on a wakeup. WATCH_PERIOD is the supervised poll fallback
# that bounds staleness when both signals are lost; head-local sqlite
# reads are ~free, so the legacy 0.3 s default keeps even the degraded
# path inside the "<2 s without a poll tick (server-side)" bar.
WATCH_PERIOD = env_registry.get_float('SKYT_CHANNEL_WATCH_PERIOD')


def read_frame(stream) -> Dict[str, Any]:
    header = stream.read(_LEN.size)
    if len(header) < _LEN.size:
        raise EOFError
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f'frame of {length} bytes exceeds {MAX_FRAME}')
    body = b''
    while len(body) < length:
        chunk = stream.read(length - len(body))
        if not chunk:
            raise EOFError
        body += chunk
    return json.loads(body.decode('utf-8'))


def write_frame(stream, obj: Dict[str, Any], lock=None) -> None:
    body = json.dumps(obj).encode('utf-8')
    data = _LEN.pack(len(body)) + body
    if lock is not None:
        with lock:
            stream.write(data)
            stream.flush()
    else:
        stream.write(data)
        stream.flush()


class ChannelServer:
    def __init__(self, runtime_dir: str) -> None:
        self.runtime_dir = runtime_dir
        self._out = sys.stdout.buffer
        self._lock = threading.Lock()
        self._stopping = threading.Event()

    # -- outbound ------------------------------------------------------

    def _send(self, obj: Dict[str, Any]) -> None:
        try:
            write_frame(self._out, obj, self._lock)
        except (BrokenPipeError, OSError):
            self._stopping.set()

    # -- op handlers ---------------------------------------------------

    def _handle(self, req: Dict[str, Any]) -> None:
        rid = req.get('id')
        op = req.get('op')
        rt = self.runtime_dir
        try:
            if op == 'ping':
                result = {'pong': True, 'ts': time.time()}
            elif op == 'submit':
                result = job_cli.cmd_submit(rt, req['payload_b64'])
            elif op == 'add':
                result = job_cli.cmd_add(rt, req.get('name', ''),
                                         int(req.get('num_hosts', 1)),
                                         req.get('status', 'PENDING'))
            elif op == 'set_status':
                result = job_cli.cmd_set_status(rt, int(req['job_id']),
                                                req['status'],
                                                req.get('exit_code'))
            elif op == 'list':
                result = job_cli.cmd_list(rt)
            elif op == 'get':
                result = job_cli.cmd_get(rt, int(req['job_id']))
            elif op == 'cancel':
                result = job_cli.cmd_cancel(rt, int(req['job_id']))
            elif op == 'set_autostop':
                result = job_cli.cmd_set_autostop(rt, req['config_b64'])
            elif op == 'daemon_status':
                result = job_cli.cmd_daemon_status(rt)
            elif op == 'tail':
                self._stream_tail(rid, int(req['job_id']),
                                  bool(req.get('follow')))
                return
            else:
                self._send({'id': rid, 'ok': False,
                            'error': f'unknown op {op!r}'})
                return
        except Exception as e:  # pylint: disable=broad-except
            self._send({'id': rid, 'ok': False,
                        'error': f'{type(e).__name__}: {e}'})
            return
        self._send({'id': rid, 'ok': True, 'result': result})

    def _stream_tail(self, rid, job_id: int, follow: bool) -> None:
        job = job_lib.get_job(self.runtime_dir, job_id)
        if job is None:
            self._send({'id': rid, 'ok': False, 'kind': 'not_found',
                        'error': f'No job {job_id} on cluster'})
            return
        log_path = os.path.join(
            job_lib.job_log_dir(self.runtime_dir, job_id), 'rank_0.log')

        stop_condition = job_cli.follow_stop_condition(self.runtime_dir,
                                                       job_id)

        def job_done() -> bool:
            return self._stopping.is_set() or stop_condition()

        if not follow and not os.path.exists(log_path):
            self._send({'id': rid, 'ok': False, 'kind': 'not_found',
                        'error': f'No logs for job {job_id}'})
            return
        for line in log_lib.tail_file(log_path, follow=follow,
                                      stop_when=job_done):
            self._send({'id': rid, 'stream': 'data', 'text': line})
            if self._stopping.is_set():
                return
        self._send({'id': rid, 'stream': 'end'})

    # -- job-table watcher (the push half) -----------------------------

    @staticmethod
    def _watch_fallback() -> float:
        """Poll cadence when no notification arrives. With eventing on,
        wakeups come from the bus/data_version within ~ms and this only
        bounds staleness after a LOST signal — capped at 2 s so even
        the degraded mode meets the <2 s push bar."""
        env = env_registry.get_float('SKYT_CHANNEL_WATCH_FALLBACK')
        if env is not None:
            return env
        if not events.enabled():
            return WATCH_PERIOD
        return max(WATCH_PERIOD, min(2.0, 10 * WATCH_PERIOD))

    def _watch(self) -> None:
        seen: Dict[int, str] = {}
        first = True
        # Event-driven (replaces the fixed-cadence table diff): op
        # handlers in THIS process publish on every job write; the
        # on-node daemon's writes (separate process) bump jobs.db's
        # data_version. Either wakes the diff immediately; the
        # supervised fallback diff below survives losing both.
        signal = events.external_signal(
            None, os.path.join(os.path.expanduser(self.runtime_dir),
                               'jobs.db'), events.RUNTIME_JOBS)
        cursor = events.cursor(events.RUNTIME_JOBS)
        while not self._stopping.is_set():
            # Snapshot BEFORE the diff read: a daemon write landing
            # mid-diff fires the next wait instead of being missed.
            ext_base = events.external_cursor(events.RUNTIME_JOBS,
                                              signal)
            try:
                jobs = job_lib.list_jobs(self.runtime_dir)
            except Exception:  # pylint: disable=broad-except
                jobs = []
            for job in jobs:
                job_id, status = job['job_id'], job['status']
                if seen.get(job_id) != status:
                    seen[job_id] = status
                    if not first:  # don't replay history on connect
                        self._send({'event': 'job', 'job_id': job_id,
                                    'status': status,
                                    'name': job.get('name'),
                                    'exit_code': job.get('exit_code'),
                                    'ts': time.time()})
            first = False
            cursor, _ = events.wait_for(events.RUNTIME_JOBS, cursor,
                                        self._watch_fallback(),
                                        external=signal,
                                        stop_event=self._stopping,
                                        external_base=ext_base)

    def serve(self) -> None:
        watcher = threading.Thread(target=self._watch, daemon=True)
        watcher.start()
        stdin = sys.stdin.buffer
        while not self._stopping.is_set():
            try:
                req = read_frame(stdin)
            except EOFError:
                break
            except ValueError:
                break
            threading.Thread(target=self._handle, args=(req,),
                             daemon=True).start()
        self._stopping.set()


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument('--runtime-dir',
                        default=job_lib.DEFAULT_RUNTIME_DIR)
    args = parser.parse_args()
    ChannelServer(args.runtime_dir).serve()
    return 0


if __name__ == '__main__':
    sys.exit(main())
