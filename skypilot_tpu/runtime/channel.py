"""Persistent runtime channel: client side.

One long-lived ``channel_server`` process per cluster, spawned through
the cluster's transport (``CommandRunner.popen``: local bash / ssh /
kubectl exec) and multiplexed by request id. Replaces one-SSH-exec-per-op
``RemoteJobTable`` traffic with framed messages on an open pipe, and
surfaces the server's job-state pushes (parity: the reference's
skylet gRPC channel, ``cloud_vm_ray_backend.py:2395``; VERDICT r3
missing #3).

``get_channel(info)`` caches one client per cluster per process and
transparently reconnects a dead channel on next use. ``job_table_for``
(runtime/job_client.py) upgrades to a ``ChannelJobTable`` when a channel
can be established, keeping the job_cli shim as the fallback transport.
"""
from __future__ import annotations

import atexit
import queue
import threading
from typing import Any, Callable, Dict, IO, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.runtime.channel_server import read_frame, write_frame
from skypilot_tpu.runtime.job_client import (REMOTE_PKG_DIR,
                                             REMOTE_RUNTIME_DIR,
                                             encode_b64_json,
                                             encode_submit_payload)
from skypilot_tpu.utils import env_registry, log

logger = log.init_logger(__name__)

DEFAULT_TIMEOUT = env_registry.get_float('SKYT_CHANNEL_TIMEOUT')


class ChannelError(exceptions.CommandError):
    def __init__(self, message: str) -> None:
        super().__init__(1, 'runtime channel', error_msg=message)


class ChannelClient:
    """Framed-protocol client over a Popen'd channel_server."""

    def __init__(self, proc, name: str = '') -> None:
        self.proc = proc
        self.name = name
        self._lock = threading.Lock()          # write serialization
        self._next_id = 1
        self._pending: Dict[int, queue.Queue] = {}
        self._pending_lock = threading.Lock()
        self.on_event: Optional[Callable[[Dict[str, Any]], None]] = None
        self._reader = threading.Thread(target=self._read_loop,
                                        name=f'channel-{name}',
                                        daemon=True)
        self._reader.start()

    # -- plumbing ------------------------------------------------------

    def _read_loop(self) -> None:
        stream = self.proc.stdout
        try:
            while True:
                frame = read_frame(stream)
                if 'event' in frame:
                    cb = self.on_event
                    if cb is not None:
                        try:
                            cb(frame)
                        except Exception:  # pylint: disable=broad-except
                            logger.debug('event callback failed',
                                         exc_info=True)
                    continue
                rid = frame.get('id')
                with self._pending_lock:
                    waiter = self._pending.get(rid)
                if waiter is not None:
                    waiter.put(frame)
        except (EOFError, ValueError, OSError):
            pass
        finally:
            # Wake every waiter so callers fail fast instead of timing
            # out one by one against a dead channel.
            with self._pending_lock:
                waiters = list(self._pending.values())
            for waiter in waiters:
                waiter.put({'ok': False, 'error': 'channel closed',
                            'closed': True})

    def alive(self) -> bool:
        return self.proc.poll() is None and self._reader.is_alive()

    def close(self) -> None:
        try:
            if self.proc.stdin:
                self.proc.stdin.close()
        except OSError:
            pass
        try:
            self.proc.terminate()
        except OSError:
            pass

    def _send(self, obj: Dict[str, Any]) -> int:
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            obj = {'id': rid, **obj}
            with self._pending_lock:
                self._pending[rid] = queue.Queue()
            try:
                write_frame(self.proc.stdin, obj)
            except (BrokenPipeError, OSError) as e:
                with self._pending_lock:
                    self._pending.pop(rid, None)
                raise ChannelError(f'channel write failed: {e}') from e
        return rid

    def _wait(self, rid: int, timeout: float) -> Dict[str, Any]:
        try:
            frame = self._pending[rid].get(timeout=timeout)
        except queue.Empty:
            raise ChannelError(f'channel op timed out after {timeout}s')
        finally:
            with self._pending_lock:
                self._pending.pop(rid, None)
        return frame

    # -- public API ----------------------------------------------------

    def request(self, op: str, timeout: float = DEFAULT_TIMEOUT,
                **params) -> Any:
        rid = self._send({'op': op, **params})
        frame = self._wait(rid, timeout)
        if not frame.get('ok'):
            raise ChannelError(frame.get('error', 'unknown channel error'))
        return frame.get('result')

    def tail(self, job_id: int, *, follow: bool = False,
             stream: Optional[IO[str]] = None,
             timeout: float = DEFAULT_TIMEOUT) -> str:
        """Stream a job's rank-0 log over the channel; returns the full
        text. ``follow`` keeps streaming until the job is terminal —
        with NO additional round trips (the server pushes chunks)."""
        rid = self._send({'op': 'tail', 'job_id': job_id,
                          'follow': follow})
        waiter = self._pending[rid]
        buf = []
        try:
            while True:
                try:
                    # follow streams have no inter-chunk deadline: a
                    # silent job may log nothing for hours.
                    frame = waiter.get(timeout=None if follow else timeout)
                except queue.Empty:
                    raise ChannelError(
                        f'tail timed out after {timeout}s')
                if frame.get('stream') == 'data':
                    text = frame.get('text', '')
                    buf.append(text)
                    if stream is not None:
                        stream.write(text)
                        stream.flush()
                    continue
                if frame.get('stream') == 'end':
                    return ''.join(buf)
                if frame.get('kind') == 'not_found':
                    raise exceptions.JobNotFoundError(
                        frame.get('error', f'no job {job_id}'))
                raise ChannelError(
                    frame.get('error', 'channel closed mid-tail'))
        finally:
            with self._pending_lock:
                self._pending.pop(rid, None)


class ChannelJobTable:
    """JobTable-shaped facade over a ChannelClient (see
    runtime/job_client.py for the interface contract)."""

    def __init__(self, client: ChannelClient) -> None:
        self.client = client

    def submit(self, name, num_hosts, scripts, metadata=None) -> int:
        b64 = encode_submit_payload(name, num_hosts, scripts, metadata)
        return int(self.client.request('submit', payload_b64=b64)['job_id'])

    def add_job(self, name, num_hosts, status) -> int:
        return int(self.client.request(
            'add', name=name or '', num_hosts=num_hosts,
            status=status.value)['job_id'])

    def set_status(self, job_id, status, exit_code=None) -> None:
        self.client.request('set_status', job_id=job_id,
                            status=status.value, exit_code=exit_code)

    def list_jobs(self):
        return self.client.request('list')

    def get(self, job_id):
        job = self.client.request('get', job_id=job_id)
        return None if job.get('error') == 'not_found' else job

    def cancel(self, job_id) -> bool:
        return bool(self.client.request('cancel',
                                        job_id=job_id)['cancelled'])

    def set_autostop(self, config) -> None:
        self.client.request('set_autostop',
                            config_b64=encode_b64_json(config))

    def tail(self, job_id, *, follow=False, stream=None) -> str:
        import sys
        return self.client.tail(job_id, follow=follow,
                                stream=stream or sys.stdout)

    def daemon_alive(self) -> bool:
        try:
            return bool(self.client.request('daemon_status',
                                            timeout=30).get('alive'))
        except (ChannelError, exceptions.CommandError):
            return False


# ---------------------------------------------------------------------------
# Per-process channel cache
# ---------------------------------------------------------------------------

_channels: Dict[str, ChannelClient] = {}
_channels_lock = threading.Lock()


def channels_enabled() -> bool:
    return env_registry.get_bool('SKYT_RUNTIME_CHANNEL')


def _spawn(info) -> Optional[ChannelClient]:
    from skypilot_tpu.backend import runtime_setup
    from skypilot_tpu.utils.command_runner import runners_for_cluster
    head = runners_for_cluster(info)[0]
    if runtime_setup.is_local_style(info):
        import shlex
        import sys
        # Quoted: a state dir with spaces/metacharacters would
        # otherwise start the server against the wrong path, and the
        # failure is silent (job_table_for just falls back to the
        # shim, losing the push path).
        runtime_dir = runtime_setup.head_runtime_dir(info)
        cmd = (f'{shlex.quote(sys.executable)} '
               f'-m skypilot_tpu.runtime.channel_server '
               f'--runtime-dir {shlex.quote(runtime_dir)}')
    else:
        cmd = (f'PYTHONPATH={REMOTE_PKG_DIR}:$PYTHONPATH '
               f'python3 -m skypilot_tpu.runtime.channel_server '
               f'--runtime-dir {REMOTE_RUNTIME_DIR}')
    try:
        proc = head.popen(cmd)
    except (OSError, exceptions.CommandError) as e:
        logger.debug('channel spawn for %s failed: %s',
                     info.cluster_name, e)
        return None
    client = ChannelClient(proc, name=info.cluster_name)
    try:
        client.request('ping', timeout=30)
    except (ChannelError, exceptions.CommandError) as e:
        logger.debug('channel ping for %s failed: %s',
                     info.cluster_name, e)
        client.close()
        return None
    return client


def get_channel(info) -> Optional[ChannelClient]:
    """The cluster's live channel, (re)connecting as needed; None when a
    channel can't be established (caller falls back to the shim)."""
    if not channels_enabled():
        return None
    with _channels_lock:
        client = _channels.get(info.cluster_name)
        if client is not None and client.alive():
            return client
        if client is not None:
            client.close()
            del _channels[info.cluster_name]
    client = _spawn(info)
    if client is None:
        return None
    with _channels_lock:
        existing = _channels.get(info.cluster_name)
        if existing is not None and existing.alive():
            client.close()   # lost a benign race
            return existing
        _channels[info.cluster_name] = client
    return client


def drop_channel(cluster_name: str) -> None:
    """Close + forget a cluster's channel (teardown, tests)."""
    with _channels_lock:
        client = _channels.pop(cluster_name, None)
    if client is not None:
        client.close()


@atexit.register
def _close_all() -> None:
    with _channels_lock:
        clients = list(_channels.values())
        _channels.clear()
    for client in clients:
        client.close()
