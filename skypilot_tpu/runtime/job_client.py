"""Backend-side access to a cluster's job table.

Two transports behind one interface (parity: the reference reaches the
cluster job queue via skylet gRPC, ``cloud_vm_ray_backend.py:2884``, with
an SSH-codegen fallback, ``job_lib.py:1161``):

* ``DirectJobTable`` -- the head "host" is a directory on this machine
  (fake/local providers): plain function calls into runtime/job_lib.
* ``RemoteJobTable`` -- a real cluster: run the job_cli shim on the head
  node through the cluster's CommandRunner (SSH/kubectl).
"""
from __future__ import annotations

import base64
import json
import os
import time
from typing import Any, Dict, IO, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.runtime import cluster_spec, job_lib, log_lib

REMOTE_RUNTIME_DIR = '~/.skyt_runtime'
# Where runtime_setup extracts the shipped package on each host.
REMOTE_PKG_DIR = '~/.skyt_runtime/runtime'


def encode_b64_json(obj: Any) -> str:
    """Wire encoding shared by the job_cli shim and the channel
    transport — both feed job_cli's cmd_* handlers on the head."""
    return base64.b64encode(
        json.dumps(obj).encode('utf-8')).decode('ascii')


def encode_submit_payload(name: Optional[str], num_hosts: int,
                          scripts: Dict[int, str],
                          metadata: Optional[Dict[str, Any]]) -> str:
    return encode_b64_json({
        'name': name,
        'num_hosts': num_hosts,
        'scripts': {str(r): s for r, s in scripts.items()},
        'metadata': metadata or {},
    })


class JobTable:
    """Submit/inspect/cancel jobs + runtime-daemon state on one cluster."""

    def submit(self, name: Optional[str], num_hosts: int,
               scripts: Dict[int, str],
               metadata: Optional[Dict[str, Any]] = None) -> int:
        raise NotImplementedError

    def add_job(self, name: Optional[str], num_hosts: int,
                status: job_lib.JobStatus) -> int:
        """Record a job row without scripts (foreground execution)."""
        raise NotImplementedError

    def set_status(self, job_id: int, status: job_lib.JobStatus,
                   exit_code: Optional[int] = None) -> None:
        raise NotImplementedError

    def list_jobs(self) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def get(self, job_id: int) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def cancel(self, job_id: int) -> bool:
        raise NotImplementedError

    def set_autostop(self, config: Dict[str, Any]) -> None:
        raise NotImplementedError

    def tail(self, job_id: int, *, follow: bool = False,
             stream: Optional[IO[str]] = None) -> str:
        raise NotImplementedError

    def daemon_alive(self) -> bool:
        raise NotImplementedError


class DirectJobTable(JobTable):
    def __init__(self, runtime_dir: str) -> None:
        self.runtime_dir = runtime_dir

    def submit(self, name, num_hosts, scripts, metadata=None) -> int:
        job_id = job_lib.add_job(self.runtime_dir, name,
                                 num_hosts=num_hosts, metadata=metadata,
                                 status=job_lib.JobStatus.SETTING_UP)
        log_dir = job_lib.job_log_dir(self.runtime_dir, job_id)
        os.makedirs(log_dir, exist_ok=True)
        for rank, script in scripts.items():
            with open(os.path.join(log_dir, f'rank_{rank}.sh'), 'w',
                      encoding='utf-8') as f:
                f.write(script)
        job_lib.set_status(self.runtime_dir, job_id,
                           job_lib.JobStatus.PENDING)
        return job_id

    def add_job(self, name, num_hosts, status):
        return job_lib.add_job(self.runtime_dir, name,
                               num_hosts=num_hosts, status=status)

    def set_status(self, job_id, status, exit_code=None):
        job_lib.set_status(self.runtime_dir, job_id, status,
                           exit_code=exit_code)

    def list_jobs(self):
        return job_lib.list_jobs(self.runtime_dir)

    def get(self, job_id):
        return job_lib.get_job(self.runtime_dir, job_id)

    def cancel(self, job_id):
        return job_lib.cancel_job(self.runtime_dir, job_id)

    def set_autostop(self, config):
        cluster_spec.set_autostop(self.runtime_dir, config)

    def tail(self, job_id, *, follow=False, stream=None):
        if self.get(job_id) is None:
            raise exceptions.JobNotFoundError(
                f'No job {job_id} on cluster')
        log_path = os.path.join(
            job_lib.job_log_dir(self.runtime_dir, job_id), 'rank_0.log')
        if not follow and not os.path.exists(log_path):
            raise exceptions.JobNotFoundError(
                f'No logs for job {job_id} at {log_path}')
        from skypilot_tpu.runtime import job_cli
        lines = log_lib.tail_file(
            log_path, follow=follow,
            stop_when=job_cli.follow_stop_condition(self.runtime_dir,
                                                    job_id))
        import sys
        return log_lib.stream_to(lines, stream or sys.stdout)

    def daemon_alive(self) -> bool:
        # cmd_daemon_status verifies the heartbeat's PID is actually
        # alive — a daemon killed seconds ago leaves a fresh heartbeat
        # that would otherwise read as healthy for up to 30s.
        from skypilot_tpu.runtime import job_cli
        return bool(job_cli.cmd_daemon_status(
            self.runtime_dir).get('alive'))


class RemoteJobTable(JobTable):
    """Drives the job_cli shim on the head node via a CommandRunner."""

    def __init__(self, head_runner,
                 runtime_dir: str = REMOTE_RUNTIME_DIR) -> None:
        self.runner = head_runner
        self.runtime_dir = runtime_dir

    def _invoke(self, args: str, *, stream: Optional[IO[str]] = None,
                check_rc: bool = True) -> Any:
        cmd = (f'PYTHONPATH={REMOTE_PKG_DIR}:$PYTHONPATH '
               f'python3 -m skypilot_tpu.runtime.job_cli '
               f'--runtime-dir {self.runtime_dir} {args}')
        code, output = self.runner.run(cmd, stream_to=stream)
        if code != 0 and check_rc:
            raise exceptions.CommandError(
                code, f'job_cli {args.split()[0]}',
                error_msg=output[-2000:])
        return code, output

    @staticmethod
    def _parse(output: str) -> Any:
        for line in reversed(output.strip().splitlines()):
            line = line.strip()
            if line.startswith(('{', '[')):
                return json.loads(line)
        raise exceptions.CommandError(
            1, 'job_cli', error_msg=f'No JSON in output: {output[-500:]}')

    def submit(self, name, num_hosts, scripts, metadata=None) -> int:
        b64 = encode_submit_payload(name, num_hosts, scripts, metadata)
        _, output = self._invoke(f'submit {b64}')
        return int(self._parse(output)['job_id'])

    def add_job(self, name, num_hosts, status):
        import shlex
        name_arg = f'--name {shlex.quote(name)} ' if name else ''
        _, output = self._invoke(
            f'add {name_arg}--num-hosts {num_hosts} '
            f'--status {status.value}')
        return int(self._parse(output)['job_id'])

    def set_status(self, job_id, status, exit_code=None):
        exit_arg = (f' --exit-code {exit_code}'
                    if exit_code is not None else '')
        self._invoke(f'set-status {job_id} {status.value}{exit_arg}')

    def list_jobs(self):
        _, output = self._invoke('list')
        return self._parse(output)

    def get(self, job_id):
        _, output = self._invoke(f'get {job_id}')
        job = self._parse(output)
        return None if job.get('error') == 'not_found' else job

    def cancel(self, job_id):
        _, output = self._invoke(f'cancel {job_id}')
        return bool(self._parse(output)['cancelled'])

    def set_autostop(self, config):
        self._invoke(f'set-autostop {encode_b64_json(config)}')

    def tail(self, job_id, *, follow=False, stream=None):
        import sys
        stream = stream or sys.stdout
        flag = ' --follow' if follow else ''
        code, output = self._invoke(f'tail {job_id}{flag}', stream=stream,
                                    check_rc=False)
        if code == 3:
            raise exceptions.JobNotFoundError(
                f'No job/logs for {job_id}: {output[-300:]}')
        if code != 0:
            raise exceptions.CommandError(code, 'job_cli tail',
                                          error_msg=output[-2000:])
        return output

    def daemon_alive(self) -> bool:
        try:
            _, output = self._invoke('daemon-status')
            return bool(self._parse(output).get('alive'))
        except exceptions.CommandError:
            return False


def job_table_for(info) -> JobTable:
    """The right transport for this cluster's job table.

    Non-local clusters prefer, in order: the channel BROKER (a resident
    process — the API server — owns one live channel per cluster and
    short-lived forked request children proxy through its unix socket,
    runtime/channel_broker.py); a direct persistent channel owned by
    THIS process (one live connection per cluster, framed ops, no
    per-op SSH exec — runtime/channel.py); the job_cli shim as the last
    fallback (runtime not shipped yet, transport down, or
    ``SKYT_RUNTIME_CHANNEL=0``).
    """
    from skypilot_tpu.backend import runtime_setup
    from skypilot_tpu.utils.command_runner import runners_for_cluster
    if runtime_setup.is_local_style(info):
        return DirectJobTable(runtime_setup.head_runtime_dir(info))
    from skypilot_tpu.runtime import channel as channel_lib
    from skypilot_tpu.runtime import channel_broker
    table = channel_broker.broker_job_table(info)
    if table is not None:
        return table
    client = channel_lib.get_channel(info)
    if client is not None:
        return channel_lib.ChannelJobTable(client)
    return RemoteJobTable(runners_for_cluster(info)[0])
