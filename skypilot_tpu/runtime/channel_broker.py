"""Channel broker: one resident owner for the per-cluster runtime
channels, shared by every short-lived process.

The executor forks a process per request (server/executor.py), so the
per-PROCESS channel cache in runtime/channel.py never pays off on the
request path: each forked child that touches a cluster spawns a fresh
``channel_server`` over SSH, uses it once, and exits — exactly the
per-op transport cost the channel exists to kill. The reference keeps
ONE cached skylet channel per cluster inside its long-lived server
process (``cloud_vm_ray_backend.py:2395``); this broker restores that
shape for the fork-per-request architecture:

* The API server runs a :class:`ChannelBroker` thread that listens on a
  unix socket and OWNS the channel cache (``channel.get_channel`` in
  the server process — the same cache the server daemons use, so event
  pushes keep landing in one place).
* Runner processes (and the request children they fork) inherit
  ``SKYT_CHANNEL_BROKER_SOCK`` and proxy job-table ops through the
  socket — zero SSH spawns on the request path.
* Anything without the env (CLI local mode, tests, the server process
  itself) keeps the direct per-process channel path.

Wire format: the channel's own framed JSON (4-byte length prefix,
``channel_server.read_frame``/``write_frame``). One request frame per
connection-op: ``{"op": .., "info": <ClusterInfo dict>, "params": ..}``;
responses mirror the channel protocol, including ``stream`` frames for
``tail``.
"""
from __future__ import annotations

import os
import shutil
import socket
import socketserver
import tempfile
import threading
from typing import Any, Dict, IO, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.runtime.channel import ChannelError
from skypilot_tpu.runtime.channel_server import read_frame, write_frame
from skypilot_tpu.utils import env_registry, log

logger = log.init_logger(__name__)

BROKER_SOCK_ENV = 'SKYT_CHANNEL_BROKER_SOCK'
DEFAULT_TIMEOUT = env_registry.get_float('SKYT_CHANNEL_TIMEOUT')


def _sock_dir() -> str:
    # A fresh mkdtemp (0700) under /tmp — not the state dir: AF_UNIX
    # paths cap at ~107 bytes and test tmpdirs routinely blow past
    # that. The private parent directory closes the ADVICE r5 window
    # where the socket itself was world-connectable between bind and
    # chmod: no other local user can traverse to it at any point.
    return tempfile.mkdtemp(prefix='skyt-brk-', dir='/tmp')


class _Handler(socketserver.BaseRequestHandler):
    """One connection = one op (connects are ~free on a unix socket and
    a connection-per-op keeps the broker stateless per client)."""

    def handle(self) -> None:  # noqa: D102
        rfile = self.request.makefile('rb')
        wfile = self.request.makefile('wb')
        try:
            frame = read_frame(rfile)
            self._dispatch(frame, wfile)
        except (EOFError, ValueError, OSError, BrokenPipeError):
            pass
        finally:
            for f in (rfile, wfile):
                try:
                    f.close()
                except OSError:
                    pass

    def _dispatch(self, frame: Dict[str, Any], wfile) -> None:
        from skypilot_tpu.provision.api import ClusterInfo
        from skypilot_tpu.runtime import channel as channel_lib
        op = frame.get('op')
        if op == 'broker_ping':
            write_frame(wfile, {'ok': True, 'result': 'pong'})
            wfile.flush()
            return
        info = ClusterInfo.from_dict(frame['info'])
        client = channel_lib.get_channel(info)
        if op == 'ensure_channel':
            write_frame(wfile, {'ok': True,
                                'result': client is not None})
            wfile.flush()
            return
        if client is None:
            write_frame(wfile, {'ok': False, 'no_channel': True,
                                'error': f'no channel to '
                                         f'{info.cluster_name}'})
            wfile.flush()
            return
        params = frame.get('params') or {}
        timeout = float(frame.get('timeout') or DEFAULT_TIMEOUT)
        try:
            if op == 'tail':
                self._tail(client, params, wfile, timeout)
            else:
                result = client.request(op, timeout=timeout, **params)
                write_frame(wfile, {'ok': True, 'result': result})
        except exceptions.JobNotFoundError as e:
            write_frame(wfile, {'ok': False, 'kind': 'not_found',
                                'error': str(e)})
        except (exceptions.CommandError, OSError) as e:
            write_frame(wfile, {'ok': False, 'error': str(e)})
        wfile.flush()

    @staticmethod
    def _tail(client, params: Dict[str, Any], wfile,
              timeout: float) -> None:
        class _FrameStream:
            """Relay tail chunks as stream frames as they arrive."""

            @staticmethod
            def write(text: str) -> None:
                write_frame(wfile, {'stream': 'data', 'text': text})

            @staticmethod
            def flush() -> None:
                wfile.flush()

        client.tail(int(params['job_id']),
                    follow=bool(params.get('follow')),
                    stream=_FrameStream(), timeout=timeout)
        write_frame(wfile, {'stream': 'end'})


class _ThreadingUnixServer(socketserver.ThreadingMixIn,
                           socketserver.UnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class ChannelBroker:
    """The in-server broker endpoint (started by ApiServer)."""

    def __init__(self, sock_path: Optional[str] = None) -> None:
        self._own_dir: Optional[str] = None
        if sock_path is None:
            self._own_dir = _sock_dir()
            sock_path = os.path.join(self._own_dir, 'broker.sock')
        self.sock_path = sock_path
        # Umask-guard the bind for caller-supplied paths too: the
        # socket is born 0600 instead of racing a post-bind chmod.
        old_umask = os.umask(0o177)
        try:
            self._server = _ThreadingUnixServer(self.sock_path, _Handler)
        finally:
            os.umask(old_umask)
        os.chmod(self.sock_path, 0o600)
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name='channel-broker',
                                        daemon=True)
        self._thread.start()
        logger.debug('Channel broker listening on %s', self.sock_path)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass
        if self._own_dir is not None:
            shutil.rmtree(self._own_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Client side (runs in runner/request-child processes)
# ---------------------------------------------------------------------------


class BrokerUnavailable(ChannelError):
    """Broker socket gone/unresponsive mid-use. Subclasses ChannelError
    (itself a CommandError) so every existing channel-failure handler —
    daemon_alive's (ChannelError, CommandError) catch, status refresh —
    degrades the same way a dead direct channel does."""


class BrokerChannelProxy:
    """Quacks like ChannelClient (``request``/``tail``) but executes
    each op through the broker's cached channel."""

    def __init__(self, sock_path: str, info) -> None:
        self.sock_path = sock_path
        self.info_dict = info.to_dict()
        self.name = info.cluster_name

    def _dial(self):
        try:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(DEFAULT_TIMEOUT + 30)
            sock.connect(self.sock_path)
            return sock
        except OSError as e:
            raise BrokerUnavailable(str(e)) from e

    def _roundtrip(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        sock = self._dial()
        try:
            wfile = sock.makefile('wb')
            rfile = sock.makefile('rb')
            write_frame(wfile, frame)
            wfile.flush()
            return read_frame(rfile)
        except (EOFError, ValueError, OSError) as e:
            raise BrokerUnavailable(str(e)) from e
        finally:
            sock.close()

    def request(self, op: str, timeout: float = DEFAULT_TIMEOUT,
                **params) -> Any:
        reply = self._roundtrip({'op': op, 'info': self.info_dict,
                                 'params': params, 'timeout': timeout})
        if not reply.get('ok'):
            if reply.get('kind') == 'not_found':
                raise exceptions.JobNotFoundError(
                    reply.get('error', 'not found'))
            raise ChannelError(reply.get('error', 'broker op failed'))
        return reply.get('result')

    def ensure_channel(self) -> bool:
        reply = self._roundtrip({'op': 'ensure_channel',
                                 'info': self.info_dict})
        return bool(reply.get('ok') and reply.get('result'))

    def tail(self, job_id: int, *, follow: bool = False,
             stream: Optional[IO[str]] = None,
             timeout: float = DEFAULT_TIMEOUT) -> str:
        sock = self._dial()
        buf = []
        try:
            wfile = sock.makefile('wb')
            rfile = sock.makefile('rb')
            if follow:
                sock.settimeout(None)  # silent jobs may log nothing
            write_frame(wfile, {'op': 'tail', 'info': self.info_dict,
                                'params': {'job_id': job_id,
                                           'follow': follow},
                                'timeout': timeout})
            wfile.flush()
            while True:
                frame = read_frame(rfile)
                if frame.get('stream') == 'data':
                    text = frame.get('text', '')
                    buf.append(text)
                    if stream is not None:
                        stream.write(text)
                        stream.flush()
                    continue
                if frame.get('stream') == 'end':
                    return ''.join(buf)
                if frame.get('kind') == 'not_found':
                    raise exceptions.JobNotFoundError(
                        frame.get('error', f'no job {job_id}'))
                raise ChannelError(
                    frame.get('error', 'broker closed mid-tail'))
        except (EOFError, ValueError, OSError) as e:
            raise BrokerUnavailable(str(e)) from e
        finally:
            sock.close()


def broker_job_table(info):
    """A JobTable proxied through the broker, or None (no broker env,
    broker dead, or no channel to this cluster — caller falls back)."""
    sock_path = os.environ.get(BROKER_SOCK_ENV)
    if not sock_path:
        return None
    from skypilot_tpu.runtime.channel import ChannelJobTable
    proxy = BrokerChannelProxy(sock_path, info)
    try:
        if not proxy.ensure_channel():
            return None
    except BrokerUnavailable as e:
        logger.debug('channel broker unavailable (%s); using direct '
                     'transport', e)
        return None
    return ChannelJobTable(proxy)
