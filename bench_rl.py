#!/usr/bin/env python3
"""Live-sync GRPO rollout pipeline bench: delta weight refresh with
generation running vs stop-the-world weight sync (ISSUE 20).

CPU-only; no cloud credentials. Four arms over the same tiny-model
rollout fleet (2 continuous-batching engines + 1 GRPO learner), each
with its compile/warmup waves off the clock:

1. ``flat_out_ceiling`` — generation only, no learner coupling, no
   weight sync ever: the tokens/s the engines can emit (informational;
   not an acceptance denominator).
2. ``live`` — the real pipeline (``jobs/rl_pipeline.py``): the learner
   commits delta manifests, replicas pull per-shard and swap at a step
   boundary, staggered so generation never stops fleet-wide.
   Weight-sync latency = the sync OPERATION (delta pull + in-place
   swap) on one replica while the rest of the fleet keeps generating.
3. ``no_refresh`` — the same pipeline with refreshes disabled: the
   steady rollout tokens/s denominator for the >=90% claim (same
   learner coupling, no sync cost, unbounded staleness).
4. ``stop_the_world`` — the on-policy baseline every naive RL loop
   ships: on each learner commit the WHOLE fleet halts (in-flight
   waves drain), every replica pulls the FULL weight tree and swaps in
   drain mode, then generation resumes. Weight-sync latency = the
   fleet-wide generation-blocked window per sync.

Acceptance (ISSUE 20): live weight-sync p50 at least 3x better than
stop-the-world; live rollout tokens/s >= 90% of the no-refresh
reference; max consumed staleness <= the max_staleness valve bound.

Emits one JSON document on stdout; run_benches.sh tees it into
``BENCH_rl_<suffix>.json`` and the tables land in PERF.md.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

REPLICAS = 2
STEPS = 24
PROMPTS_PER_STEP = 4
GROUP_SIZE = 2
PROMPT_LEN = 6
MAX_NEW_TOKENS = 48
MAX_STALENESS = 12
QUEUE_BATCHES = 3


def pct(samples, p):
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(p * len(s)))]


def make_engines(cfg, params):
    from skypilot_tpu.inference.continuous import ContinuousBatchingEngine
    return [
        ContinuousBatchingEngine(
            cfg=cfg, params=params,
            max_slots=PROMPTS_PER_STEP * GROUP_SIZE,
            max_len=PROMPT_LEN + MAX_NEW_TOKENS + 1)
        for _ in range(REPLICAS)
    ]


def make_waves(cfg):
    import jax
    import numpy as np
    from skypilot_tpu.train import grpo
    pool, pool_targets = grpo.make_prompts(
        jax.random.key(42), 16, PROMPT_LEN, cfg.vocab_size)
    pool = np.asarray(pool)
    pool_targets = np.asarray(pool_targets)

    def wave(rank, seq):
        p, g = PROMPTS_PER_STEP, GROUP_SIZE
        idx = ((seq * REPLICAS + rank) * p + np.arange(p)) % len(pool)
        return (np.repeat(pool[idx], g, axis=0),
                np.repeat(pool_targets[idx], g), g)

    return wave


def run_wave(engine, tiled, seq, rank):
    from skypilot_tpu.train import grpo
    generated, version = grpo.engine_rollouts(
        engine, [list(map(int, row)) for row in tiled],
        max_new_tokens=MAX_NEW_TOKENS, temperature=1.0,
        step=(seq * 131 + rank))
    return generated, version


def bench_reference(cfg):
    """Arm 1: the fleet generates flat out, no weight sync — the
    steady tokens/s ceiling."""
    import numpy as np
    from skypilot_tpu.train import grpo
    learner = grpo.GrpoLearner(cfg, learning_rate=1e-3)
    engines = make_engines(cfg, learner.params)
    wave = make_waves(cfg)
    tokens = [0] * REPLICAS
    waves_per_replica = STEPS  # comparable wall time to arm 2
    warm = threading.Barrier(REPLICAS + 1)

    def worker(rank):
        tiled, _, _ = wave(rank, 0)
        run_wave(engines[rank], tiled, 0, rank)  # compile, untimed
        warm.wait()
        for seq in range(1, waves_per_replica + 1):
            tiled, _, _ = wave(rank, seq)
            generated, _ = run_wave(engines[rank], tiled, seq, rank)
            tokens[rank] += int(np.asarray(generated).size)

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(REPLICAS)]
    for t in threads:
        t.start()
    warm.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    for e in engines:
        e.shutdown()
    return {'rollout_tokens': sum(tokens),
            'rollout_tokens_per_s': sum(tokens) / elapsed,
            'elapsed_s': round(elapsed, 3)}


WARMUP_STEPS = 2


def timed_pipeline_run(pipe):
    """Drive a built pipeline: consume WARMUP_STEPS off the clock
    (engine + learner jit compiles, the cold first refresh), then time
    STEPS more. Returns (elapsed_s, produced_tokens, summary)."""
    pipe._build()
    for worker in pipe.workers:
        worker.start()
    try:
        done = 0
        while done < WARMUP_STEPS:
            if pipe._consume_one(timeout=30.0):
                done += 1
        tokens0 = sum(w.tokens for w in pipe.workers)
        t0 = time.monotonic()
        done = 0
        while done < STEPS:
            if pipe._consume_one(timeout=30.0):
                done += 1
        elapsed = time.monotonic() - t0
        tokens = sum(w.tokens for w in pipe.workers) - tokens0
    finally:
        for worker in pipe.workers:
            worker.stop()
        for worker in pipe.workers:
            worker.engine.shutdown()
    return elapsed, tokens, pipe.summary(elapsed)


def bench_live(cfg, root):
    """Arm 2: the real pipeline — staggered in-place delta refresh.

    Weight-sync latency here is the sync OPERATION (delta pull + swap
    at the next step boundary): the window one replica spends inside a
    refresh while the rest of the fleet — and this replica's own
    in-flight requests, until the boundary — keep generating. The
    stop-the-world arm's comparable window blocks the whole fleet."""
    from skypilot_tpu.jobs.rl_pipeline import PipelineConfig, RLPipeline

    class _OpLatencyPipeline(RLPipeline):
        def _build(self):
            super()._build()
            for worker in self.workers:
                # Detach the commit-wall map: refresh_latencies then
                # time the pull+swap op itself, comparable to the STW
                # window.
                worker.publish_wall = {}
                # Warm the replica's local copy (the distributed
                # rollout role full-pulls before serving, too): the
                # timed refreshes are deltas, not cold transfers.
                self.store.pull(worker.pull_dest)

    pcfg = PipelineConfig(rollout_replicas=REPLICAS,
                          max_staleness=MAX_STALENESS,
                          queue_batches=QUEUE_BATCHES,
                          refresh_mode='step',
                          refresh_concurrency=1,
                          store=os.path.join(root, 'live'))
    pipe = _OpLatencyPipeline(cfg, pcfg, steps=STEPS,
                      prompts_per_step=PROMPTS_PER_STEP,
                      group_size=GROUP_SIZE, prompt_len=PROMPT_LEN,
                      max_new_tokens=MAX_NEW_TOKENS, num_prompts=16,
                      max_slots=PROMPTS_PER_STEP * GROUP_SIZE)
    elapsed, tokens, summary = timed_pipeline_run(pipe)
    return {
        'rollout_tokens': tokens,
        'rollout_tokens_per_s': tokens / elapsed,
        'elapsed_s': round(elapsed, 3),
        'weight_sync_p50_s': round(summary['refresh_p50_s'], 4),
        'weight_sync_p99_s': round(summary['refresh_p99_s'], 4),
        'refreshes': summary['refreshes'],
        'staleness_max': summary['staleness_max'],
        'staleness_mean': round(summary['staleness_mean'], 3),
        'valve_waits': summary['valve_waits'],
        'batches_unretired': summary['batches_unretired'],
    }


def bench_no_refresh(cfg, root):
    """Arm 2a: the SAME pipeline with weight sync disabled — the
    steady pipeline tokens/s denominator for the >=90%-through-refresh
    claim. (The flat-out arm above is learner-free, so it measures the
    engines, not the pipeline; this arm keeps the learner coupling and
    removes only the syncs.)"""
    from skypilot_tpu.jobs.rl_pipeline import PipelineConfig, RLPipeline

    class _NoRefreshPipeline(RLPipeline):
        def _build(self):
            super()._build()
            for worker in self.workers:
                worker.maybe_refresh = lambda: False

    pcfg = PipelineConfig(rollout_replicas=REPLICAS,
                          max_staleness=10 ** 6,  # never throttle
                          queue_batches=QUEUE_BATCHES,
                          refresh_mode='step',
                          refresh_concurrency=1,
                          store=os.path.join(root, 'noref'))
    pipe = _NoRefreshPipeline(
        cfg, pcfg, steps=STEPS, prompts_per_step=PROMPTS_PER_STEP,
        group_size=GROUP_SIZE, prompt_len=PROMPT_LEN,
        max_new_tokens=MAX_NEW_TOKENS, num_prompts=16,
        max_slots=PROMPTS_PER_STEP * GROUP_SIZE)
    elapsed, tokens, summary = timed_pipeline_run(pipe)
    return {
        'rollout_tokens': tokens,
        'rollout_tokens_per_s': tokens / elapsed,
        'elapsed_s': round(elapsed, 3),
        'staleness_max': summary['staleness_max'],
    }


def bench_stop_the_world(cfg, root):
    """Arm 3: on each commit the whole fleet halts — in-flight waves
    drain, every replica pulls the FULL tree and swaps in drain mode —
    then generation resumes. The sync latency is the fleet-wide
    blocked window."""
    import numpy as np
    from skypilot_tpu.jobs.rl_pipeline import PolicyStore, RolloutQueue
    from skypilot_tpu.train import grpo
    learner = grpo.GrpoLearner(cfg, learning_rate=1e-3)
    store = PolicyStore(os.path.join(root, 'stw'))
    store.publish(learner.params, learner.version)
    engines = make_engines(cfg, learner.params)
    wave = make_waves(cfg)
    queue = RolloutQueue(capacity=QUEUE_BATCHES)
    halt = threading.Event()       # set = generation must stop
    resume = threading.Event()
    resume.set()
    idle = [threading.Event() for _ in range(REPLICAS)]
    stop = threading.Event()
    tokens = [0] * REPLICAS

    def reward(generated, targets):
        import jax.numpy as jnp
        return np.asarray(grpo.reward_fn(jnp.asarray(generated),
                                         jnp.asarray(targets)))

    def worker(rank):
        from skypilot_tpu.jobs.rl_pipeline import RolloutBatch
        seq = 0
        pending = None
        while not stop.is_set():
            if halt.is_set():
                # Mid-put batches are held, not dropped: the worker
                # parks idle and finishes the hand-off after resume.
                idle[rank].set()
                resume.wait(timeout=0.5)
                continue
            idle[rank].clear()
            if pending is None:
                tiled, targets, g = wave(rank, seq)
                generated, version = run_wave(engines[rank], tiled,
                                              seq, rank)
                tokens[rank] += int(np.asarray(generated).size)
                pending = RolloutBatch(
                    prompts=np.asarray(tiled, np.int32),
                    generated=np.asarray(generated, np.int32),
                    rewards=reward(generated, targets), group_size=g,
                    policy_version=int(version), rank=rank, seq=seq)
                seq += 1
            if queue.put(pending, timeout=0.2):
                pending = None

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(REPLICAS)]
    for t in threads:
        t.start()
    sync_latencies = []
    staleness = []
    t0 = None
    for step in range(WARMUP_STEPS + STEPS):
        if step == WARMUP_STEPS:
            # Clock starts after the compile-heavy warmup steps, same
            # as the pipeline arms.
            sync_latencies.clear()
            staleness.clear()
            for rank in range(REPLICAS):
                tokens[rank] = 0
            t0 = time.monotonic()
        batch = queue.pop(timeout=120)
        assert batch is not None, 'stop-the-world learner starved'
        consumed_at = learner.version
        learner.learn_rollouts(batch.prompts, batch.generated,
                               batch.rewards, batch.group_size)
        staleness.append(max(0, consumed_at - batch.policy_version))
        queue.ack(batch)
        store.publish(learner.params, learner.version)
        # THE stop-the-world window: halt, drain, full pull, swap.
        sync_t0 = time.monotonic()
        resume.clear()
        halt.set()
        for flag in idle:
            flag.wait(timeout=120)
        for rank, engine in enumerate(engines):
            dest = os.path.join(root, 'stw', f'replica-{rank}')
            shutil.rmtree(dest, ignore_errors=True)  # full, not delta
            pulled = store.pull(dest)
            engine.refresh_weights(pulled['updates'],
                                   version=pulled['version'],
                                   mode='drain')
        halt.clear()
        resume.set()
        sync_latencies.append(time.monotonic() - sync_t0)
    stop.set()
    resume.set()
    for t in threads:
        t.join(timeout=30)
    elapsed = time.monotonic() - t0
    for e in engines:
        e.shutdown()
    return {
        'rollout_tokens': sum(tokens),
        'rollout_tokens_per_s': sum(tokens) / elapsed,
        'elapsed_s': round(elapsed, 3),
        'weight_sync_p50_s': round(pct(sync_latencies, 0.50), 4),
        'weight_sync_p99_s': round(pct(sync_latencies, 0.99), 4),
        'syncs': len(sync_latencies),
        'staleness_max': max(staleness, default=0),
    }


def main() -> int:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    from skypilot_tpu.models.config import get_model_config
    cfg = get_model_config('tiny')
    root = tempfile.mkdtemp(prefix='skyt-bench-rl-')
    try:
        print('arm 1/4: flat-out generation ceiling',
              file=sys.stderr)
        reference = bench_reference(cfg)
        print('arm 2/4: live delta refresh (the pipeline)',
              file=sys.stderr)
        live = bench_live(cfg, root)
        print('arm 3/4: pipeline with sync disabled (steady '
              'denominator)', file=sys.stderr)
        no_refresh = bench_no_refresh(cfg, root)
        print('arm 4/4: stop-the-world sync baseline', file=sys.stderr)
        stw = bench_stop_the_world(cfg, root)
    finally:
        shutil.rmtree(root, ignore_errors=True)

    sync_speedup = (stw['weight_sync_p50_s'] /
                    max(live['weight_sync_p50_s'], 1e-9))
    # Steady throughput: the learner consumes faster than the fleet
    # produces (post-warmup), so both pipeline arms are rollout-bound
    # and produced tokens/s IS the fleet's steady generation rate —
    # with refreshes interleaved (live) vs without (no_refresh).
    throughput_fraction = (live['rollout_tokens_per_s'] /
                           max(no_refresh['rollout_tokens_per_s'],
                               1e-9))
    doc = {
        'bench': 'rl_pipeline',
        'config': {'replicas': REPLICAS, 'steps': STEPS,
                   'prompts_per_step': PROMPTS_PER_STEP,
                   'group_size': GROUP_SIZE, 'prompt_len': PROMPT_LEN,
                   'max_new_tokens': MAX_NEW_TOKENS,
                   'max_staleness': MAX_STALENESS, 'model': 'tiny'},
        'flat_out_ceiling': reference,
        'live': live,
        'no_refresh': no_refresh,
        'stop_the_world': stw,
        'acceptance': {
            'weight_sync_p50_speedup': round(sync_speedup, 2),
            'weight_sync_p50_speedup_ok': sync_speedup >= 3.0,
            'throughput_fraction_of_no_refresh':
                round(throughput_fraction, 4),
            'throughput_fraction_ok': throughput_fraction >= 0.9,
            'staleness_bounded':
                live['staleness_max'] <= MAX_STALENESS,
        },
    }
    print(json.dumps(doc, indent=2))
    ok = doc['acceptance']
    return 0 if (ok['weight_sync_p50_speedup_ok'] and
                 ok['throughput_fraction_ok'] and
                 ok['staleness_bounded']) else 1


if __name__ == '__main__':
    raise SystemExit(main())
