"""Client-server tests: SDK -> HTTP -> executor -> core ops, in-process
server (the reference tests its API server with FastAPI's testclient via
``mock_client_requests``, tests/common_test_fixtures.py:58; here the real
HTTP server runs on a loopback port with the real process-pool executor)."""
import io
import os
import time

import pytest

from skypilot_tpu import exceptions, state
from skypilot_tpu.client import sdk
from skypilot_tpu.provision import fake
from skypilot_tpu.server import requests_db
from skypilot_tpu.server.app import ApiServer
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task


@pytest.fixture()
def server(tmp_home, monkeypatch):
    fake.reset()
    requests_db.reset_db_for_tests()
    srv = ApiServer(port=0)  # ephemeral port
    srv.start_background()
    monkeypatch.setenv('SKYT_API_SERVER_URL', srv.url)
    yield srv
    srv.shutdown()
    requests_db.reset_db_for_tests()
    fake.reset()


def _tpu_task(run='echo hi', accel='tpu-v5e-8', **kw):
    return Task(name='t', run=run,
                resources=Resources(cloud='fake', accelerators=accel), **kw)


def test_health_and_autostart_detection(server):
    assert sdk.api_is_healthy()
    assert sdk.ensure_api_server() == server.url


def test_launch_via_sdk_async_contract(server):
    request_id = sdk.launch(_tpu_task(
        'echo "rank=$TPU_WORKER_ID"'), 'api-e2e')
    # Submission returns immediately with an id; get() blocks to the result.
    assert isinstance(request_id, str) and len(request_id) == 32
    result = sdk.get(request_id, timeout=120)
    assert result == [['api-e2e', 1]]

    # Cluster is UP server-side; status round-trips through the SHORT queue.
    records = sdk.get(sdk.status(), timeout=60)
    assert [r['name'] for r in records] == ['api-e2e']
    assert records[0]['status'] == 'UP'

    # Job queue + logs through the server.
    jobs = sdk.get(sdk.queue('api-e2e'), timeout=60)
    assert jobs[0]['status'] == 'SUCCEEDED'
    buf = io.StringIO()
    sdk.stream_and_get(sdk.tail_logs('api-e2e', 1), output=buf)
    assert 'rank=0' in buf.getvalue()

    sdk.get(sdk.down('api-e2e'), timeout=60)
    assert sdk.get(sdk.status(), timeout=60) == []


def test_request_failure_propagates(server):
    request_id = sdk.queue('no-such-cluster')
    with pytest.raises(exceptions.RequestFailedError) as err:
        sdk.get(request_id, timeout=60)
    assert 'no-such-cluster' in str(err.value)


def test_provision_logs_streamed(server):
    request_id = sdk.launch(_tpu_task(), 'stream-e2e')
    buf = io.StringIO()
    result = sdk.stream_and_get(request_id, output=buf)
    assert result == [['stream-e2e', 1]]
    # Provisioning progress from the worker process reached the client.
    assert 'stream-e2e' in buf.getvalue()


def test_cancel_pending_request(server, monkeypatch):
    # Block the LONG queue with a slow fault so the next request stays
    # PENDING long enough to cancel.
    fake.inject_slow_create(3)
    first = sdk.launch(_tpu_task(), 'slow-1')
    time.sleep(0.3)
    second = sdk.launch(_tpu_task(), 'slow-2')
    # Cancel the second while queued or early-running.
    assert sdk.api_cancel(second)
    with pytest.raises(exceptions.RequestCancelledError):
        sdk.get(second, timeout=30)
    fake.clear_faults()
    sdk.get(first, timeout=120)


def test_request_id_prefix_lookup(server):
    request_id = sdk.status()
    sdk.get(request_id, timeout=60)
    short = request_id[:12]
    assert sdk.get(short, timeout=60) is not None


def test_workdir_upload_content_addressed(server, tmp_path):
    workdir = tmp_path / 'proj'
    workdir.mkdir()
    (workdir / 'data.txt').write_text('uploaded-data')
    task = _tpu_task('cat data.txt', workdir=str(workdir))
    result = sdk.stream_and_get(sdk.launch(task, 'up-e2e'),
                                output=io.StringIO())
    assert result == [['up-e2e', 1]]
    buf = io.StringIO()
    sdk.stream_and_get(sdk.tail_logs('up-e2e', 1), output=buf)
    assert 'uploaded-data' in buf.getvalue()


# r20 triage: 29s of streaming a synthetic GB; bounded-memory logic is
# also pinned by the smaller upload tests
@pytest.mark.slow
def test_large_upload_streams_with_bounded_memory(server, tmp_path):
    """VERDICT r3 weak #3: the server buffered the whole upload body in
    RAM. A >256 MB workdir must now stream through spool files on both
    ends with O(chunk) memory growth, and a repeat upload must be
    skipped entirely via the digest probe."""
    import psutil
    workdir = tmp_path / 'big'
    workdir.mkdir()
    # Incompressible payload so the tarball really is >256 MB on the wire.
    with open(workdir / 'blob.bin', 'wb') as f:
        for _ in range(260):
            f.write(os.urandom(1 << 20))
    proc = psutil.Process()
    rss_before = proc.memory_info().rss
    cfg = sdk._upload_workdir({'workdir': str(workdir)})
    rss_growth = proc.memory_info().rss - rss_before
    assert rss_growth < 32 * (1 << 20), (
        f'upload ballooned RSS by {rss_growth >> 20} MiB')
    extracted = cfg['workdir']
    assert os.path.getsize(os.path.join(extracted, 'blob.bin')) == 260 << 20

    # Second upload of identical content: the digest probe must answer
    # before any body is sent and resolve to the same extracted path.
    cfg2 = sdk._upload_workdir({'workdir': str(workdir)})
    assert cfg2['workdir'] == extracted


def test_serve_endpoints_roundtrip(server):
    # No services yet.
    assert sdk.get(sdk.serve_status()) == []
    # Unknown service errors propagate through the executor.
    with pytest.raises(exceptions.RequestFailedError):
        sdk.get(sdk.serve_down('nope'))


def test_legacy_truncated_digest_upload_aliased(server, tmp_path):
    """Upload back-compat (ADVICE r5 low): a pre-upgrade client
    claiming the 16-char X-Skyt-Digest gets its content stored under
    the FULL digest (no new objects accumulate in the legacy 64-bit
    address space) with a short-form alias, so its next probe on the
    truncated digest still hits."""
    import hashlib
    import json
    import tarfile
    import urllib.request
    workdir = tmp_path / 'legacy'
    workdir.mkdir()
    (workdir / 'f.txt').write_text('legacy-content')
    tar_path = tmp_path / 'w.tar.gz'
    with tarfile.open(tar_path, 'w:gz') as tar:
        tar.add(workdir, arcname='.')
    body = tar_path.read_bytes()
    digest = hashlib.sha256(body).hexdigest()
    req = urllib.request.Request(
        f'{server.url}/upload', data=body, method='POST',
        headers={'X-Skyt-Digest': digest[:16]})
    with urllib.request.urlopen(req, timeout=30) as resp:
        reply = json.loads(resp.read())
    assert reply['workdir_token'] == digest
    assert reply['path'].endswith(digest)
    assert os.path.isdir(reply['path'])
    # The legacy short probe resolves through the alias...
    with urllib.request.urlopen(
            f'{server.url}/upload/{digest[:16]}', timeout=10) as resp:
        probe = json.loads(resp.read())
    assert probe['exists']
    # ...as does the full-digest probe.
    with urllib.request.urlopen(
            f'{server.url}/upload/{digest}', timeout=10) as resp:
        assert json.loads(resp.read())['exists']
