"""Recipes registry: every shipped recipe parses, and representative ones
launch end-to-end on the fake cloud (parity: the reference's recipes are
exercised by real-cloud smoke tests; here the fake cloud runs the
payloads as local processes)."""
import json

import pytest

from skypilot_tpu import core, execution, recipes
from skypilot_tpu.provision import fake
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task


@pytest.fixture(autouse=True)
def fresh(tmp_home):
    fake.reset()
    yield
    fake.reset()


def test_registry_lists_all_recipes():
    names = {r['name'] for r in recipes.list_recipes()}
    assert {'pretrain-1b7', 'pretrain-llama3-8b', 'serve-llm',
            'grpo-spot', 'collective-bench', 'longcontext-ring'} <= names
    for r in recipes.list_recipes():
        assert r['description'], f"recipe {r['name']} has no description"


def test_every_recipe_parses_as_task():
    from skypilot_tpu.spec.dag import Dag
    for r in recipes.list_recipes():
        # Recipes may be single tasks or multi-document pipelines
        # (chains / fan-out graphs); both load through Dag.from_yaml.
        dag = Dag.from_yaml(recipes.resolve(f"recipe://{r['name']}"))
        for task in dag.tasks:
            assert task.run, (f"recipe {r['name']} task "
                              f"{task.name!r} has no run command")
        assert any(t.resources[0].accelerators is not None
                   for t in dag.tasks), (
            f"recipe {r['name']} requests no accelerators anywhere")


def test_resolve_unknown_recipe():
    with pytest.raises(FileNotFoundError, match='pretrain-1b7'):
        recipes.resolve('recipe://no-such-recipe')


def test_serve_recipe_has_valid_service_spec():
    from skypilot_tpu.serve.service_spec import ServiceSpec
    task = Task.from_yaml('recipe://serve-llm')
    spec = ServiceSpec.from_yaml_config(task.service)
    assert spec.readiness_path == '/health'
    assert spec.port == 8080
    assert spec.max_replicas == 3


def test_collective_bench_recipe_launches_on_fake_cloud():
    task = Task.from_yaml('recipe://collective-bench')
    # shrink the payload for CI; drop the pip-install setup
    task.run = task.run.replace('--op all --size-mb 256',
                                '--op all_reduce --size-mb 2 --iters 2')
    task.setup = None
    task.storage_mounts = {}
    task.resources = [Resources(cloud='fake',
                                accelerators='tpu-v5e-8')]
    execution.launch(task, cluster_name='cb')
    jobs = core.queue('cb')
    assert jobs[0]['status'] == 'SUCCEEDED'
    log = core.tail_logs('cb', jobs[0]['job_id'])
    line = next(l for l in log.splitlines()
                if l.startswith('{') and 'collective_all_reduce' in l)
    result = json.loads(line)
    assert result['value'] > 0
    assert result['detail']['devices'] >= 1


# r20 triage: 14s end-to-end launch; the collective-bench recipe launch
# keeps the fake-cloud e2e path in tier 1
@pytest.mark.slow
def test_pretrain_recipe_launches_tiny_on_fake_cloud(tmp_path):
    task = Task.from_yaml('recipe://pretrain-1b7')
    ckpt = tmp_path / 'ckpt'
    task.run = ('python3 -m skypilot_tpu.train.pretrain --model tiny '
                f'--steps 4 --batch 2 --seq 32 --log-every 2 '
                f'--checkpoint-dir {ckpt} --checkpoint-every 4')
    task.setup = None
    task.storage_mounts = {}
    task.resources = [Resources(cloud='fake', accelerators='tpu-v5e-8')]
    execution.launch(task, cluster_name='pt')
    jobs = core.queue('pt')
    assert jobs[0]['status'] == 'SUCCEEDED', core.tail_logs('pt', 1)
    log = core.tail_logs('pt', jobs[0]['job_id'])
    assert '"done": true' in log
    from skypilot_tpu.train import checkpoint as ckpt_lib
    assert ckpt_lib.latest_step(str(ckpt)) == 4
