"""GCP provider against a stubbed REST transport.

Parity bars: ``sky/provision/gcp/instance_utils.py`` (TPU-VM + GCE
lifecycle), ``sky/provision/gcp/config.py`` (network/firewall/key
bootstrap). The fake transport simulates the TPU + Compute REST APIs in a
dict (moto-style, per SURVEY §4's test-strategy implication) so create /
stop / start / terminate round-trips, key injection, and the zone=None
guard are all unit-testable offline.
"""
import re

import pytest

from skypilot_tpu import exceptions, state
from skypilot_tpu.provision import gcp
from skypilot_tpu.provision.api import ProvisionRequest
from skypilot_tpu.spec.resources import Resources


class FakeGcp(gcp.GcpTpuProvider):
    """Transport stub: answers TPU/Compute REST calls from in-memory
    dicts and records every (method, url) for assertions."""

    def __init__(self):
        super().__init__(project='proj')
        self.calls = []
        self.qrs = {}         # qr_id -> state
        self.qr_specs = {}    # qr_id -> creation body (tpu.nodeSpec)
        self.nodes = {}       # node_id -> node dict
        self.instances = {}   # name -> instance dict
        self.firewalls = {}
        self.has_default_net = True

    def _request(self, method, url, body=None):
        self.calls.append((method, url))
        assert 'None' not in url, f'unresolved zone/project in URL: {url}'
        # --- compute: networks/firewalls ---
        if '/global/networks/' in url:
            name = url.rsplit('/', 1)[1]
            if method == 'GET':
                if name == 'default' and self.has_default_net:
                    return {'name': 'default'}
                raise exceptions.ProvisionError(f'404 {name} not found')
        if url.endswith('/global/networks') and method == 'POST':
            return {'name': body['name']}
        if '/global/firewalls' in url:
            name = url.rsplit('/', 1)[1]
            if method == 'GET':
                if name in self.firewalls:
                    return self.firewalls[name]
                raise exceptions.ProvisionError(f'404 {name} not found')
            if method == 'POST':
                self.firewalls[body['name']] = body
                return body
            if method == 'DELETE':
                self.firewalls.pop(name, None)
                return {}
        # --- tpu: queued resources ---
        m = re.search(r'queuedResources\?queuedResourceId=([\w-]+)$', url)
        if m and method == 'POST':
            qr_id = m.group(1)
            self.qrs[qr_id] = 'ACTIVE'
            self.qr_specs[qr_id] = body
            spec = body['tpu']['nodeSpec'][0]
            self.nodes[qr_id] = {
                'name': f'projects/proj/locations/z/nodes/{qr_id}',
                'state': 'READY',
                'labels': spec['node']['labels'],
                'metadata': spec['node']['metadata'],
                'networkEndpoints': [
                    {'ipAddress': '10.0.0.1',
                     'accessConfig': {'externalIp': '34.1.2.3'}},
                    {'ipAddress': '10.0.0.2',
                     'accessConfig': {'externalIp': '34.1.2.4'}},
                ],
            }
            return {}
        m = re.search(r'queuedResources/([\w-]+)$', url)
        if m and method == 'GET':
            return {'state': {'state': self.qrs[m.group(1)]}}
        if url.endswith('/queuedResources') and method == 'GET':
            # Real list responses carry the full QueuedResource object
            # including tpu.nodeSpec (and its labels), not just the name.
            return {'queuedResources': [
                {'name': f'projects/proj/locations/z/queuedResources/{q}',
                 **self.qr_specs[q]}
                for q in self.qrs]}
        if 'queuedResources/' in url and method == 'DELETE':
            qr_id = url.split('queuedResources/')[1].split('?')[0]
            self.qrs.pop(qr_id, None)
            self.qr_specs.pop(qr_id, None)
            self.nodes.pop(qr_id, None)
            return {}
        # --- tpu: nodes ---
        if url.endswith('/nodes') and method == 'GET':
            return {'nodes': list(self.nodes.values())}
        m = re.search(r'nodes/([\w-]+):(\w+)$', url)
        if m and method == 'POST':
            node_id, verb = m.groups()
            self.nodes[node_id]['state'] = (
                'STOPPED' if verb == 'stop' else 'READY')
            return {}
        # --- compute: instances ---
        if url.rstrip('/').endswith('/instances') and method == 'POST':
            self.instances[body['name']] = {**body, 'status': 'RUNNING',
                                            'networkInterfaces': [{
                                                'networkIP': '10.0.1.5',
                                                'accessConfigs': [
                                                    {'natIP': '34.9.9.9'}],
                                            }]}
            return {}
        if '/instances?filter=' in url and method == 'GET':
            return {'items': list(self.instances.values())}
        m = re.search(r'instances/([\w-]+)/(stop|start)$', url)
        if m and method == 'POST':
            name, verb = m.groups()
            self.instances[name]['status'] = (
                'TERMINATED' if verb == 'stop' else 'RUNNING')
            return {}
        m = re.search(r'instances/([\w-]+)$', url)
        if m and method == 'DELETE':
            self.instances.pop(m.group(1), None)
            return {}
        raise AssertionError(f'unhandled fake call: {method} {url}')


@pytest.fixture()
def provider(tmp_home, monkeypatch):
    monkeypatch.setattr(
        gcp, 'ensure_ssh_keypair',
        lambda: ('/fake/key', 'ssh-ed25519 AAAA fake'))
    gcp.GcpTpuProvider._bootstrapped_projects = {}
    return FakeGcp()


def _tpu_request(name='c1', accel='tpu-v5e-8', **kw):
    return ProvisionRequest(
        cluster_name=name,
        resources=Resources(cloud='gcp', accelerators=accel, **kw),
        num_nodes=1, region='us-central2', zone='us-central2-b')


def _record(name='c1', zone='us-central2-b'):
    state.add_or_update_cluster(name=name,
                                status=state.ClusterStatus.INIT,
                                cloud='gcp', region='us-central2',
                                zone=zone)


def test_tpu_create_injects_ssh_key_and_network(provider, tmp_home):
    _record()
    info = provider.run_instances(_tpu_request())
    node = provider.nodes['c1-n0-s0']
    assert node['metadata']['ssh-keys'] == 'skyt:ssh-ed25519 AAAA fake'
    assert info.ssh_user == 'skyt'
    assert info.ssh_key_path == gcp.ssh_key_path()
    assert len(info.hosts) == 2  # one per networkEndpoint (worker)
    assert info.hosts[0].internal_ip == '10.0.0.1'
    # bootstrap probed default net and created the ssh firewall rule
    assert 'skyt-allow-ssh' in provider.firewalls


def test_stop_start_roundtrip(provider, tmp_home):
    _record()
    provider.run_instances(_tpu_request())
    provider.stop_instances('c1')
    assert provider.query_instances('c1') == {'c1-n0-s0': 'stopped'}
    provider.run_instances(
        ProvisionRequest(cluster_name='c1',
                         resources=Resources(cloud='gcp',
                                             accelerators='tpu-v5e-8'),
                         num_nodes=1, region='us-central2',
                         zone='us-central2-b', resume=True))
    assert provider.query_instances('c1') == {'c1-n0-s0': 'running'}


def test_stop_without_zone_is_guarded(provider, tmp_home):
    # No cluster record at all: must not build a locations/None URL
    # (VERDICT r1 weak #4); the fake asserts 'None' never appears.
    provider.stop_instances('ghost')
    assert provider.calls == []


def test_cpu_instance_create_for_controller_vm(provider, tmp_home):
    _record('ctrl')
    req = ProvisionRequest(
        cluster_name='ctrl',
        resources=Resources(cloud='gcp', cpus=4),
        num_nodes=1, region='us-central2', zone='us-central2-b')
    info = provider.run_instances(req)
    inst = provider.instances['ctrl-n0']
    assert inst['machineType'].endswith('e2-standard-4')
    meta = {i['key']: i['value'] for i in inst['metadata']['items']}
    assert meta['ssh-keys'] == 'skyt:ssh-ed25519 AAAA fake'
    assert info.hosts[0].external_ip == '34.9.9.9'
    provider.terminate_instances('ctrl')
    assert provider.instances == {}


def test_terminate_spares_prefix_sibling_cluster(provider, tmp_home):
    # VERDICT r3 weak #5: teardown matched QRs by name prefix, so
    # terminating cluster 'a' deleted cluster 'a-n1''s QR 'a-n1-n0-s0'
    # ('a-n1-n0-s0'.startswith('a-n')). The label filter must not.
    _record('a')
    _record('a-n1')
    provider.run_instances(_tpu_request('a'))
    provider.run_instances(_tpu_request('a-n1'))
    assert set(provider.qrs) == {'a-n0-s0', 'a-n1-n0-s0'}
    provider.terminate_instances('a')
    assert set(provider.qrs) == {'a-n1-n0-s0'}
    assert provider.query_instances('a-n1') == {'a-n1-n0-s0': 'running'}


def test_terminate_cleans_up_port_firewall(provider, tmp_home):
    _record()
    req = _tpu_request()
    req.ports = ['8080']
    provider.run_instances(req)
    assert 'skyt-c1-ports' in provider.firewalls
    provider.terminate_instances('c1')
    assert 'skyt-c1-ports' not in provider.firewalls
    assert provider.qrs == {}


def test_bootstrap_creates_net_when_no_default(provider, tmp_home):
    provider.has_default_net = False
    _record()
    provider.run_instances(_tpu_request())
    posted = [(m, u) for m, u in provider.calls
              if m == 'POST' and u.endswith('/global/networks')]
    assert posted, 'skyt-net creation expected when default VPC is absent'
    node = provider.nodes['c1-n0-s0']
    # nodes join the created network
    assert provider._network == 'skyt-net'


def test_spot_tpu_sets_spot_flag(provider, tmp_home):
    _record('sp')
    req = ProvisionRequest(
        cluster_name='sp',
        resources=Resources(cloud='gcp', accelerators='tpu-v5e-8',
                            use_spot=True),
        num_nodes=1, region='us-central2', zone='us-central2-b')
    provider.run_instances(req)
    # the fake records the QR body only via nodes; assert via calls
    assert any('queuedResources?queuedResourceId=sp-n0-s0' in u
               for _, u in provider.calls)
