"""Every shipped example YAML parses, schema-validates, and (where its
cloud exists in the catalog) plans to a concrete candidate. Parity: the
reference's examples/ are exercised by smoke tests; here parse+plan is
the offline equivalent."""
import glob
import os

import pytest

from skypilot_tpu import optimizer
from skypilot_tpu.spec import schemas
from skypilot_tpu.spec.dag import Dag
from skypilot_tpu.spec.task import Task

EXAMPLES_DIR = os.path.join(os.path.dirname(__file__), '..', 'examples')
EXAMPLE_PATHS = sorted(glob.glob(os.path.join(EXAMPLES_DIR, '*.yaml')))


def test_examples_exist():
    assert len(EXAMPLE_PATHS) >= 10


@pytest.mark.parametrize('path', EXAMPLE_PATHS,
                         ids=[os.path.basename(p) for p in EXAMPLE_PATHS])
def test_example_parses_and_validates(path):
    dag = Dag.from_yaml(path)
    for task in dag.tasks:
        assert task.run, f'{path}: no run section'
    # First comment line is the doc line (recipes registry convention).
    with open(path, encoding='utf-8') as f:
        assert f.readline().startswith('# '), f'{path}: missing doc comment'


@pytest.mark.parametrize('path', [
    p for p in EXAMPLE_PATHS
    if os.path.basename(p) in ('minimal.yaml', 'multinode-jax.yaml',
                               'tpu-pod-v5e-32.yaml',
                               'spot-pretrain-recovery.yaml')
], ids=os.path.basename)
def test_example_plans(path, tmp_home):
    """Catalog-backed examples produce at least one launchable candidate."""
    task = Task.from_yaml(path)
    candidates = optimizer.Optimizer.plan_task(task)
    assert candidates, f'{path}: optimizer found no candidates'
