"""`skyt api login` + log-shipping daemon tests.

Parity: ``sky api login`` (client/oauth.py token flow) and
``sky/logs/__init__.py:12`` get_logging_agent (external log stores).
"""
import os
import time

import pytest
from click.testing import CliRunner

from skypilot_tpu import config, execution, state
from skypilot_tpu.client import cli, sdk
from skypilot_tpu.provision import fake
from skypilot_tpu.server import daemons, requests_db
from skypilot_tpu.server.app import ApiServer
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task


@pytest.fixture()
def server(tmp_home, monkeypatch):
    fake.reset()
    requests_db.reset_db_for_tests()
    srv = ApiServer(port=0)
    srv.start_background()
    monkeypatch.setenv('SKYT_API_SERVER_URL', srv.url)
    yield srv
    srv.shutdown()
    requests_db.reset_db_for_tests()
    fake.reset()


def test_api_login_stores_endpoint_and_token(server, monkeypatch):
    monkeypatch.setenv('SKYT_API_SERVER_TOKEN', 'corp-token')
    result = CliRunner().invoke(
        cli.cli, ['api', 'login', '-e', server.url, '-t', 'corp-token'])
    assert result.exit_code == 0, result.output
    assert 'Logged in' in result.output
    assert config.get_nested(('api_server', 'endpoint')) == server.url
    assert config.get_nested(('api_server', 'token')) == 'corp-token'
    # With the env var gone, the SDK resolves the configured endpoint.
    monkeypatch.delenv('SKYT_API_SERVER_URL')
    assert sdk.api_server_url() == server.url


def test_api_login_rejects_bad_token(server, monkeypatch):
    monkeypatch.setenv('SKYT_API_SERVER_TOKEN', 'corp-token')
    result = CliRunner().invoke(
        cli.cli, ['api', 'login', '-e', server.url, '-t', 'wrong'])
    assert result.exit_code != 0
    assert 'rejected' in result.output


def test_log_shipper_ships_terminal_job_logs_once(tmp_home):
    fake.reset()
    sink = os.path.join(str(tmp_home), 'log-sink')
    config.set_nested(('logs',), {'store': f'file://{sink}'})
    task = Task(name='t', run='echo ship-me-please',
                resources=Resources(cloud='fake', accelerators='tpu-v5e-8'))
    execution.launch(task, 'ship-c')

    daemons._log_ship_tick()  # noqa: SLF001
    shipped = os.path.join(sink, 'skyt-logs', 'ship-c', 'job-1.log')
    assert os.path.exists(shipped), os.listdir(sink)
    with open(shipped, encoding='utf-8') as f:
        assert 'ship-me-please' in f.read()

    # Second tick is a no-op (manifest de-dupe): truncate the shipped
    # file and confirm it is not re-uploaded.
    with open(shipped, 'w', encoding='utf-8') as f:
        f.write('tombstone')
    daemons._log_ship_tick()  # noqa: SLF001
    with open(shipped, encoding='utf-8') as f:
        assert f.read() == 'tombstone'

    from skypilot_tpu import core
    core.down('ship-c')
    fake.reset()


def test_log_shipper_noop_without_config(tmp_home):
    daemons._log_ship_tick()  # noqa: SLF001  (must not raise)
