"""Job-group tests: gang provisioning barrier, cross-task host env,
gang cancellation.

Parity: ``sky/jobs/job_group_networking.py:118-217`` (gang-scheduled
multi-task groups + cross-task networking).
"""
import time

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs import job_groups
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.provision import fake
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task


@pytest.fixture(autouse=True)
def fast_controller(tmp_home, monkeypatch):
    monkeypatch.setenv('SKYT_JOBS_CONTROLLER_POLL', '0.2')
    monkeypatch.setenv('SKYT_JOBS_LAUNCH_RETRY_GAP', '0.2')
    monkeypatch.setenv('SKYT_JOBGROUP_BARRIER_TIMEOUT', '90')
    fake.reset()
    yield
    fake.reset()


def _member(name, run):
    return Task(name=name, run=run,
                resources=Resources(cloud='fake',
                                    accelerators='tpu-v5e-8'))


def _wait(job_id, statuses, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        record = jobs_state.get(job_id)
        if record and record.status.value in statuses:
            return record
        time.sleep(0.2)
    record = jobs_state.get(job_id)
    raise AssertionError(
        f'job {job_id} stuck in '
        f'{record.status.value if record else None}; wanted {statuses}. '
        f'Controller log:\n'
        + jobs_core.tail_logs(job_id, controller=True)[-3000:])


def test_group_members_see_each_other():
    """Both members run with SKYT_JOBGROUP + sibling host env vars."""
    tasks = [
        _member('alpha', 'echo "alpha sees beta at '
                         '$SKYT_JOBGROUP_HOSTS_BETA in $SKYT_JOBGROUP"'),
        _member('beta', 'echo "beta sees alpha at '
                        '$SKYT_JOBGROUP_HOSTS_ALPHA"'),
    ]
    job_ids = jobs_core.launch_group(tasks, 'duo')
    assert len(job_ids) == 2
    for job_id in job_ids:
        record = _wait(job_id, {'SUCCEEDED'})
        assert record.group_name == 'duo'
        assert record.group_hosts  # published at the barrier
    alpha_log = jobs_core.tail_logs(job_ids[0], controller=True)
    assert 'gang' not in (jobs_state.get(job_ids[0]).failure_reason or '')
    del alpha_log


def test_group_validation():
    with pytest.raises(exceptions.InvalidSpecError):
        jobs_core.launch_group([_member('solo', 'true')], 'g')
    with pytest.raises(exceptions.InvalidSpecError):
        jobs_core.launch_group(
            [_member('dup', 'true'), _member('dup', 'true')], 'g')


def test_sibling_failure_gang_cancels():
    """One member fails -> the long-running sibling is cancelled."""
    tasks = [
        _member('worker', 'sleep 120'),
        _member('crasher', 'sleep 1 && exit 7'),
    ]
    job_ids = jobs_core.launch_group(tasks, 'doomed')
    crasher = _wait(job_ids[1], {'FAILED'})
    assert crasher.status == jobs_state.ManagedJobStatus.FAILED
    worker = _wait(job_ids[0], {'CANCELLED'}, timeout=120)
    assert 'gang' in (worker.failure_reason or '')


def test_barrier_aborts_when_member_cannot_provision(monkeypatch):
    """Member B's provisioning fails outright -> member A is released
    from the barrier with a gang abort, not a hang."""
    monkeypatch.setenv('SKYT_JOBS_MAX_LAUNCH_RETRIES', '1')
    bad = Task(name='bad', run='true',
               resources=Resources(cloud='fake',
                                   accelerators='tpu-v5e-8',
                                   region='nonexistent-region'))
    good = _member('good', 'sleep 60')
    job_ids = jobs_core.launch_group([good, bad], 'halfbaked')
    _wait(job_ids[1], {'FAILED_NO_RESOURCE', 'FAILED_SETUP'})
    released = _wait(job_ids[0], {'CANCELLED'}, timeout=120)
    assert released.status == jobs_state.ManagedJobStatus.CANCELLED


def test_env_key_sanitization():
    assert job_groups._env_key('my-task.v2', 1) == (  # noqa: SLF001
        'SKYT_JOBGROUP_HOSTS_MY_TASK_V2')
    assert job_groups._env_key(None, 7) == 'SKYT_JOBGROUP_HOSTS_JOB7'
