"""SSH node-pool provider tests: BYO machines as a provision target.

Parity: ``sky/ssh_node_pools/`` + ``sky/provision/ssh/``. The "remote"
hosts are the tests/fake_bin ssh/rsync shims (as in test_ssh_runtime) so
the full SSH cluster path — runtime shipping, remote daemon, detached
queue — runs against inventory-declared hosts.
"""
import json
import os
import time

import pytest
import yaml

from skypilot_tpu import check, core, exceptions, execution, state
from skypilot_tpu.provision import ssh_pool
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task

_FAKE_BIN = os.path.join(os.path.dirname(__file__), 'fake_bin')

_POOL_IPS = ['10.9.0.1', '10.9.0.2', '10.9.0.3']


@pytest.fixture(autouse=True)
def ssh_pool_env(tmp_home, monkeypatch):
    state_dir = os.environ['SKYT_STATE_DIR']
    os.makedirs(state_dir, exist_ok=True)
    inventory = os.path.join(state_dir, 'ssh_node_pools.yaml')
    with open(inventory, 'w', encoding='utf-8') as f:
        yaml.safe_dump({
            'lab': {'user': 'skyt', 'hosts': _POOL_IPS},
        }, f)
    # Map the inventory IPs onto private host roots for the ssh shim.
    map_path = os.path.join(state_dir, 'fake_ssh_map.json')
    roots = {}
    for i, ip in enumerate(_POOL_IPS):
        root = os.path.join(state_dir, 'ssh_hosts', f'host{i}')
        os.makedirs(root, exist_ok=True)
        roots[ip] = root
    with open(map_path, 'w', encoding='utf-8') as f:
        json.dump(roots, f)
    monkeypatch.setenv('SKYT_FAKE_SSH_MAP', map_path)
    monkeypatch.setenv('PATH', _FAKE_BIN + os.pathsep + os.environ['PATH'])
    yield


def _task(run='echo hi', num_nodes=1):
    return Task(name='byo', run=run, num_nodes=num_nodes,
                resources=Resources(cloud='ssh'))


def test_check_reports_pool():
    enabled, reason = check.check(['ssh'])['ssh']
    assert enabled and 'lab' not in reason  # counts, not names
    assert '1 pool(s), 3 host(s)' in reason


def test_inventory_parsing_shapes(tmp_home):
    path = ssh_pool.inventory_path()
    with open(path, 'w', encoding='utf-8') as f:
        yaml.safe_dump({
            'mixed': {'user': 'u', 'identity_file': '~/.ssh/k',
                      'hosts': ['1.1.1.1',
                                {'ip': '2.2.2.2', 'port': 2222}]},
        }, f)
    pools = ssh_pool.load_inventory()
    assert pools['mixed']['hosts'][0] == {'ip': '1.1.1.1'}
    assert pools['mixed']['hosts'][1]['port'] == 2222


# r20 triage: 5s sshd end-to-end; allocation exclusivity keeps the pool
# contract in tier 1
@pytest.mark.slow
def test_launch_on_byo_hosts_end_to_end():
    """Full SSH-cluster path against inventory hosts: rank env, queue,
    logs, teardown releases the allocation."""
    results = execution.launch(
        _task('echo "rank=$SKYT_NODE_RANK of $SKYT_NUM_NODES"',
              num_nodes=2), 'byo-e2e')
    assert results == [('byo-e2e', 1)]
    record = state.get_cluster('byo-e2e')
    assert record.cloud == 'ssh' and record.region == 'lab'
    assert record.hourly_cost == 0

    deadline = time.time() + 60
    while time.time() < deadline:
        jobs = core.queue('byo-e2e')
        if jobs and jobs[0]['status'] in ('SUCCEEDED', 'FAILED'):
            break
        time.sleep(0.5)
    assert jobs[0]['status'] == 'SUCCEEDED'
    log_text = core.tail_logs('byo-e2e', 1)
    assert 'rank=0 of 2' in log_text

    provider = ssh_pool.SshNodePoolProvider()
    assert len(provider.query_instances('byo-e2e')) == 2
    core.down('byo-e2e')
    assert provider.query_instances('byo-e2e') == {}


def test_allocation_exclusivity_and_capacity():
    execution.launch(_task(num_nodes=2), 'byo-a')
    # Only 1 of 3 hosts left; a 2-node cluster must NOT steal allocated
    # hosts.
    with pytest.raises(exceptions.ResourcesUnavailableError):
        execution.launch(_task(num_nodes=2), 'byo-b')
    execution.launch(_task(num_nodes=1), 'byo-c')
    a_hosts = {h['internal_ip'] for h in
               state.get_cluster('byo-a').handle['hosts']}
    c_hosts = {h['internal_ip'] for h in
               state.get_cluster('byo-c').handle['hosts']}
    assert not a_hosts & c_hosts
    core.down('byo-a')
    core.down('byo-c')


def test_stop_is_noop_terminate_frees():
    execution.launch(_task(num_nodes=1), 'byo-stop')
    provider = ssh_pool.SshNodePoolProvider()
    provider.stop_instances('byo-stop')
    assert provider.query_instances('byo-stop')  # still allocated
    provider.terminate_instances('byo-stop')
    assert provider.query_instances('byo-stop') == {}
    state.remove_cluster('byo-stop')
