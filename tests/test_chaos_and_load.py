"""Chaos + load tests for the client-server layer.

Parity: ``tests/chaos/chaos_proxy.py`` (fault-injecting proxy between SDK
and server proves client retry/idempotency) and
``tests/load_tests/test_load_on_server.py`` (concurrent request storm).
"""
import concurrent.futures
import io
import time

import pytest

from chaos_proxy import ChaosProxy, cut_after, refuse
from skypilot_tpu import exceptions
from skypilot_tpu.client import sdk
from skypilot_tpu.provision import fake
from skypilot_tpu.server import requests_db
from skypilot_tpu.server.app import ApiServer
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task


@pytest.fixture()
def server(tmp_home, monkeypatch):
    fake.reset()
    requests_db.reset_db_for_tests()
    srv = ApiServer(port=0)
    srv.start_background()
    monkeypatch.setenv('SKYT_API_SERVER_URL', srv.url)
    yield srv
    srv.shutdown()
    requests_db.reset_db_for_tests()
    fake.reset()


def _tpu_task(run='echo hi'):
    return Task(name='t', run=run,
                resources=Resources(cloud='fake', accelerators='tpu-v5e-8'))


def _point_sdk_at(monkeypatch, url):
    monkeypatch.setenv('SKYT_API_SERVER_URL', url)


# -- chaos: connection faults between SDK and server -------------------


def test_launch_survives_refused_connections(server, monkeypatch):
    """Every other connection is refused; launch+get still succeed and the
    work is scheduled exactly once (idempotency key dedupe)."""
    host, port = server.httpd.server_address
    proxy = ChaosProxy(host, port,
                       default=lambda i: refuse() if i % 2 == 0 else None)
    proxy.start()
    _point_sdk_at(monkeypatch, proxy.url)
    try:
        request_id = sdk.launch(_tpu_task(), 'chaos-launch')
        result = sdk.get(request_id, timeout=120)
        assert result == [['chaos-launch', 1]]
    finally:
        proxy.stop()
    # The refused first attempt must not have double-submitted.
    launches = [r for r in requests_db.list_requests()
                if r.name == 'launch']
    assert len(launches) == 1
    assert proxy.connections >= 2  # the fault actually fired


def test_poll_survives_midstream_cut(server, monkeypatch):
    """The /api/get response is cut mid-body; the client retries the poll
    and still resolves the request."""
    host, port = server.httpd.server_address
    # Connection 0 = POST /launch passes; cut the next response early.
    proxy = ChaosProxy(host, port, plan={1: cut_after(20)})
    proxy.start()
    _point_sdk_at(monkeypatch, proxy.url)
    try:
        request_id = sdk.launch(_tpu_task(), 'chaos-poll')
        assert sdk.get(request_id, timeout=120) == [['chaos-poll', 1]]
    finally:
        proxy.stop()


def test_stream_resumes_without_replay_or_loss(server, monkeypatch):
    """A log stream cut mid-flight resumes from the received offset: the
    final transcript has every line exactly once."""
    host, port = server.httpd.server_address
    run = ' && '.join(f'echo marker-{i:03d}' for i in range(40))
    _point_sdk_at(monkeypatch, server.url)
    request_id = sdk.launch(_tpu_task(run), 'chaos-stream')
    assert sdk.get(request_id, timeout=120) == [['chaos-stream', 1]]

    tail_id = sdk.tail_logs('chaos-stream', 1)
    # Through the proxy: conn 0 is the health probe, conn 1 the stream —
    # cut the stream a few hundred bytes in; the retry passes clean.
    proxy = ChaosProxy(host, port, plan={1: cut_after(300)})
    proxy.start()
    _point_sdk_at(monkeypatch, proxy.url)
    buf = io.StringIO()
    try:
        sdk.stream_and_get(tail_id, output=buf)
    finally:
        proxy.stop()
    text = buf.getvalue()
    for i in range(40):
        assert text.count(f'marker-{i:03d}') == 1, (i, text[:2000])


def test_unreachable_server_raises_cleanly(tmp_home, monkeypatch):
    """With the server gone entirely, retries exhaust into a typed error
    (not a hang), and quickly."""
    monkeypatch.setenv('SKYT_API_SERVER_URL', 'http://127.0.0.1:1')
    monkeypatch.setenv('SKYT_CLIENT_RETRIES', '2')
    start = time.time()
    with pytest.raises(exceptions.ApiServerError):
        sdk.status()
    assert time.time() - start < 10


# -- load: concurrent request storm ------------------------------------


# r20 triage: 8s load soak
@pytest.mark.slow
def test_concurrent_request_storm(server, monkeypatch):
    """50 concurrent SDK calls (mixed short/long) all complete; the server
    stays healthy (parity: tests/load_tests/test_load_on_server.py's
    50-concurrent-requests scenario)."""
    _point_sdk_at(monkeypatch, server.url)
    # Under a saturated CI host, transient connection errors are part of
    # the exercise — give the client more retry budget than the default.
    monkeypatch.setenv('SKYT_CLIENT_RETRIES', '7')
    launch_id = sdk.launch(_tpu_task(), 'storm')
    assert sdk.get(launch_id, timeout=120) == [['storm', 1]]

    def one_status(_):
        return sdk.get(sdk.status(), timeout=60)

    def one_queue(_):
        return sdk.get(sdk.queue('storm'), timeout=60)

    with concurrent.futures.ThreadPoolExecutor(max_workers=50) as pool:
        futures = [pool.submit(one_status, i) for i in range(25)]
        futures += [pool.submit(one_queue, i) for i in range(25)]
        results = [f.result(timeout=180) for f in futures]
    assert len(results) == 50
    for record in results[:25]:
        assert record[0]['name'] == 'storm'
    assert sdk.api_is_healthy()
    # Every request resolved terminally; none stuck RUNNING/PENDING.
    stuck = [r for r in requests_db.list_requests(limit=200)
             if not r.status.is_terminal()]
    assert not stuck


def test_executor_pool_respects_caps(server, monkeypatch):
    """Backlogged SHORT requests never spawn more runners than the cap."""
    _point_sdk_at(monkeypatch, server.url)
    ids = [sdk.status() for _ in range(30)]
    for request_id in ids:
        sdk.get(request_id, timeout=120)
    pool = server.executor._runners  # noqa: SLF001
    for schedule_type, runners in pool.items():
        assert len(runners) <= server.executor._caps[schedule_type]  # noqa: SLF001
