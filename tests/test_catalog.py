"""Catalog lookup tests (ref: sky/catalog tests)."""
import pytest

from skypilot_tpu import catalog
from skypilot_tpu.catalog.common import get_offerings, pick_cpu_instance_type


def test_tpu_offerings():
    offerings = get_offerings('tpu-v5p-64')
    assert offerings
    for o in offerings:
        assert o.cloud == 'gcp'
        assert o.tpu is not None and o.tpu.chips == 32
        assert o.price_hr == pytest.approx(32 * 4.20)
        assert o.spot_price_hr < o.price_hr
        assert o.zone.startswith(o.region)


def test_region_filter():
    offerings = get_offerings('tpu-v5e-8', region='us-west4')
    assert offerings and all(o.region == 'us-west4' for o in offerings)
    assert get_offerings('tpu-v5e-8', region='mars-central1') == []


def test_gpu_offerings():
    offerings = get_offerings('A100', 8)
    assert offerings
    assert offerings[0].price_hr == pytest.approx(8 * 3.67)


def test_multi_slice_pricing():
    single = get_offerings('tpu-v5e-16')[0]
    multi = get_offerings('tpu-v5e-16', num_slices=4)[0]
    assert multi.price_hr == pytest.approx(4 * single.price_hr)


def test_list_accelerators():
    accs = catalog.list_accelerators(name_filter='v6e')
    assert 'tpu-v6e-8' in accs
    assert all('v6e' in name for name in accs)
    all_accs = catalog.list_accelerators()
    assert 'A100' in all_accs and 'tpu-v5p-8' in all_accs


def test_hourly_cost():
    cost = catalog.get_hourly_cost('tpu-v5e-8')
    assert cost == pytest.approx(8 * 1.20)
    spot = catalog.get_hourly_cost('tpu-v5e-8', use_spot=True)
    assert spot < cost
    assert catalog.get_hourly_cost(None, cpus=4) > 0


def test_pick_cpu_instance():
    assert pick_cpu_instance_type(8, None) == 'n2-standard-8'
    assert pick_cpu_instance_type(None, None) == 'n2-standard-2'


def test_validate_region_zone():
    catalog.validate_region_zone('gcp', 'us-central1', 'us-central1-a')
    with pytest.raises(Exception):
        catalog.validate_region_zone('gcp', 'us-central1', 'europe-west4-a')
