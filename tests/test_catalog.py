"""Catalog lookup tests (ref: sky/catalog tests)."""
import pytest

from skypilot_tpu import catalog
from skypilot_tpu.catalog.common import get_offerings, pick_cpu_instance_type


def test_tpu_offerings():
    offerings = get_offerings('tpu-v5p-64')
    assert offerings
    for o in offerings:
        assert o.cloud == 'gcp'
        assert o.tpu is not None and o.tpu.chips == 32
        assert o.price_hr == pytest.approx(32 * 4.20)
        assert o.spot_price_hr < o.price_hr
        assert o.zone.startswith(o.region)


def test_region_filter():
    offerings = get_offerings('tpu-v5e-8', region='us-west4')
    assert offerings and all(o.region == 'us-west4' for o in offerings)
    assert get_offerings('tpu-v5e-8', region='mars-central1') == []


def test_gpu_offerings():
    offerings = get_offerings('A100', 8)
    assert offerings
    assert offerings[0].price_hr == pytest.approx(8 * 3.67)


def test_multi_slice_pricing():
    single = get_offerings('tpu-v5e-16')[0]
    multi = get_offerings('tpu-v5e-16', num_slices=4)[0]
    assert multi.price_hr == pytest.approx(4 * single.price_hr)


def test_list_accelerators():
    accs = catalog.list_accelerators(name_filter='v6e')
    assert 'tpu-v6e-8' in accs
    assert all('v6e' in name for name in accs)
    all_accs = catalog.list_accelerators()
    assert 'A100' in all_accs and 'tpu-v5p-8' in all_accs


def test_hourly_cost():
    cost = catalog.get_hourly_cost('tpu-v5e-8')
    assert cost == pytest.approx(8 * 1.20)
    spot = catalog.get_hourly_cost('tpu-v5e-8', use_spot=True)
    assert spot < cost
    assert catalog.get_hourly_cost(None, cpus=4) > 0


def test_pick_cpu_instance():
    assert pick_cpu_instance_type(8, None) == 'n2-standard-8'
    assert pick_cpu_instance_type(None, None) == 'n2-standard-2'


def test_validate_region_zone():
    catalog.validate_region_zone('gcp', 'us-central1', 'us-central1-a')
    with pytest.raises(Exception):
        catalog.validate_region_zone('gcp', 'us-central1', 'europe-west4-a')


# -- hosted feed refresh (VERDICT r2 missing #3) ---------------------------

import json as _json
import os as _os
import time as _time

import pytest as _pytest

from skypilot_tpu.catalog import data_fetchers, refresh


@_pytest.fixture()
def feed(tmp_home, tmp_path, monkeypatch):
    """A local feed file wired up as the configured catalog feed."""
    path = tmp_path / 'feed.json'
    doc = data_fetchers.build_feed()
    doc['gcp']['tpu_chip_hour_prices']['v5e'] = [9.99, 4.44]
    path.write_text(_json.dumps(doc))
    monkeypatch.setenv('SKYT_CATALOG_FEED', str(path))
    refresh.clear_cache()
    yield path
    refresh.clear_cache()


def test_overlay_overrides_baked_prices(feed):
    offers = get_offerings('tpu-v5e-8')
    assert offers
    # v5e-8 == 8 chips at the overlaid 9.99/chip price.
    assert abs(offers[0].price_hr - 8 * 9.99) < 1e-6
    assert abs(offers[0].spot_price_hr - 8 * 4.44) < 1e-6


def test_no_feed_uses_baked_tables(tmp_home, monkeypatch):
    monkeypatch.delenv('SKYT_CATALOG_FEED', raising=False)
    refresh.clear_cache()
    assert refresh.get_overlay() == {}
    assert get_offerings('tpu-v5e-8')  # baked tables still serve


def test_unreachable_feed_falls_back_to_cache_then_baked(
        feed, tmp_home, monkeypatch):
    # Prime the on-disk cache from the good feed.
    overlay = refresh.get_overlay()
    assert overlay['gcp']['tpu_chip_hour_prices']['v5e'] == [9.99, 4.44]
    assert _os.path.exists(refresh.cache_path())
    # The feed becomes unreachable (same URL): the cached copy serves.
    _os.rename(str(feed), str(feed) + '.hidden')
    refresh.clear_cache()
    overlay2 = refresh.get_overlay(refresh=True)
    assert overlay2.get('gcp', {}).get('tpu_chip_hour_prices',
                                       {}).get('v5e') == [9.99, 4.44]
    # No cache either: empty overlay, baked tables, still no exception.
    _os.remove(refresh.cache_path())
    refresh.clear_cache()
    assert refresh.get_overlay(refresh=True) == {}


def test_feed_fetched_once_within_ttl(feed, monkeypatch):
    reads = []
    real_fetch = refresh._fetch

    def counting_fetch(url):
        reads.append(url)
        return real_fetch(url)

    monkeypatch.setattr(refresh, '_fetch', counting_fetch)
    refresh.get_overlay()
    refresh.get_overlay()
    refresh.get_overlay()
    assert len(reads) <= 1  # served from memory/disk cache afterwards


def test_staleness_warning(feed, monkeypatch):
    assert refresh.staleness_warning() is None  # fresh feed
    # An ancient generated_at stamps the feed as stale.
    doc = _json.loads(feed.read_text())
    doc['generated_at'] = _time.time() - 90 * 86400
    feed.write_text(_json.dumps(doc))
    refresh.clear_cache()
    _os.remove(refresh.cache_path())
    warning = refresh.staleness_warning()
    assert warning and 'days old' in warning


def test_data_fetchers_roundtrip(tmp_path):
    out = tmp_path / 'regen.json'
    data_fetchers.main(['--out', str(out)])
    doc = _json.loads(out.read_text())
    assert doc['version'] == 1
    assert 'v5e' in doc['gcp']['tpu_chip_hour_prices']
    assert 'A10G' in doc['aws']['gpu_instance_types']
