"""Provision + failover tests against the fake cloud (ref: moto-backed
mock_aws_backend, tests/common_test_fixtures.py:494)."""
import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.optimizer import candidates_for
from skypilot_tpu.provision import fake, get_provider
from skypilot_tpu.provision.provisioner import (Blocklist,
                                                provision_with_failover)
from skypilot_tpu.spec.resources import Resources

CLOUDS = ['fake']


@pytest.fixture(autouse=True)
def fresh_fake_cloud(tmp_home):
    fake.reset()
    yield
    fake.reset()


def _candidates(accel='tpu-v5e-16', **kw):
    return candidates_for(Resources(cloud='fake', accelerators=accel, **kw),
                          CLOUDS)


def test_provision_multi_host_slice():
    info, chosen = provision_with_failover('c1', _candidates('tpu-v5e-16'),
                                           num_nodes=1)
    assert len(info.hosts) == 2          # v5e-16 = 2 hosts
    assert info.hosts[0].worker_index == 0
    assert info.hosts[1].worker_index == 1
    assert chosen.resources.zone is not None
    provider = get_provider('fake')
    states = provider.query_instances('c1')
    assert len(states) == 2 and all(s == 'running' for s in states.values())


def test_multi_slice_hosts():
    cands = candidates_for(
        Resources(cloud='fake', accelerators='tpu-v5e-16', num_slices=2),
        CLOUDS)
    info, _ = provision_with_failover('c2', cands, num_nodes=1)
    assert len(info.hosts) == 4          # 2 slices x 2 hosts


def test_stockout_fails_over_to_next_zone():
    cands = _candidates('tpu-v5e-8')
    first_zone = cands[0].resources.zone
    fake.inject_stockout(first_zone)
    info, chosen = provision_with_failover('c3', cands, num_nodes=1)
    assert chosen.resources.zone != first_zone
    assert info.hosts


def test_quota_error_blocklists_region():
    cands = _candidates('tpu-v5e-8')
    first_region = cands[0].resources.region
    fake.inject_quota_exceeded(first_region)
    blocklist = Blocklist()
    _, chosen = provision_with_failover('c4', cands, num_nodes=1,
                                        blocklist=blocklist)
    assert chosen.resources.region != first_region
    assert ('fake', first_region) in blocklist.regions


def test_exhaustion_raises_with_history():
    cands = _candidates('tpu-v5e-8')
    for c in cands:
        fake.inject_stockout(c.resources.zone)
    with pytest.raises(exceptions.ResourcesUnavailableError) as exc:
        provision_with_failover('c5', cands, num_nodes=1)
    assert exc.value.failover_history
    assert any('stockout' in str(e) for e in exc.value.failover_history)


def test_transient_stockout_retry_succeeds_later():
    cands = _candidates('tpu-v5e-8')
    # one-shot stockout in the first zone: first try fails over, but a
    # *fresh* provisioning round (new blocklist) succeeds there again
    fake.inject_stockout(cands[0].resources.zone, count=1)
    _, chosen1 = provision_with_failover('c6', cands, num_nodes=1)
    assert chosen1.resources.zone != cands[0].resources.zone
    _, chosen2 = provision_with_failover('c7', cands, num_nodes=1)
    assert chosen2.resources.zone == cands[0].resources.zone


def test_stop_resume_cycle():
    cands = _candidates('tpu-v5e-8')
    provision_with_failover('c8', cands, num_nodes=1)
    provider = get_provider('fake')
    provider.stop_instances('c8')
    assert all(s == 'stopped'
               for s in provider.query_instances('c8').values())
    assert provider.get_cluster_info('c8') is None
    info, _ = provision_with_failover('c8', cands, num_nodes=1, resume=True)
    assert all(s == 'running'
               for s in provider.query_instances('c8').values())
    assert info.hosts


def test_preemption_visible_in_query():
    provision_with_failover('c9', _candidates('tpu-v5e-8', use_spot=True),
                            num_nodes=1)
    fake.preempt_cluster('c9')
    provider = get_provider('fake')
    assert all(s == 'preempted'
               for s in provider.query_instances('c9').values())


def test_gcp_error_classification():
    from skypilot_tpu.provision.gcp import classify_gcp_error
    err = classify_gcp_error(
        'The zone does not have enough resources available')
    assert isinstance(err, exceptions.CapacityError)
    err = classify_gcp_error('Quota exceeded for TPUS_PER_PROJECT')
    assert isinstance(err, exceptions.QuotaExceededError)
    err = classify_gcp_error('internal server error')
    assert isinstance(err, exceptions.ProvisionError)
    assert not isinstance(err, (exceptions.CapacityError,
                                exceptions.QuotaExceededError))
