"""Postgres backends for state + locks (VERDICT r3 missing #6).

The stdlib wire client (utils/pg.py) runs against tests/fake_pg.py — a
protocol-v3 server with REAL SCRAM-SHA-256 auth backed by in-memory
sqlite — the same fake-transport strategy as the GCP/S3/Azure drivers.
Parity bars: ``sky/global_user_state.py`` (sqlite OR postgres state)
and ``sky/utils/locks.py:164`` (PostgresLock advisory locks).
"""
import threading
import time

import pytest

from skypilot_tpu import state
from skypilot_tpu.utils import locks as locks_lib
from skypilot_tpu.utils import pg

from tests.fake_pg import FakePgServer


@pytest.fixture()
def pg_server(tmp_home, monkeypatch):
    server = FakePgServer()
    monkeypatch.setenv('SKYT_DB_URL', server.url)
    # Invalidate any cached per-thread sqlite connection.
    state._local.__dict__.clear()
    yield server
    state._local.__dict__.clear()
    server.close()


def test_scram_auth_and_basic_queries(pg_server):
    conn = pg.PgConnection.from_url(pg_server.url)
    conn.execute('CREATE TABLE t (a TEXT, b INTEGER, c REAL)')
    conn.execute('INSERT INTO t VALUES (?,?,?)',
                 ("it's quoted", 7, 2.5))
    row = conn.execute('SELECT * FROM t').fetchone()
    assert row == {'a': "it's quoted", 'b': 7, 'c': 2.5}
    assert isinstance(row['b'], int) and isinstance(row['c'], float)
    with pytest.raises(pg.PgError):
        conn.execute('SELECT * FROM missing_table')
    # The connection survives an error (ReadyForQuery resync).
    assert conn.execute('SELECT b FROM t').fetchone() == {'b': 7}
    conn.close()


def test_wrong_password_refused(pg_server):
    bad = pg_server.url.replace(':secret@', ':wrong@')
    with pytest.raises(pg.PgError, match='authentication failed'):
        pg.PgConnection.from_url(bad)


def test_state_roundtrip_on_postgres(pg_server):
    state.add_or_update_cluster(
        'pgc', status=state.ClusterStatus.INIT, cloud='gcp',
        region='us-central2', num_nodes=2, hourly_cost=4.5,
        handle={'hosts': [{'internal_ip': '10.0.0.1'}]})
    state.set_cluster_status('pgc', state.ClusterStatus.UP)
    state.add_cluster_event('pgc', 'UP', 'provisioned')
    record = state.get_cluster('pgc')
    assert record.status == state.ClusterStatus.UP
    assert record.cloud == 'gcp'
    assert record.num_nodes == 2
    assert isinstance(record.num_nodes, int)
    assert record.hourly_cost == 4.5
    assert record.handle == {'hosts': [{'internal_ip': '10.0.0.1'}]}
    assert [c.name for c in state.get_clusters()] == ['pgc']
    events = state.get_cluster_events('pgc')
    assert [e['event'] for e in events] == ['UP']
    assert isinstance(events[0]['ts'], float)
    state.remove_cluster('pgc')
    assert state.get_cluster('pgc') is None


def test_distributed_lock_uses_advisory_locks(pg_server):
    lock_a = locks_lib.cluster_lock('plk')
    lock_b = locks_lib.cluster_lock('plk', timeout=0.3)
    assert isinstance(lock_a._backend,
                      locks_lib._PostgresLockBackend)
    lock_a.acquire()
    with pytest.raises(locks_lib.LockTimeout):
        lock_b.acquire()
    lock_a.release()
    lock_b.acquire()   # freed -> acquirable
    lock_b.release()


def test_advisory_lock_released_when_holder_connection_dies(pg_server):
    """The property filelocks cannot give across machines: a crashed
    holder's lock frees when its DB session drops."""
    holder = locks_lib.cluster_lock('crash')
    holder.acquire()
    waiter = locks_lib.cluster_lock('crash', timeout=5)
    acquired = threading.Event()

    def wait_for_it():
        waiter.acquire()
        acquired.set()

    thread = threading.Thread(target=wait_for_it, daemon=True)
    thread.start()
    time.sleep(0.2)
    assert not acquired.is_set()
    holder._backend._conn.close()   # simulated process crash
    assert acquired.wait(timeout=5), (
        'advisory lock not released on holder disconnect')
    waiter.release()


def test_managed_jobs_state_on_postgres(pg_server):
    from skypilot_tpu.jobs import state as jobs_state
    jobs_state._local.__dict__.clear()
    job_a = jobs_state.submit({'run': 'echo a'}, 'job-a', 'FAILOVER', 1)
    job_b = jobs_state.submit({'run': 'echo b'}, 'job-b', 'FAILOVER', 0)
    assert (job_a, job_b) == (1, 2)

    # Claim honors FIFO and the launching cap.
    assert jobs_state.claim_waiting_job(1, 10) == job_a
    assert jobs_state.claim_waiting_job(1, 10) is None  # cap hit
    jobs_state.set_schedule_state(job_a, jobs_state.ScheduleState.ALIVE)
    assert jobs_state.claim_waiting_job(1, 10) == job_b

    assert jobs_state.set_status(
        job_a, jobs_state.ManagedJobStatus.RUNNING)
    assert jobs_state.set_status(
        job_a, jobs_state.ManagedJobStatus.SUCCEEDED)
    # Terminal status never overwritten (the rowcount-guard idiom).
    assert not jobs_state.set_status(
        job_a, jobs_state.ManagedJobStatus.RUNNING)

    record = jobs_state.get(job_a)
    assert record.status == jobs_state.ManagedJobStatus.SUCCEEDED
    assert record.max_restarts_on_errors == 1
    assert isinstance(record.submitted_at, float)
    names = [r.name for r in jobs_state.list_jobs()]
    assert names == ['job-b', 'job-a']
    jobs_state._local.__dict__.clear()


def test_serve_state_on_postgres(pg_server):
    """Serve offload rides the shared DB: services + replicas written
    through one API-server/controller must be visible to any other
    process pointed at the same SKYT_DB_URL."""
    from skypilot_tpu.serve import serve_state
    serve_state._local.__dict__.clear()
    assert serve_state.add_service('svc', {'replicas': 1},
                                   {'run': 'srv'}, 8001)
    assert not serve_state.add_service('svc', {}, {}, 8002)  # duplicate
    serve_state.set_controller_pid('svc', 42,
                                   controller_cluster='ctl-cluster')
    serve_state.set_lb_host('svc', '10.0.0.9')
    serve_state.add_replica('svc', 1, 'svc-replica-1', is_spot=False)
    serve_state.set_replica_endpoint('svc', 1, 'http://10.0.0.7:9000',
                                     'us-central2-b')
    serve_state.set_replica_status('svc', 1,
                                   serve_state.ReplicaStatus.READY)

    record = serve_state.get_service('svc')
    assert record.controller_cluster == 'ctl-cluster'
    assert record.controller_pid == 42
    assert record.endpoint == 'http://10.0.0.9:8001'
    replicas = serve_state.list_replicas('svc')
    assert len(replicas) == 1
    assert replicas[0].status == serve_state.ReplicaStatus.READY
    assert replicas[0].endpoint == 'http://10.0.0.7:9000'

    # Restart claim: exactly one concurrent observer wins; budget caps.
    assert serve_state.claim_controller_restart('svc', 42, 3)
    assert not serve_state.claim_controller_restart('svc', 42, 3)
    record = serve_state.get_service('svc')
    assert record.controller_pid is None
    assert record.controller_restarts == 1
    assert isinstance(record.controller_claimed_at, float)
    # Stale-claim reclamation only past the grace period.
    assert not serve_state.reclaim_stale_controller_claim(
        'svc', stale_after=30.0)
    assert serve_state.reclaim_stale_controller_claim(
        'svc', stale_after=0.0)

    serve_state.remove_service('svc')
    assert serve_state.get_service('svc') is None
    assert serve_state.list_replicas('svc') == []
    serve_state._local.__dict__.clear()


# -- TLS + extended-protocol bind params (VERDICT r4 next-round #6) ---------


def test_tls_require_roundtrip(tmp_home, monkeypatch):
    from tests import fake_pg as fake_pg_mod
    server = FakePgServer(tls=True)
    try:
        url = server.url + '?sslmode=require'
        conn = pg.PgConnection.from_url(url)
        conn.execute('CREATE TABLE tt (a TEXT, b INTEGER)')
        conn.execute('INSERT INTO tt VALUES (?, ?)', ('x', 3))
        assert conn.execute('SELECT b FROM tt WHERE a = ?',
                            ('x',)).fetchone() == {'b': 3}
        conn.close()
    finally:
        server.close()


def test_tls_verify_full_accepts_right_ca_rejects_wrong(tmp_home):
    from tests import fake_pg as fake_pg_mod
    server = FakePgServer(tls=True)
    try:
        good = (server.url + '?sslmode=verify-full'
                f'&sslrootcert={fake_pg_mod.CA_CERT}')
        conn = pg.PgConnection.from_url(good)
        assert conn.execute('SELECT 1 AS one').fetchone() == {'one': 1}
        conn.close()
        bad = (server.url + '?sslmode=verify-full'
               f'&sslrootcert={fake_pg_mod.WRONG_CA_CERT}')
        with pytest.raises(pg.PgError, match='TLS handshake failed'):
            pg.PgConnection.from_url(bad)
    finally:
        server.close()


def test_tls_required_but_server_plaintext(tmp_home):
    server = FakePgServer(tls=False)
    try:
        with pytest.raises(pg.PgError, match='refused TLS'):
            pg.PgConnection.from_url(server.url + '?sslmode=require')
    finally:
        server.close()


def test_state_works_over_tls(tmp_home, monkeypatch):
    """The whole dual-backend state layer over a verify-full TLS URL —
    the realistic cloud-managed-Postgres HA deployment."""
    from tests import fake_pg as fake_pg_mod
    server = FakePgServer(tls=True)
    monkeypatch.setenv(
        'SKYT_DB_URL',
        server.url + '?sslmode=verify-full'
        f'&sslrootcert={fake_pg_mod.CA_CERT}')
    state._local.__dict__.clear()
    try:
        state.add_or_update_cluster('tlsc',
                                    status=state.ClusterStatus.UP,
                                    cloud='gcp', region='us-central2')
        record = state.get_cluster('tlsc')
        assert record.status == state.ClusterStatus.UP
        state.remove_cluster('tlsc')
    finally:
        state._local.__dict__.clear()
        server.close()


def test_bind_params_resist_injection_and_weird_values(pg_server):
    """Values travel as extended-protocol bind params, never spliced
    into SQL: injection-shaped strings are stored verbatim."""
    conn = pg.PgConnection.from_url(pg_server.url)
    conn.execute('CREATE TABLE inj (v TEXT)')
    hostile = "'; DROP TABLE inj; --"
    conn.execute('INSERT INTO inj VALUES (?)', (hostile,))
    assert conn.execute('SELECT v FROM inj').fetchone() == {'v': hostile}
    # Comment scanner: a ? inside a line comment is NOT a placeholder.
    row = conn.execute('SELECT v FROM inj -- what? really?\n'
                       'WHERE v = ?', (hostile,)).fetchone()
    assert row == {'v': hostile}
    # Non-finite floats are rejected loudly instead of emitting
    # invalid SQL.
    with pytest.raises(ValueError, match='non-finite'):
        conn.execute('INSERT INTO inj VALUES (?)', (float('inf'),))
    conn.close()


def test_dollar_param_translation():
    assert pg.to_dollar_params('a = ? AND b = ?') == 'a = $1 AND b = $2'
    assert pg.to_dollar_params("v = '?' AND w = ?") == "v = '?' AND w = $1"
    assert (pg.to_dollar_params('x = ? -- not this ?\nAND y = ?') ==
            'x = $1 -- not this ?\nAND y = $2')


def test_reconnect_after_db_restart(tmp_home, monkeypatch):
    """ADVICE r4 medium: a cached per-thread connection must be evicted
    after the server drops it — a transient Postgres restart must not
    wedge the thread until process restart."""
    server = FakePgServer()
    port = server.port
    monkeypatch.setenv('SKYT_DB_URL', server.url)
    state._local.__dict__.clear()
    try:
        state.add_or_update_cluster('rc', status=state.ClusterStatus.UP,
                                    cloud='gcp', region='us-central2')
        assert state.get_cluster('rc') is not None
        # The DB restarts (connection drops; data is gone — fake_pg is
        # in-memory, which is fine: we only care about reconnection).
        server.close()
        with pytest.raises(pg.PgError):
            state.get_cluster('rc')
        server = FakePgServer(port=port)
        # The fake's in-memory DB lost the schema with the restart (a
        # real Postgres keeps it on disk); re-arm schema init so the
        # reconnect path is what's under test, not DDL durability.
        state._pg_schema_ready.clear()
        # Same thread, next call: reconnects instead of failing forever.
        assert state.get_cluster('rc') is None  # fresh empty DB
        state.add_or_update_cluster('rc2',
                                    status=state.ClusterStatus.INIT,
                                    cloud='gcp', region='us-central2')
        assert state.get_cluster('rc2') is not None
    finally:
        state._local.__dict__.clear()
        server.close()
