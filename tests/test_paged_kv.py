"""Paged KV pool, chunked prefill, and prefix cache.

Covers the three layers of the rebuilt continuous-batching core: the
host-side block pool (alloc/free/refcount recycling), the digest-chain
prefix cache (hit produces IDENTICAL output to a cold prefill), chunked
prefill correctness (multi-chunk prompt == whole-prompt reference), the
pool-pressure paths (queueing vs clean failure), metrics accounting,
and a latency-marked smoke asserting decode cadence stays bounded while
a long prompt is being absorbed in chunks.
"""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.inference.continuous import ContinuousBatchingEngine
from skypilot_tpu.inference.paged import (BlockImporter, BlockPool,
                                          PrefixCache, chain_digests)
from skypilot_tpu.models import decode as decode_lib


# ---------------------------------------------------------------------------
# Host-side pool + prefix cache (no device work)
# ---------------------------------------------------------------------------

def test_block_pool_alloc_free_refcount_recycling():
    pool = BlockPool(5)          # blocks 1..4 allocatable, 0 reserved
    assert pool.total_blocks == 4 and pool.free_blocks == 4
    got = [pool.alloc() for _ in range(4)]
    assert got == [1, 2, 3, 4]   # deterministic order, never the null 0
    assert pool.alloc() is None  # exhausted
    # Sharing: a second reference keeps the block out of the free list.
    pool.incref(2)
    pool.decref(2)
    assert pool.free_blocks == 0
    pool.decref(2)
    assert pool.free_blocks == 1
    assert pool.alloc() == 2     # recycled
    # Double free / bad refs are loud.
    pool.decref(3)
    with pytest.raises(ValueError, match='double free'):
        pool.decref(3)
    with pytest.raises(ValueError, match='unallocated'):
        pool.incref(3)
    with pytest.raises(ValueError, match='unallocated'):
        pool.incref(0)


def test_prefix_cache_chain_lookup_insert_evict():
    pool = BlockPool(9)
    cache = PrefixCache(pool, block_size=4)
    ids = list(range(11))            # 2 full blocks + partial tail
    blocks = [pool.alloc() for _ in range(3)]
    cache.insert(ids, blocks)
    assert cache.cached_blocks == 2  # only FULL blocks are cached
    assert pool.refcount(blocks[0]) == 2   # slot ref + cache ref
    assert pool.refcount(blocks[2]) == 1   # partial tail never shared
    # Full-prefix chain match (capped below the last token).
    assert cache.lookup(ids, limit_tokens=10) == blocks[:2]
    pool.decref(blocks[0])
    pool.decref(blocks[1])
    # Diverging second block breaks the chain after one block.
    other = [0, 1, 2, 3, 99, 99, 99, 99]
    assert cache.lookup(other, limit_tokens=8) == blocks[:1]
    pool.decref(blocks[0])
    # limit_tokens caps the match even when more blocks are cached.
    assert cache.lookup(ids, limit_tokens=4) == blocks[:1]
    pool.decref(blocks[0])
    # Eviction releases the cache's block references.
    for b in blocks:
        pool.decref(b)               # drop the slot refs
    assert pool.free_blocks == 6      # tail freed; 2 cached blocks held
    assert cache.evict_one() and cache.evict_one()
    assert not cache.evict_one()
    assert pool.free_blocks == 8


def test_prefix_pressure_eviction_skips_blocks_shared_with_slots():
    """Pool-pressure eviction must only drop entries whose block it
    alone holds: evicting entries shared with live slots frees nothing
    and would wipe the reusable prefix chains for no gain."""
    pool = BlockPool(3)                  # blocks 1..2 allocatable
    cache = PrefixCache(pool, block_size=4)
    ids = list(range(8))                 # 2 full blocks
    blocks = [pool.alloc(), pool.alloc()]
    cache.insert(ids, blocks)            # cache ref on both (ref 2)
    assert pool.free_blocks == 0
    pool.decref(blocks[1])               # "slot" released block 2 only
    assert cache.reclaimable_blocks == 1
    assert cache.evict_reclaimable()     # frees the cache-only block
    assert pool.free_blocks == 1
    # The surviving entry's block is still held by the "slot": not
    # evictable under pressure, chain survives.
    assert not cache.evict_reclaimable()
    assert cache.cached_blocks == 1


# ---------------------------------------------------------------------------
# KV-migration import bookkeeping (disaggregated serving, ISSUE 18)
# ---------------------------------------------------------------------------

def _pool_snapshot(pool):
    return ([pool.refcount(b) for b in range(pool.num_blocks)],
            pool.free_blocks)


def test_chain_digests_match_prefix_cache_keying():
    """The exported chain digests ARE the prefix-cache keys: a block
    whose digest appears in the decode-side cache is resident and must
    never move."""
    pool = BlockPool(9)
    cache = PrefixCache(pool, block_size=4)
    ids = list(range(12))
    blocks = [pool.alloc() for _ in range(3)]
    cache.insert(ids, blocks)
    digests = chain_digests(ids, 4)
    assert len(digests) == 3
    # Same rolling keying: a lookup over the same ids walks exactly
    # the digest chain (all 3 full blocks are cached).
    hit = cache.lookup(ids, limit_tokens=12)
    assert hit == blocks
    for b in hit:
        pool.decref(b)
    # Divergence re-keys every later block in the chain.
    other = list(range(12))
    other[5] = 99
    diverged = chain_digests(other, 4)
    assert diverged[0] == digests[0]
    assert diverged[1] != digests[1] and diverged[2] != digests[2]


def test_block_importer_aborted_import_is_exactly_pre_import_state():
    """The r13 rollback-parity property, for migration: an import that
    dies mid-flight leaves refcounts AND prefix-cache entries exactly
    where they were before the import began."""
    pool = BlockPool(10)
    cache = PrefixCache(pool, block_size=4)
    shared_ids = list(range(8))                  # 2 full shared blocks
    shared = [pool.alloc(), pool.alloc()]
    cache.insert(shared_ids, shared)
    ids = shared_ids + [50, 51, 52, 53, 54]      # + 2 more blocks (9 tok)
    before_refs, before_free = _pool_snapshot(pool)
    before_entries = cache.cached_blocks

    importer = BlockImporter(pool, cache)
    got = importer.begin(ids, needed_total=3, block_size=4)
    assert got is not None
    blocks, n_resident = got
    assert n_resident == 2 and blocks[:2] == shared
    assert len(blocks) == 3
    # Mid-import state really moved: shared blocks gained a ref, a
    # private block got allocated.
    assert pool.refcount(shared[0]) == before_refs[shared[0]] + 1
    assert pool.free_blocks == before_free - 1

    importer.abort()                             # migration died
    assert _pool_snapshot(pool) == (before_refs, before_free)
    assert cache.cached_blocks == before_entries
    # abort is idempotent — a second call must not double-free.
    importer.abort()
    assert _pool_snapshot(pool) == (before_refs, before_free)


def test_block_importer_pool_exhaustion_retains_nothing():
    pool = BlockPool(4)                          # 3 allocatable
    cache = PrefixCache(pool, block_size=4)
    shared = [pool.alloc()]
    cache.insert(list(range(4)), shared)
    before = _pool_snapshot(pool)
    importer = BlockImporter(pool, cache)
    # Needs 4 blocks (1 resident + 3 private) but only 2 are free.
    got = importer.begin(list(range(16)), needed_total=4, block_size=4)
    assert got is None
    assert not importer.active
    assert _pool_snapshot(pool) == before


def test_block_importer_commit_transfers_ownership():
    pool = BlockPool(6)
    importer = BlockImporter(pool, None)         # no prefix cache
    got = importer.begin([1, 2, 3, 4, 5, 6], needed_total=2,
                         block_size=4)
    assert got is not None
    blocks, n_resident = got
    assert n_resident == 0 and len(blocks) == 2
    importer.commit()
    # After commit the refs belong to the caller: abort is a no-op and
    # the caller's decref is the one that frees.
    importer.abort()
    assert all(pool.refcount(b) == 1 for b in blocks)
    for b in blocks:
        pool.decref(b)
    assert pool.free_blocks == pool.total_blocks


def test_block_importer_rejects_overlapping_imports():
    pool = BlockPool(6)
    importer = BlockImporter(pool, None)
    assert importer.begin([1, 2, 3, 4], needed_total=1,
                          block_size=4) is not None
    with pytest.raises(RuntimeError, match='open import'):
        importer.begin([5, 6, 7, 8], needed_total=1, block_size=4)
    importer.abort()
    assert pool.free_blocks == pool.total_blocks


# ---------------------------------------------------------------------------
# Engine-level: chunked prefill + prefix reuse correctness
# ---------------------------------------------------------------------------

@pytest.fixture(scope='module')
def paged_engine():
    eng = ContinuousBatchingEngine('tiny', max_slots=2, max_len=96,
                                   block_size=8, prefill_chunk=8)
    yield eng
    eng.shutdown()


def _reference_greedy(engine, ids, max_new_tokens):
    tokens = jnp.asarray([ids], jnp.int32)
    lengths = jnp.asarray([len(ids)], jnp.int32)
    generated, gen_len = decode_lib.generate(
        engine.params, tokens, lengths, engine.cfg,
        max_new_tokens=max_new_tokens, temperature=0.0)
    return list(np.asarray(generated)[0][:int(gen_len[0])])


def test_multi_chunk_prefill_matches_whole_prompt(paged_engine):
    """A 21-token prompt through 8-token chunks (3 chunks, one partial,
    crossing block boundaries) equals the single-pass reference."""
    ids = [(7 * i + 3) % 512 for i in range(21)]
    out = paged_engine.generate_ids(ids, max_new_tokens=8)
    assert out == _reference_greedy(paged_engine, ids, 8)
    assert paged_engine.stats()['prefill_chunks'] >= 3


def test_block_boundary_prompt_lengths(paged_engine):
    """Prompt lengths at exact block/chunk multiples are the classic
    off-by-one spots: first decode write needs a fresh tail block."""
    for n in (8, 16, 24):
        ids = [(5 * i + 1) % 512 for i in range(n)]
        out = paged_engine.generate_ids(ids, max_new_tokens=6)
        assert out == _reference_greedy(paged_engine, ids, 6), n


def test_prefix_cache_hit_identical_output_and_counters(paged_engine):
    """The second request over a shared prefix reuses cached blocks
    (no recompute) and MUST produce identical tokens."""
    ids = [(3 * i + 11) % 512 for i in range(20)]
    before = paged_engine.stats()
    first = paged_engine.generate_ids(ids, max_new_tokens=8)
    mid = paged_engine.stats()
    second = paged_engine.generate_ids(ids, max_new_tokens=8)
    after = paged_engine.stats()
    assert first == second == _reference_greedy(paged_engine, ids, 8)
    assert mid['prefix_cache_misses'] == before['prefix_cache_misses'] + 1
    assert after['prefix_cache_hits'] == mid['prefix_cache_hits'] + 1
    # 20 tokens = 2 full 8-token blocks reusable.
    assert (after['prefix_tokens_reused'] >=
            mid['prefix_tokens_reused'] + 16)
    # The hit skipped the shared blocks' prefill compute: the second
    # pass only chunks the private suffix (4 tokens = 1 chunk).
    assert (after['prefill_chunks'] - mid['prefill_chunks'] <
            mid['prefill_chunks'] - before['prefill_chunks'])


def test_shared_prefix_divergent_suffixes_concurrent(paged_engine):
    """Two live slots referencing the SAME prefix blocks with different
    private tails — the copy-on-write read path must not cross-talk."""
    prefix = [(9 * i + 2) % 512 for i in range(16)]
    a = prefix + [401, 17]
    b = prefix + [88]
    paged_engine.generate_ids(prefix + [250], max_new_tokens=2)  # seed cache
    outs = {}

    def run(name, ids):
        outs[name] = paged_engine.generate_ids(ids, max_new_tokens=8)

    threads = [threading.Thread(target=run, args=('a', a)),
               threading.Thread(target=run, args=('b', b))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert outs['a'] == _reference_greedy(paged_engine, a, 8)
    assert outs['b'] == _reference_greedy(paged_engine, b, 8)


def test_stats_block_gauges(paged_engine):
    stats = paged_engine.stats()
    assert stats['blocks_total'] == paged_engine.num_blocks - 1
    assert 0 <= stats['blocks_free'] <= stats['blocks_total']
    assert 0.0 <= stats['block_occupancy'] <= 1.0
    assert stats['block_size'] == 8
    # Accounting invariant: every submitted request is completed,
    # errored, or still in flight.
    in_flight = stats['active'] + stats['pending']
    assert stats['requests'] == (stats['completions'] +
                                 stats['request_errors'] + in_flight)


# ---------------------------------------------------------------------------
# Pool-pressure paths
# ---------------------------------------------------------------------------

def test_pool_pressure_queues_requests_not_fails():
    """More concurrent work than the pool can hold at once: admission
    waits for blocks instead of failing, and every request completes
    correctly (HBM oversubscription degrades to queueing)."""
    eng = ContinuousBatchingEngine('tiny', max_slots=4, max_len=64,
                                   block_size=8, prefill_chunk=8,
                                   num_blocks=9,  # 8 usable = 64 tokens
                                   prefix_cache=False)
    try:
        prompts = [[(i * 13 + j) % 512 for j in range(12)]
                   for i in range(4)]
        outs = [None] * 4

        def run(i):
            outs[i] = eng.generate_ids(prompts[i], max_new_tokens=6)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for i in range(4):
            assert outs[i] == _reference_greedy(eng, prompts[i], 6), i
        stats = eng.stats()
        assert stats['completions'] == 4
        assert stats['blocks_free'] == stats['blocks_total']
        # 4 slots x 3 blocks of demand against 8 usable blocks: the
        # engine MUST have preempted (and deterministically resumed)
        # at least one request rather than failing it.
        assert stats['preemptions'] >= 1
    finally:
        eng.shutdown()


def test_impossible_prompt_fails_cleanly():
    """A prompt that can NEVER fit the pool fails loudly instead of
    stalling the queue forever."""
    eng = ContinuousBatchingEngine('tiny', max_slots=2, max_len=64,
                                   block_size=8, prefill_chunk=8,
                                   num_blocks=3)  # 2 usable = 16 tokens
    try:
        with pytest.raises(RuntimeError, match='KV blocks'):
            eng.generate_ids(list(range(30)), max_new_tokens=4,
                             timeout=30)
        stats = eng.stats()
        assert stats['request_errors'] == 1
        assert stats['blocks_free'] == stats['blocks_total']
    finally:
        eng.shutdown()


def test_prefill_error_counts_and_frees_blocks(monkeypatch):
    """ISSUE 7 satellite: a prefill failure must land in the
    prefill_errors counter, keep requests == completions + errors, and
    return the slot's blocks to the pool."""
    # Same shapes as the module fixture: the module-level jit cache
    # makes this engine build compile-free.
    eng = ContinuousBatchingEngine('tiny', max_slots=2, max_len=96,
                                   block_size=8, prefill_chunk=8)
    try:
        def boom(*a, **k):
            raise RuntimeError('injected prefill failure')

        monkeypatch.setattr(eng, '_prefill_fn', boom)
        with pytest.raises(RuntimeError, match='injected'):
            eng.generate_ids([1, 2, 3, 4, 5], max_new_tokens=4,
                             timeout=30)
        stats = eng.stats()
        assert stats['prefill_errors'] == 1
        assert stats['request_errors'] == 1
        assert stats['requests'] == (stats['completions'] +
                                     stats['request_errors'])
        assert stats['blocks_free'] == stats['blocks_total']
        monkeypatch.undo()
        # The engine keeps serving after the failure.
        out = eng.generate_ids([5, 6, 7], max_new_tokens=4)
        assert out == _reference_greedy(eng, [5, 6, 7], 4)
    finally:
        eng.shutdown()


def test_queue_wait_metric_advances(paged_engine):
    before = paged_engine.stats()
    paged_engine.generate_ids([1, 2, 3], max_new_tokens=2)
    after = paged_engine.stats()
    assert after['queue_wait_seconds'] >= before['queue_wait_seconds']
    assert after['completions'] == before['completions'] + 1


# ---------------------------------------------------------------------------
# Decode cadence under chunked prefill (tier-1 latency smoke)
# ---------------------------------------------------------------------------

@pytest.mark.latency
def test_decode_cadence_bounded_while_long_prompt_prefills(paged_engine):
    """Sarathi property, structurally: while a LONG prompt is being
    absorbed, an already-decoding request keeps emitting tokens —
    chunks interleave with decode steps instead of freezing the loop
    for the whole prefill. Asserted on interleaving order (per-chunk
    scheduling is deterministic), with only a generous wall-clock
    sanity bound — never exact timings."""
    eng = paged_engine
    chunks_before = eng.stats()['prefill_chunks']
    long_ids = [(i * 7 + 1) % 512 for i in range(80)]  # 10 chunks
    short = eng.stream_ids([3, 1, 4, 1], max_new_tokens=40,
                           timeout=120)
    first = next(short)                    # short is decoding
    assert isinstance(first, int)
    long_done = threading.Event()
    long_out = {}

    def run_long():
        long_out['ids'] = eng.generate_ids(long_ids,
                                           max_new_tokens=2,
                                           timeout=120)
        long_done.set()

    thread = threading.Thread(target=run_long)
    thread.start()
    interleaved = 0
    gaps = []
    last = time.monotonic()
    for tok in short:
        now = time.monotonic()
        gaps.append(now - last)
        last = now
        if not long_done.is_set():
            interleaved += 1
    thread.join(timeout=120)
    # The short request made progress DURING the long absorb: with
    # one chunk per decode step, ~10 chunks must interleave >= a
    # couple of short-request tokens before the long one finishes.
    assert interleaved >= 2, (interleaved, gaps)
    # Generous sanity bound: no single inter-token stall anywhere
    # near the full-prefill freeze of the old inline path.
    assert max(gaps) < 5.0, max(gaps)
    assert len(long_out['ids']) == 2
    assert eng.stats()['prefill_chunks'] >= chunks_before + 10


def test_engine_emits_trace_spans_per_request(paged_engine, tmp_home,
                                              monkeypatch):
    """Distributed tracing through the engine: a request submitted with
    a trace context records an infer.request span with queue-wait /
    prefill-chunk / decode children sharing the caller's trace_id
    (docs/observability.md)."""
    from skypilot_tpu.utils import trace_store, tracing
    monkeypatch.setenv('SKYT_TRACE_SAMPLE', '1')
    tracing.reset_for_tests()
    ctx = tracing.SpanContext.new_root()
    ids = [(3 * i + 5) % 512 for i in range(21)]  # 3 chunks
    out = paged_engine.generate_ids(ids, max_new_tokens=4,
                                    trace_ctx=ctx)
    assert len(out) <= 4
    spans = trace_store.load_trace(ctx.trace_id)
    by_name = {}
    for s in spans:
        by_name.setdefault(s['name'], []).append(s)
    assert len(by_name['infer.request']) == 1
    request_span = by_name['infer.request'][0]
    assert request_span['parent_span_id'] == ctx.span_id
    assert request_span['annotations']['tokens'] == len(out)
    assert len(by_name['infer.prefill_chunk']) >= 3
    for child_name in ('infer.queue_wait', 'infer.prefill_chunk',
                       'infer.decode'):
        for child in by_name[child_name]:
            assert child['parent_span_id'] == request_span['span_id']
    decode = by_name['infer.decode'][0]
    assert decode['annotations']['tokens'] == len(out)
    # Untraced requests stay span-free (no ctx -> no bookkeeping).
    paged_engine.generate_ids([1, 2, 3], max_new_tokens=2)
    assert len(trace_store.load_trace(ctx.trace_id)) == len(spans)
    tracing.reset_for_tests()
