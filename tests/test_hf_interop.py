"""Real-model interop (VERDICT r2 missing #1): HF safetensors <->
stacked pytree converters, verified for *numerical parity against
transformers' own Llama implementation* (torch CPU), plus the real BPE
tokenizer behind the engine interface.

Zero-egress CI: checkpoints are synthesized in-test with transformers
(random weights, HF layout on disk) — exactly the artifact a published
Llama-3 checkpoint is, minus the download.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import hf_interop, llama
from skypilot_tpu.models.config import get_model_config

transformers = pytest.importorskip('transformers')
torch = pytest.importorskip('torch')


def _tiny_hf_config(**kw):
    defaults = dict(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, rope_theta=10_000.0, rms_norm_eps=1e-5,
        max_position_embeddings=256, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False)
    defaults.update(kw)
    return transformers.LlamaConfig(**defaults)


def _save_tiny_llama(tmp_path, **kw):
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(_tiny_hf_config(**kw))
    model.eval()
    out = str(tmp_path / 'ckpt')
    model.save_pretrained(out, safe_serialization=True)
    return model, out


def _our_logits(out_dir, tokens, **overrides):
    params, cfg = hf_interop.load_checkpoint(
        out_dir, dtype=jnp.float32,
        compute_dtype=jnp.float32, attention_impl='xla', **overrides)
    return np.asarray(
        llama.forward(params, jnp.asarray(tokens), cfg)), cfg


def _hf_logits(model, tokens):
    with torch.no_grad():
        return model(torch.tensor(tokens)).logits.numpy()


# r20 triage: 7s transformers import + forward
@pytest.mark.slow
def test_forward_matches_transformers_llama():
    """Loaded checkpoint produces the same logits as transformers'
    LlamaForCausalLM — the end-to-end conversion correctness proof
    (layout, transposes, GQA, rope convention, rms-norm)."""
    import tempfile
    from pathlib import Path
    with tempfile.TemporaryDirectory() as td:
        model, out = _save_tiny_llama(Path(td))
        tokens = np.random.RandomState(0).randint(0, 128, (2, 17))
        ours, cfg = _our_logits(out, tokens)
        theirs = _hf_logits(model, tokens)
        assert cfg.n_kv_heads == 2 and cfg.rope_theta == 10_000.0
        np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)


def test_forward_matches_transformers_tied_embeddings():
    import tempfile
    from pathlib import Path
    with tempfile.TemporaryDirectory() as td:
        model, out = _save_tiny_llama(Path(td), tie_word_embeddings=True)
        tokens = np.random.RandomState(1).randint(0, 128, (1, 9))
        ours, cfg = _our_logits(out, tokens)
        assert cfg.tie_embeddings
        np.testing.assert_allclose(ours, _hf_logits(model, tokens),
                                   atol=2e-4, rtol=2e-4)


def test_forward_matches_transformers_llama3_rope_scaling():
    """Llama-3.1's NTK rope scaling (HF rope_type='llama3')."""
    import tempfile
    from pathlib import Path
    scaling = {'rope_type': 'llama3', 'factor': 8.0,
               'low_freq_factor': 1.0, 'high_freq_factor': 4.0,
               'original_max_position_embeddings': 64}
    with tempfile.TemporaryDirectory() as td:
        model, out = _save_tiny_llama(Path(td), rope_scaling=scaling,
                                      max_position_embeddings=512)
        tokens = np.random.RandomState(2).randint(0, 128, (1, 130))
        ours, cfg = _our_logits(out, tokens)
        assert cfg.rope_scaling == (8.0, 1.0, 4.0, 64)
        np.testing.assert_allclose(ours, _hf_logits(model, tokens),
                                   atol=3e-4, rtol=3e-4)


def test_roundtrip_export_import_exact():
    cfg = get_model_config('tiny')
    params = llama.init_params(jax.random.key(0), cfg)
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        hf_interop.save_checkpoint(params, cfg, td)
        params2, cfg2 = hf_interop.load_checkpoint(
            td, dtype=jnp.float32)
        assert cfg2.d_model == cfg.d_model
        assert cfg2.n_kv_heads == cfg.n_kv_heads
        flat1 = jax.tree_util.tree_leaves_with_path(params)
        flat2 = dict(jax.tree_util.tree_leaves_with_path(params2))
        for path, leaf in flat1:
            np.testing.assert_array_equal(
                np.asarray(leaf), np.asarray(flat2[path]),
                err_msg=str(path))


def test_roundtrip_moe_export_import():
    cfg = get_model_config('tiny-moe')
    params = llama.init_params(jax.random.key(1), cfg)
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        hf_interop.save_checkpoint(params, cfg, td)
        params2, cfg2 = hf_interop.load_checkpoint(td, dtype=jnp.float32)
        assert cfg2.num_experts == cfg.num_experts
        np.testing.assert_array_equal(
            np.asarray(params['layers']['moe']['wi_gate']),
            np.asarray(params2['layers']['moe']['wi_gate']))


def test_export_loadable_by_transformers():
    """The other direction: our export opens in transformers and agrees
    logit-for-logit — the finetune-then-publish path."""
    cfg = get_model_config(
        'tiny', compute_dtype=jnp.float32, attention_impl='xla')
    params = llama.init_params(jax.random.key(2), cfg)
    tokens = np.random.RandomState(3).randint(0, cfg.vocab_size, (1, 11))
    ours = np.asarray(llama.forward(params, jnp.asarray(tokens), cfg))
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        hf_interop.save_checkpoint(params, cfg, td)
        model = transformers.LlamaForCausalLM.from_pretrained(td)
        model.eval()
        np.testing.assert_allclose(ours, _hf_logits(model, tokens),
                                   atol=2e-4, rtol=2e-4)


def test_sharded_checkpoint_with_index():
    """Multi-shard checkpoints (model.safetensors.index.json)."""
    import tempfile
    from pathlib import Path
    with tempfile.TemporaryDirectory() as td:
        model, out = _save_tiny_llama(Path(td))
        # Re-shard by hand: split tensors across two files + index.
        reader = hf_interop.SafetensorsReader(
            os.path.join(out, 'model.safetensors'))
        names = reader.keys()
        half = len(names) // 2
        shards = {'model-00001-of-00002.safetensors': names[:half],
                  'model-00002-of-00002.safetensors': names[half:]}
        weight_map = {}
        for fn, keys in shards.items():
            hf_interop.write_safetensors(
                os.path.join(out, fn),
                {k: np.asarray(reader.get(k)) for k in keys})
            weight_map.update({k: fn for k in keys})
        reader.close()
        os.remove(os.path.join(out, 'model.safetensors'))
        with open(os.path.join(out,
                               'model.safetensors.index.json'), 'w') as f:
            json.dump({'weight_map': weight_map}, f)
        tokens = np.random.RandomState(4).randint(0, 128, (1, 8))
        ours, _ = _our_logits(out, tokens)
        np.testing.assert_allclose(ours, _hf_logits(model, tokens),
                                   atol=2e-4, rtol=2e-4)


def test_bf16_safetensors_roundtrip():
    import ml_dtypes
    import tempfile
    arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4) / 7
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, 'x.safetensors')
        hf_interop.write_safetensors(
            path, {'a': arr.astype(ml_dtypes.bfloat16)})
        with hf_interop.SafetensorsReader(path) as r:
            got = r.get('a')
        assert got.dtype == ml_dtypes.bfloat16
        np.testing.assert_allclose(got.astype(np.float32), arr,
                                   atol=1e-2)


def test_reader_matches_safetensors_library():
    """Cross-validate the in-tree container writer against the official
    safetensors parser."""
    from safetensors.numpy import load_file
    import tempfile
    tensors = {'w': np.random.RandomState(0).randn(3, 5).astype(np.float32),
               'b': np.arange(5, dtype=np.int32)}
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, 'x.safetensors')
        hf_interop.write_safetensors(path, tensors)
        loaded = load_file(path)
        for k, v in tensors.items():
            np.testing.assert_array_equal(loaded[k], v)


def test_unmapped_tensor_raises():
    import tempfile
    from pathlib import Path
    with tempfile.TemporaryDirectory() as td:
        _, out = _save_tiny_llama(Path(td))
        # Corrupt: add a stray tensor.
        extra = os.path.join(out, 'model.safetensors')
        with hf_interop.SafetensorsReader(extra) as r:
            tensors = {k: np.asarray(r.get(k)) for k in r.keys()}
        tensors['model.layers.0.self_attn.q_proj.bias'] = (
            np.zeros(4, np.float32))
        hf_interop.write_safetensors(extra, tensors)
        with pytest.raises(ValueError, match='unmapped'):
            hf_interop.load_checkpoint(out, dtype=jnp.float32)


def test_redundant_tied_head_and_inv_freq_skipped():
    """Community exports often ship the tied lm_head and legacy
    rotary inv_freq buffers — both must be skipped, not fatal."""
    import tempfile
    from pathlib import Path
    with tempfile.TemporaryDirectory() as td:
        model, out = _save_tiny_llama(Path(td), tie_word_embeddings=True)
        st = os.path.join(out, 'model.safetensors')
        with hf_interop.SafetensorsReader(st) as r:
            tensors = {k: np.asarray(r.get(k)) for k in r.keys()}
        tensors['lm_head.weight'] = np.asarray(
            tensors['model.embed_tokens.weight'])
        tensors['model.layers.0.self_attn.rotary_emb.inv_freq'] = (
            np.zeros(8, np.float32))
        hf_interop.write_safetensors(st, tensors)
        tokens = np.random.RandomState(5).randint(0, 128, (1, 7))
        ours, _ = _our_logits(out, tokens)
        np.testing.assert_allclose(ours, _hf_logits(model, tokens),
                                   atol=2e-4, rtol=2e-4)


def test_qwen2_and_gemma_rejected_clearly():
    with pytest.raises(ValueError, match='qwen2'):
        hf_interop.config_from_hf({'model_type': 'qwen2'})
    with pytest.raises(ValueError, match='gemma'):
        hf_interop.config_from_hf({'model_type': 'gemma'})
