"""Serve controller offload over the SHARED POSTGRES backend: the
offloaded controller process reads/writes services + replicas through
SKYT_DB_URL (the deployment where the controller cluster has no
filesystem in common with the API server beyond the runtime tarball).
Completes the HA story: serve state is replica-visible the same way
cluster/jobs/requests state is."""
import time
import urllib.request

import pytest

from skypilot_tpu import core as sky_core
from skypilot_tpu import execution, state
from skypilot_tpu.provision import fake
from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve import serve_state
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task

from tests.fake_pg import FakePgServer

ECHO_SERVER = ('python3 -m http.server "$SKYT_SERVE_REPLICA_PORT" '
               '--bind 127.0.0.1')


@pytest.fixture()
def pg_offload(tmp_home, monkeypatch):
    server = FakePgServer()
    monkeypatch.setenv('SKYT_DB_URL', server.url)
    for mod in (state, serve_state):
        mod._local.__dict__.clear()
    from skypilot_tpu.jobs import state as jobs_state
    jobs_state._local.__dict__.clear()
    monkeypatch.setenv('SKYT_SERVE_CONTROLLER_POLL', '0.2')
    monkeypatch.setenv('SKYT_SERVE_NOT_READY_THRESHOLD', '2')
    monkeypatch.setenv('SKYT_SERVE_LB_HOST', '127.0.0.1')
    monkeypatch.setenv('SKYT_SERVE_ENDPOINT_HOST', '127.0.0.1')
    fake.reset()
    execution.launch(
        Task(name='ctl',
             resources=Resources(cloud='fake', accelerators='tpu-v5e-8')),
        cluster_name='pg-ctl')
    monkeypatch.setenv('SKYT_SERVE_CONTROLLER_CLUSTER', 'pg-ctl')
    yield server
    for record in serve_state.list_services():
        try:
            serve_core.down(record.name, purge=True)
        except Exception:  # pylint: disable=broad-except
            pass
    for mod in (state, serve_state):
        mod._local.__dict__.clear()
    fake.reset()
    server.close()


def test_offloaded_service_over_shared_postgres(pg_offload):
    task = Task(name='svc', run=ECHO_SERVER,
                resources=Resources(cloud='fake',
                                    accelerators='tpu-v5e-8'),
                service={'readiness_probe': {'path': '/',
                                             'initial_delay_seconds': 30,
                                             'timeout_seconds': 2},
                         'replicas': 1})
    result = serve_core.up(task, 'pgsvc')
    deadline = time.time() + 150
    while time.time() < deadline:
        record = serve_state.get_service('pgsvc')
        if record and record.status.value == 'READY':
            break
        time.sleep(0.3)
    record = serve_state.get_service('pgsvc')
    assert record is not None and record.status.value == 'READY', (
        f'{record.status.value if record else None}; log:\n'
        f'{serve_core.tail_logs("pgsvc")[-3000:]}')
    assert record.controller_cluster == 'pg-ctl'

    # The rows physically live in the shared Postgres: read them from
    # the fake server's backing store directly, bypassing every
    # skypilot code path.
    rows = pg_offload._sqlite.execute(
        'SELECT name, controller_cluster, status FROM services'
    ).fetchall()
    assert [(r['name'], r['controller_cluster']) for r in rows] == [
        ('pgsvc', 'pg-ctl')]
    replicas = pg_offload._sqlite.execute(
        "SELECT status FROM replicas WHERE service_name='pgsvc'"
    ).fetchall()
    assert any(r['status'] == 'READY' for r in replicas)

    # And it actually serves.
    with urllib.request.urlopen(record.endpoint, timeout=10) as resp:
        assert resp.status == 200

    serve_core.down('pgsvc')
    deadline = time.time() + 90
    while serve_state.get_service('pgsvc') and time.time() < deadline:
        time.sleep(0.3)
    assert serve_state.get_service('pgsvc') is None
