"""Kubernetes/GKE TPU provider tests: selector/manifest generation,
fake-apiserver lifecycle, multi-host slices end-to-end (closing the
reference's utils.py:1299-1301 multi-host gap), and capacity failover
(the reference covers this area with tests/unit_tests/kubernetes/)."""
import os

import pytest

from skypilot_tpu import core, exceptions, execution, state
from skypilot_tpu.provision import kubernetes as k8s
from skypilot_tpu.provision.api import ProvisionRequest
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task


@pytest.fixture(autouse=True)
def fake_k8s(tmp_home, monkeypatch):
    from skypilot_tpu import check
    monkeypatch.setenv('SKYT_K8S_FAKE', '1')
    monkeypatch.setenv('SKYT_K8S_PROVISION_TIMEOUT', '2')
    # The credential-probe cache is process-global; this fixture changes
    # the env the kubernetes probe reads, so stale entries must go.
    check.clear_cache()
    k8s.fake_reset()
    yield
    k8s.fake_reset()
    check.clear_cache()


def _request(accel='tpu-v5e-8', cluster='kc', num_nodes=1, **res_kw):
    return ProvisionRequest(
        cluster_name=cluster,
        resources=Resources(cloud='kubernetes', accelerators=accel,
                            **res_kw),
        num_nodes=num_nodes, region='default', zone=None)


# -- manifest generation ----------------------------------------------------


def test_gke_selectors_normalize_accelerator_names():
    res = Resources(cloud='kubernetes', accelerators='tpu-v6e-8')
    sel = k8s.gke_tpu_selectors(res)
    assert sel['cloud.google.com/gke-tpu-accelerator'] == 'tpu-v6e-slice'
    assert sel['cloud.google.com/gke-tpu-topology'] == '2x4'
    res5e = Resources(cloud='kubernetes', accelerators='tpu-v5e-16')
    sel5e = k8s.gke_tpu_selectors(res5e)
    assert (sel5e['cloud.google.com/gke-tpu-accelerator'] ==
            'tpu-v5-lite-podslice')
    res5p = Resources(cloud='kubernetes', accelerators='tpu-v5p-64')
    assert (k8s.gke_tpu_selectors(res5p)[
        'cloud.google.com/gke-tpu-accelerator'] == 'tpu-v5p-slice')


def test_pod_manifest_tpu_requests_and_spot():
    req = _request(accel='tpu-v5e-16', use_spot=True)
    pod = k8s.build_pod_manifest(req, node=0, worker=1, namespace='ns')
    assert pod['metadata']['name'] == 'kc-0-1'
    container = pod['spec']['containers'][0]
    # v5e-16 = 2 hosts x 8 chips: each pod requests its host's chips.
    assert container['resources']['requests']['google.com/tpu'] == '8'
    assert pod['spec']['nodeSelector'][
        'cloud.google.com/gke-spot'] == 'true'
    assert pod['spec']['subdomain'] == 'kc'
    assert pod['metadata']['labels'][k8s.LABEL_WORKER] == '1'


# -- provider lifecycle on the fake apiserver -------------------------------


def test_provider_multihost_slice_lifecycle():
    provider = k8s.KubernetesProvider()
    info = provider.run_instances(_request(accel='tpu-v5e-32'))
    # v5e-32 = 4 hosts, all of one slice, one pod each.
    assert len(info.hosts) == 4
    assert [h.worker_index for h in info.hosts] == [0, 1, 2, 3]
    assert all(h.internal_ip for h in info.hosts)
    states = provider.query_instances('kc')
    assert set(states.values()) == {'running'} and len(states) == 4
    with pytest.raises(exceptions.NotSupportedError):
        provider.stop_instances('kc')
    provider.terminate_instances('kc')
    assert provider.query_instances('kc') == {}
    assert provider.get_cluster_info('kc') is None


def test_provider_unschedulable_raises_capacity_error():
    k8s.fake_inject_unschedulable('tpu-v5-lite-podslice')
    provider = k8s.KubernetesProvider()
    with pytest.raises(exceptions.CapacityError, match='unschedulable'):
        provider.run_instances(_request())
    # Gang rollback: no orphan pods left behind.
    assert provider.query_instances('kc') == {}


# -- end to end through the launch path -------------------------------------


def test_launch_on_kubernetes_multihost_rank_envs():
    task = Task(name='kt',
                run='echo "rank=$TPU_WORKER_ID of $JAX_NUM_PROCESSES"',
                resources=Resources(cloud='kubernetes',
                                    accelerators='tpu-v5e-16'))
    results = execution.launch(task, cluster_name='ke2e')
    assert results == [('ke2e', 1)]
    record = state.get_cluster('ke2e')
    assert record.status == state.ClusterStatus.UP
    assert record.cloud == 'kubernetes'
    jobs = core.queue('ke2e')
    assert jobs[0]['status'] == 'SUCCEEDED'
    log0 = core.tail_logs('ke2e', 1)
    assert 'rank=0 of 2' in log0
    core.down('ke2e')
    assert k8s.KubernetesProvider().query_instances('ke2e') == {}


def test_failover_from_k8s_capacity_to_success(monkeypatch):
    """One-shot unschedulable fault -> the provisioner retries and the
    second attempt lands (failover machinery is provider-agnostic)."""
    k8s.fake_inject_unschedulable('tpu-v5-lite-podslice', count=1)
    task = Task(name='kf', run='echo ok',
                resources=Resources(cloud='kubernetes',
                                    accelerators='tpu-v5e-8'))
    results = execution.launch(task, cluster_name='kfo')
    assert results == [('kfo', 1)]
    assert state.get_cluster('kfo').status == state.ClusterStatus.UP

def test_find_kubeconfig_colon_separated(tmp_path, monkeypatch):
    real = tmp_path / 'gke.yaml'
    real.write_text('{}')
    monkeypatch.setenv('KUBECONFIG',
                       f'{tmp_path}/missing.yaml{os.pathsep}{real}')
    assert k8s.find_kubeconfig() == str(real)
    monkeypatch.setenv('KUBECONFIG', f'{tmp_path}/nope.yaml')
    assert k8s.find_kubeconfig() is None


def test_exec_plugin_token(tmp_path):
    plugin = tmp_path / 'fake-auth-plugin'
    plugin.write_text('#!/bin/sh\n'
                      'echo \'{"apiVersion":"client.authentication.k8s.io/'
                      'v1beta1","kind":"ExecCredential",'
                      '"status":{"token":"tok-123"}}\'\n')
    plugin.chmod(0o755)
    token = k8s.RestKubernetesApi._exec_plugin_token(
        {'exec': {'command': str(plugin), 'args': []}})
    assert token == 'tok-123'
    with pytest.raises(exceptions.NoCloudAccessError):
        k8s.RestKubernetesApi._exec_plugin_token(
            {'exec': {'command': '/no/such/plugin'}})
