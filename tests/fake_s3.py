"""In-process S3-compatible endpoint for store tests (moto-style, per
SURVEY §4: 'a moto-equivalent fake' for offline provider testing).

Implements the path-style subset the client uses: bucket HEAD/PUT/DELETE,
object PUT/GET/DELETE with ETag + ranged GET (206), multipart upload
(initiate / UploadPart / complete), ListObjectsV2 with prefix +
pagination + Size/ETag metadata. Requires a SigV4 Authorization header
on every request (verifying the client signs) but does not validate the
signature.

Knobs for bench/latency tests:
* ``latency`` — seconds slept before serving each request (models RTT;
  a serial client pays it once per object, a parallel one amortizes);
* ``bandwidth`` — bytes/sec throttle per response body (models
  per-connection throughput; parallel ranged GETs of one object stream
  over independent connections and multiply it);
* ``page_size`` — ListObjectsV2 page length (2 by default so ordinary
  tests exercise pagination; benches raise it to realistic values).

``server.state.counters`` tallies operations ('put_object',
'get_object', 'get_range', 'put_part', 'list', ...) so delta-sync tests
can assert a warm re-sync moved ZERO object bodies.
"""
from __future__ import annotations

import collections
import hashlib
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Tuple
from xml.sax.saxutils import escape


class _State:
    def __init__(self) -> None:
        self.buckets: Dict[str, Dict[str, bytes]] = {}
        self.etags: Dict[Tuple[str, str], str] = {}
        self.uploads: Dict[str, Dict] = {}  # id -> {bucket,key,parts}
        self.counters: collections.Counter = collections.Counter()
        self.next_upload_id = 0
        self.lock = threading.Lock()

    def record_put(self, bucket: str, key: str, data: bytes) -> str:
        etag = hashlib.md5(data).hexdigest()
        self.buckets[bucket][key] = data
        self.etags[(bucket, key)] = etag
        return etag


def _handler_for(state: _State, latency: float, bandwidth,
                 page_size: int):

    class Handler(BaseHTTPRequestHandler):
        protocol_version = 'HTTP/1.1'

        def log_message(self, *a):  # quiet
            pass

        def setup(self):
            # One TCP connection = one handler setup (keep-alive reuse
            # tests assert parallel ranged GETs don't re-dial per part).
            with state.lock:
                state.counters['connections'] += 1
            super().setup()

        def _split(self):
            parsed = urllib.parse.urlparse(self.path)
            parts = parsed.path.lstrip('/').split('/', 1)
            bucket = parts[0]
            key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ''
            query = {k: v[0] for k, v in
                     urllib.parse.parse_qs(
                         parsed.query, keep_blank_values=True).items()}
            return bucket, key, query

        def _write_throttled(self, body: bytes) -> None:
            if not bandwidth:
                self.wfile.write(body)
                return
            chunk = 256 * 1024
            for off in range(0, len(body), chunk):
                piece = body[off:off + chunk]
                self.wfile.write(piece)
                time.sleep(len(piece) / bandwidth)

        def _read_body(self) -> bytes:
            """Request-body read with the same per-connection throttle
            (models upload bandwidth for multipart-vs-single PUTs)."""
            length = int(self.headers.get('Content-Length', 0))
            if not length:
                return b''
            if not bandwidth:
                return self.rfile.read(length)
            pieces = []
            remaining = length
            while remaining > 0:
                piece = self.rfile.read(min(256 * 1024, remaining))
                if not piece:
                    break
                pieces.append(piece)
                remaining -= len(piece)
                time.sleep(len(piece) / bandwidth)
            return b''.join(pieces)

        def _reply(self, code: int, body: bytes = b'',
                   ctype: str = 'application/xml',
                   headers: Dict[str, str] = None):
            self.send_response(code)
            self.send_header('Content-Type', ctype)
            self.send_header('Content-Length', str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            if body:
                self._write_throttled(body)

        def _check_auth(self) -> bool:
            if latency:
                time.sleep(latency)
            auth = self.headers.get('Authorization', '')
            if not auth.startswith('AWS4-HMAC-SHA256'):
                self._reply(403, b'<Error><Code>AccessDenied</Code></Error>')
                return False
            return True

        def do_HEAD(self):  # noqa: N802
            if not self._check_auth():
                return
            bucket, key, _ = self._split()
            with state.lock:
                state.counters['head'] += 1
                if bucket not in state.buckets:
                    self._reply(404)
                elif key and key not in state.buckets[bucket]:
                    self._reply(404)
                elif key:
                    obj = state.buckets[bucket][key]
                    self.send_response(200)
                    self.send_header('Content-Length', str(len(obj)))
                    self.send_header(
                        'ETag', f'"{state.etags[(bucket, key)]}"')
                    self.end_headers()
                else:
                    self._reply(200)

        def do_PUT(self):  # noqa: N802
            if not self._check_auth():
                return
            bucket, key, query = self._split()
            data = self._read_body()
            with state.lock:
                if not key:
                    state.buckets.setdefault(bucket, {})
                    self._reply(200)
                    return
                if bucket not in state.buckets:
                    self._reply(404, b'<Error><Code>NoSuchBucket</Code>'
                                     b'</Error>')
                    return
                if 'partNumber' in query and 'uploadId' in query:
                    upload = state.uploads.get(query['uploadId'])
                    if upload is None:
                        self._reply(404, b'<Error><Code>NoSuchUpload'
                                         b'</Code></Error>')
                        return
                    part_no = int(query['partNumber'])
                    upload['parts'][part_no] = data
                    state.counters['put_part'] += 1
                    etag = hashlib.md5(data).hexdigest()
                    self._reply(200, headers={'ETag': f'"{etag}"'})
                    return
                etag = state.record_put(bucket, key, data)
                state.counters['put_object'] += 1
            self._reply(200, headers={'ETag': f'"{etag}"'})

        def do_POST(self):  # noqa: N802
            if not self._check_auth():
                return
            bucket, key, query = self._split()
            length = int(self.headers.get('Content-Length', 0))
            body = self.rfile.read(length) if length else b''
            with state.lock:
                if bucket not in state.buckets:
                    self._reply(404, b'<Error><Code>NoSuchBucket</Code>'
                                     b'</Error>')
                    return
                if 'uploads' in query:
                    state.next_upload_id += 1
                    upload_id = f'upload-{state.next_upload_id}'
                    state.uploads[upload_id] = {
                        'bucket': bucket, 'key': key, 'parts': {}}
                    state.counters['initiate'] += 1
                    xml = (f'<?xml version="1.0"?>'
                           f'<InitiateMultipartUploadResult>'
                           f'<Bucket>{escape(bucket)}</Bucket>'
                           f'<Key>{escape(key)}</Key>'
                           f'<UploadId>{upload_id}</UploadId>'
                           f'</InitiateMultipartUploadResult>')
                    self._reply(200, xml.encode())
                    return
                if 'uploadId' in query:
                    upload = state.uploads.pop(query['uploadId'], None)
                    if upload is None or upload['key'] != key:
                        self._reply(404, b'<Error><Code>NoSuchUpload'
                                         b'</Code></Error>')
                        return
                    parts = [upload['parts'][n]
                             for n in sorted(upload['parts'])]
                    blob = b''.join(parts)
                    # Real S3 multipart ETag: md5 of the binary part
                    # md5s, dash, part count.
                    md5s = b''.join(hashlib.md5(p).digest()
                                    for p in parts)
                    etag = (f'{hashlib.md5(md5s).hexdigest()}'
                            f'-{len(parts)}')
                    state.buckets[bucket][key] = blob
                    state.etags[(bucket, key)] = etag
                    state.counters['complete'] += 1
                    xml = (f'<?xml version="1.0"?>'
                           f'<CompleteMultipartUploadResult>'
                           f'<Key>{escape(key)}</Key>'
                           f'<ETag>"{etag}"</ETag>'
                           f'</CompleteMultipartUploadResult>')
                    self._reply(200, xml.encode())
                    return
            self._reply(400, b'<Error><Code>InvalidRequest</Code>'
                             b'</Error>')

        def do_GET(self):  # noqa: N802
            if not self._check_auth():
                return
            bucket, key, query = self._split()
            if key:
                # Capture under the lock, stream OUTSIDE it — a
                # bandwidth-throttled body write must not serialize the
                # other connections.
                with state.lock:
                    if bucket not in state.buckets:
                        self._reply(404, b'<Error><Code>NoSuchBucket'
                                         b'</Code></Error>')
                        return
                    payload = state.buckets[bucket].get(key)
                    if payload is None:
                        self._reply(404, b'<Error><Code>NoSuchKey'
                                         b'</Code></Error>')
                        return
                    etag = state.etags[(bucket, key)]
                    rng = self.headers.get('Range', '')
                    if rng.startswith('bytes='):
                        state.counters['get_range'] += 1
                    else:
                        state.counters['get_object'] += 1
                if rng.startswith('bytes='):
                    start_s, _, end_s = rng[len('bytes='):].partition('-')
                    start = int(start_s)
                    end = int(end_s) if end_s else len(payload) - 1
                    end = min(end, len(payload) - 1)
                    self._reply(
                        206, payload[start:end + 1],
                        ctype='application/octet-stream',
                        headers={
                            'ETag': f'"{etag}"',
                            'Content-Range':
                                f'bytes {start}-{end}/{len(payload)}',
                        })
                    return
                self._reply(200, payload,
                            ctype='application/octet-stream',
                            headers={'ETag': f'"{etag}"'})
                return
            with state.lock:
                if bucket not in state.buckets:
                    self._reply(404, b'<Error><Code>NoSuchBucket</Code>'
                                     b'</Error>')
                    return
                objs = state.buckets[bucket]
                # ListObjectsV2 with small pages to exercise pagination
                state.counters['list'] += 1
                prefix = query.get('prefix', '')
                token = query.get('continuation-token', '')
                keys = sorted(k for k in objs if k.startswith(prefix))
                if token:
                    keys = [k for k in keys if k > token]
                page, rest = keys[:page_size], keys[page_size:]
                contents = ''.join(
                    f'<Contents><Key>{escape(k)}</Key>'
                    f'<Size>{len(objs[k])}</Size>'
                    f'<ETag>&quot;{state.etags[(bucket, k)]}&quot;'
                    f'</ETag></Contents>'
                    for k in page)
                truncated = 'true' if rest else 'false'
                next_token = (f'<NextContinuationToken>{escape(page[-1])}'
                              f'</NextContinuationToken>'
                              if rest else '')
                xml = (f'<?xml version="1.0"?><ListBucketResult>'
                       f'<IsTruncated>{truncated}</IsTruncated>'
                       f'{contents}{next_token}</ListBucketResult>')
                self._reply(200, xml.encode())

        def do_DELETE(self):  # noqa: N802
            if not self._check_auth():
                return
            bucket, key, _ = self._split()
            with state.lock:
                state.counters['delete'] += 1
                if key:
                    state.buckets.get(bucket, {}).pop(key, None)
                    state.etags.pop((bucket, key), None)
                else:
                    for k in list(state.etags):
                        if k[0] == bucket:
                            state.etags.pop(k, None)
                    state.buckets.pop(bucket, None)
            self._reply(204)

    return Handler


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    # The stock backlog of 5 drops SYNs when 16+ workers dial at once;
    # the kernel retransmits after ~1 s, which would masquerade as a
    # fake-server 'latency' and poison parallel-transfer timings.
    request_queue_size = 128


class FakeS3Server:
    """`with FakeS3Server() as url:` -- a live endpoint on 127.0.0.1."""

    def __init__(self, latency: float = 0.0, bandwidth=None,
                 page_size: int = 2) -> None:
        self.state = _State()
        self.httpd = _Server(
            ('127.0.0.1', 0),
            _handler_for(self.state, latency, bandwidth, page_size))
        self.url = f'http://127.0.0.1:{self.httpd.server_address[1]}'

    def body_ops(self) -> int:
        """Requests that moved an object body (delta-sync warm re-syncs
        must not grow this)."""
        c = self.state.counters
        return (c['put_object'] + c['get_object'] + c['get_range'] +
                c['put_part'])

    def __enter__(self) -> 'FakeS3Server':
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        return self

    def __exit__(self, *exc) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
