"""In-process S3-compatible endpoint for store tests (moto-style, per
SURVEY §4: 'a moto-equivalent fake' for offline provider testing).

Implements the path-style subset the client uses: bucket HEAD/PUT/DELETE,
object PUT/GET/DELETE, ListObjectsV2 with prefix + pagination. Requires a
SigV4 Authorization header on every request (verifying the client signs)
but does not validate the signature."""
from __future__ import annotations

import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict
from xml.sax.saxutils import escape


class _State:
    def __init__(self) -> None:
        self.buckets: Dict[str, Dict[str, bytes]] = {}
        self.lock = threading.Lock()


def _handler_for(state: _State):

    class Handler(BaseHTTPRequestHandler):
        protocol_version = 'HTTP/1.1'

        def log_message(self, *a):  # quiet
            pass

        def _split(self):
            parsed = urllib.parse.urlparse(self.path)
            parts = parsed.path.lstrip('/').split('/', 1)
            bucket = parts[0]
            key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ''
            query = {k: v[0] for k, v in
                     urllib.parse.parse_qs(parsed.query).items()}
            return bucket, key, query

        def _reply(self, code: int, body: bytes = b'',
                   ctype: str = 'application/xml'):
            self.send_response(code)
            self.send_header('Content-Type', ctype)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

        def _check_auth(self) -> bool:
            auth = self.headers.get('Authorization', '')
            if not auth.startswith('AWS4-HMAC-SHA256'):
                self._reply(403, b'<Error><Code>AccessDenied</Code></Error>')
                return False
            return True

        def do_HEAD(self):  # noqa: N802
            if not self._check_auth():
                return
            bucket, key, _ = self._split()
            with state.lock:
                if bucket not in state.buckets:
                    self._reply(404)
                elif key and key not in state.buckets[bucket]:
                    self._reply(404)
                else:
                    self._reply(200)

        def do_PUT(self):  # noqa: N802
            if not self._check_auth():
                return
            bucket, key, _ = self._split()
            length = int(self.headers.get('Content-Length', 0))
            data = self.rfile.read(length) if length else b''
            with state.lock:
                if not key:
                    state.buckets.setdefault(bucket, {})
                    self._reply(200)
                    return
                if bucket not in state.buckets:
                    self._reply(404, b'<Error><Code>NoSuchBucket</Code>'
                                     b'</Error>')
                    return
                state.buckets[bucket][key] = data
            self._reply(200)

        def do_GET(self):  # noqa: N802
            if not self._check_auth():
                return
            bucket, key, query = self._split()
            with state.lock:
                if bucket not in state.buckets:
                    self._reply(404, b'<Error><Code>NoSuchBucket</Code>'
                                     b'</Error>')
                    return
                objs = state.buckets[bucket]
                if key:
                    if key not in objs:
                        self._reply(404, b'<Error><Code>NoSuchKey</Code>'
                                         b'</Error>')
                        return
                    self._reply(200, objs[key],
                                ctype='application/octet-stream')
                    return
                # ListObjectsV2 with small pages to exercise pagination
                prefix = query.get('prefix', '')
                token = query.get('continuation-token', '')
                keys = sorted(k for k in objs if k.startswith(prefix))
                if token:
                    keys = [k for k in keys if k > token]
                page, rest = keys[:2], keys[2:]
                contents = ''.join(
                    f'<Contents><Key>{escape(k)}</Key></Contents>'
                    for k in page)
                truncated = 'true' if rest else 'false'
                next_token = (f'<NextContinuationToken>{escape(page[-1])}'
                              f'</NextContinuationToken>'
                              if rest else '')
                xml = (f'<?xml version="1.0"?><ListBucketResult>'
                       f'<IsTruncated>{truncated}</IsTruncated>'
                       f'{contents}{next_token}</ListBucketResult>')
                self._reply(200, xml.encode())

        def do_DELETE(self):  # noqa: N802
            if not self._check_auth():
                return
            bucket, key, _ = self._split()
            with state.lock:
                if key:
                    state.buckets.get(bucket, {}).pop(key, None)
                else:
                    state.buckets.pop(bucket, None)
            self._reply(204)

    return Handler


class FakeS3Server:
    """`with FakeS3Server() as url:` -- a live endpoint on 127.0.0.1."""

    def __init__(self) -> None:
        self.state = _State()
        self.httpd = ThreadingHTTPServer(('127.0.0.1', 0),
                                         _handler_for(self.state))
        self.httpd.daemon_threads = True
        self.url = f'http://127.0.0.1:{self.httpd.server_address[1]}'

    def __enter__(self) -> 'FakeS3Server':
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        return self

    def __exit__(self, *exc) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
