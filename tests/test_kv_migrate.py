"""KV-block migration data plane (inference/kv_migrate.py): delta
manifests, digest verification, ranged resume, backpressure, and the
chaos sites `infer.kv_migrate.push` / `infer.kv_migrate.pull`."""
import hashlib

import pytest

from skypilot_tpu.inference import kv_migrate
from skypilot_tpu.inference.paged import chain_digests
from skypilot_tpu.server import metrics

from tests.fault_injection import clause, inject_faults

BS = 4


def _counter_value(counter, **labels):
    key = tuple(sorted(labels.items()))
    return counter._values.get(key, 0.0)


def _export(request_id='req-1', n_blocks=3, tail=b'tail-state'):
    ids = list(range(100, 100 + n_blocks * BS + 2))  # +2 partial tail
    digests = chain_digests(ids, BS)
    blocks = [bytes([7 + i]) * 64 for i in range(n_blocks)]
    return kv_migrate.KvExport(
        request_id=request_id, ids=ids, block_size=BS,
        digests=digests, blocks=blocks, tail=tail,
        meta={'seed': 42, 'generated': 0})


def _no_sleep(_seconds):
    pass


# -- export + manifest -------------------------------------------------


def test_manifest_carries_digests_and_shas_not_payloads():
    export = _export()
    manifest = export.manifest()
    assert manifest['request_id'] == 'req-1'
    assert manifest['block_size'] == BS
    assert manifest['n_tokens'] == len(export.ids)
    assert [r['digest'] for r in manifest['blocks']] == export.digests
    for row, payload in zip(manifest['blocks'], export.blocks):
        assert row['sha256'] == hashlib.sha256(payload).hexdigest()
        assert row['nbytes'] == len(payload)
        assert 'data' not in row
    assert manifest['tail']['nbytes'] == len(export.tail)
    assert manifest['meta']['seed'] == 42


def test_export_rejects_misaligned_digests():
    with pytest.raises(ValueError, match='digests'):
        kv_migrate.KvExport(
            request_id='r', ids=[1] * 8, block_size=4,
            digests=[1, 2, 3], blocks=[b'x'], tail=b'', meta={})


def test_exporter_put_get_pop_idempotent():
    exporter = kv_migrate.KvExporter()
    export = _export()
    exporter.put(export)
    assert exporter.request_ids() == ['req-1']
    assert exporter.get('req-1') is export
    assert exporter.pop('req-1') is export
    assert exporter.pop('req-1') is None  # idempotent
    with pytest.raises(KeyError):
        exporter.get('req-1')


# -- delta pull --------------------------------------------------------


def test_pull_moves_only_non_resident_blocks():
    metrics.reset_for_tests()
    exporter = kv_migrate.KvExporter()
    export = _export(n_blocks=4)
    exporter.put(export)
    puller = kv_migrate.KvPuller(
        kv_migrate.LocalKvSource(exporter), sleep=_no_sleep)
    # Decode side already holds the first two chain blocks.
    resident = export.digests[:2]
    pulled = puller.pull('req-1', resident_digests=resident)
    assert pulled.resident == 2
    assert pulled.moved == 2
    assert pulled.payloads[:2] == [None, None]
    assert pulled.payloads[2:] == export.blocks[2:]
    assert pulled.tail == export.tail
    assert _counter_value(metrics.KV_MIGRATE_BLOCKS,
                          outcome='resident') == 2
    assert _counter_value(metrics.KV_MIGRATE_BLOCKS,
                          outcome='moved') == 2
    # Only the moved payloads + tail crossed the wire.
    moved_bytes = sum(len(b) for b in export.blocks[2:]) + \
        len(export.tail)
    assert _counter_value(metrics.KV_MIGRATE_BYTES,
                          direction='pull') == moved_bytes


def test_corrupt_block_repulled_never_returned():
    metrics.reset_for_tests()
    exporter = kv_migrate.KvExporter()
    export = _export(n_blocks=1)
    exporter.put(export)
    flips = {'left': 1}

    def mutate(kind, key, data):
        if kind == 'block' and flips['left'] > 0:
            flips['left'] -= 1
            return b'\x00' + data[1:]
        return data

    puller = kv_migrate.KvPuller(
        kv_migrate.LocalKvSource(exporter, mutate=mutate),
        sleep=_no_sleep)
    pulled = puller.pull('req-1')
    assert pulled.payloads[0] == export.blocks[0]  # clean bytes won
    assert puller.corrupt_retries == 1
    assert _counter_value(metrics.KV_MIGRATE_BLOCKS,
                          outcome='corrupt_retry') == 1


def test_permanently_corrupt_block_raises_block_corrupt():
    exporter = kv_migrate.KvExporter()
    exporter.put(_export(n_blocks=1))
    puller = kv_migrate.KvPuller(
        kv_migrate.LocalKvSource(
            exporter, mutate=lambda k, key, d: b'\xff' * len(d)),
        retries=2, sleep=_no_sleep)
    with pytest.raises(kv_migrate.BlockCorrupt):
        puller.pull('req-1')


def test_dead_source_exhausts_retries():
    exporter = kv_migrate.KvExporter()  # empty: every lookup fails
    puller = kv_migrate.KvPuller(
        kv_migrate.LocalKvSource(exporter), retries=2, sleep=_no_sleep)
    with pytest.raises(kv_migrate.MigrationUnavailable):
        puller.pull('gone')
    assert puller.unavailable_retries == 3  # budget fully spent


# -- chaos sites -------------------------------------------------------


def test_pull_chaos_fault_is_retried_to_success():
    exporter = kv_migrate.KvExporter()
    export = _export()
    exporter.put(export)
    puller = kv_migrate.KvPuller(
        kv_migrate.LocalKvSource(exporter), sleep=_no_sleep)
    with inject_faults(clause('infer.kv_migrate.pull',
                              'ConnectionError', times=2)):
        pulled = puller.pull('req-1')
    assert pulled.moved == len(export.blocks)
    assert puller.unavailable_retries >= 1


def test_push_chaos_fault_sheds_with_retry_after():
    exporter = kv_migrate.KvExporter()
    exporter.put(_export())
    with inject_faults(clause('infer.kv_migrate.push', 'OSError',
                              times=1)):
        status, headers, _body = kv_migrate.handle_kv_get(
            '/kv/manifest/req-1', exporter)
        assert status == 503
        assert 'Retry-After' in headers
        # Next attempt (fault budget spent) serves normally.
        status, _headers, body = kv_migrate.handle_kv_get(
            '/kv/manifest/req-1', exporter)
    assert status == 200
    assert b'req-1' in body


# -- the HTTP surface --------------------------------------------------


def test_http_pull_end_to_end_with_shed_and_release():
    exporter = kv_migrate.KvExporter()
    export = _export(n_blocks=3)
    exporter.put(export)
    with kv_migrate.KvServer(exporter) as server:
        source = kv_migrate.HTTPKvSource(server.endpoint, timeout=10)
        puller = kv_migrate.KvPuller(source, sleep=_no_sleep)
        with inject_faults(clause('infer.kv_migrate.push', 'OSError',
                                  times=1)):
            # The 503+Retry-After shed surfaces as a retryable
            # MigrationUnavailable carrying the floor.
            pulled = puller.pull(
                'req-1', resident_digests=export.digests[:1])
        assert pulled.resident == 1
        assert pulled.payloads[1:] == export.blocks[1:]
        assert pulled.tail == export.tail
        assert puller.unavailable_retries >= 1
        source.release('req-1')
    assert len(exporter) == 0


def test_http_ranged_block_resume():
    exporter = kv_migrate.KvExporter()
    export = _export(n_blocks=1)
    exporter.put(export)
    with kv_migrate.KvServer(exporter) as server:
        source = kv_migrate.HTTPKvSource(server.endpoint, timeout=10)
        digest = export.digests[0]
        whole = b''.join(source.fetch_block('req-1', digest, 0))
        part = b''.join(source.fetch_block('req-1', digest, 10))
        assert whole == export.blocks[0]
        assert part == export.blocks[0][10:]


def test_handle_kv_get_unknown_paths():
    exporter = kv_migrate.KvExporter()
    exporter.put(_export())
    assert kv_migrate.handle_kv_get('/kv/manifest/nope',
                                    exporter)[0] == 404
    assert kv_migrate.handle_kv_get('/kv/block/req-1/123456',
                                    exporter)[0] == 404
    assert kv_migrate.handle_kv_get('/other', exporter)[0] == 404
    status, _h, _b = kv_migrate.handle_kv_release('/kv/release/nope',
                                                  exporter)
    assert status == 200  # idempotent release
