"""Disaggregated SLO autoscaling: the TTFT SLO sizes the prefill
fleet and the inter-token SLO sizes the decode fleet, each through its
own latency model, Little's-law inversion, and hysteresis track
(docs/disaggregated_serving.md)."""
import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.serve.autoscalers import (Autoscaler, DecisionOp,
                                            LoadStats)
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.serve.service_spec import ServiceSpec
from skypilot_tpu.serve.slo_autoscaler import DisaggSLOAutoscaler


def _spec(**kw):
    defaults = dict(min_replicas=1, max_replicas=32,
                    target_ttft_p99_ms=200.0,
                    target_intertoken_p99_ms=50.0,
                    upscale_delay_seconds=0, downscale_delay_seconds=0)
    defaults.update(kw)
    return ServiceSpec(**defaults)


class _R:
    def __init__(self, replica_id, status=ReplicaStatus.READY,
                 role='', warm_since=None):
        self.replica_id = replica_id
        self.status = status
        self.role = role
        self.is_spot = False
        self.is_fallback = False
        self.warm_since = warm_since
        self.cloud = self.region = self.zone = None


def _sim_clock(scaler):
    clock = {'t': 0.0}
    scaler._clock = lambda: clock['t']
    scaler._wall_clock = lambda: clock['t']
    return clock


def _prime(model, base, slope):
    for _ in range(10):
        model.observe(0.0, base)
        model.observe(10.0, base + slope * 10.0)


# -- spec selection ----------------------------------------------------------


def test_spec_pair_selects_disagg_autoscaler():
    assert isinstance(Autoscaler.from_spec(_spec()), DisaggSLOAutoscaler)


def test_spec_rejects_half_a_pair():
    with pytest.raises(exceptions.InvalidSpecError, match='BOTH'):
        ServiceSpec(min_replicas=1, max_replicas=4,
                    target_ttft_p99_ms=200.0)


def test_spec_rejects_mixing_with_other_targets():
    with pytest.raises(exceptions.InvalidSpecError, match='only one'):
        ServiceSpec(min_replicas=1, max_replicas=4,
                    target_latency_p99_ms=100.0,
                    target_ttft_p99_ms=200.0,
                    target_intertoken_p99_ms=50.0)


def test_spec_round_trips_disagg_targets():
    spec = _spec()
    again = ServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert again.target_ttft_p99_ms == 200.0
    assert again.target_intertoken_p99_ms == 50.0
    assert again.disaggregated


# -- independent sizing ------------------------------------------------------


def test_two_inversions_size_fleets_independently():
    """TTFT line: base 50 slope 10 against 200ms -> n_pre =
    qps/1000 * 10*200/150 = qps/75. Inter-token line: base 10 slope 1
    against 50ms with 20 tokens/request -> n_dec =
    qps/1000 * 20 * 1*50/40 = qps/40. At 300 qps: 4 prefill,
    8 decode — the SAME traffic needs twice the decode capacity, which
    a single-model autoscaler cannot express."""
    scaler = DisaggSLOAutoscaler(_spec())
    clock = _sim_clock(scaler)
    _prime(scaler.prefill_model, base=50.0, slope=10.0)
    _prime(scaler.decode_model, base=10.0, slope=1.0)
    scaler._tokens_per_request = 20.0
    replicas = [_R(1, role='prefill'), _R(2, role='decode')]
    for _ in range(25):
        clock['t'] += 10
        decisions = scaler.evaluate(LoadStats(qps=300.0), replicas)
    snap = scaler.snapshot()
    assert snap['prefill_target'] == 4
    assert snap['decode_target'] == 8
    ups = [d for d in decisions if d.op == DecisionOp.SCALE_UP]
    assert sum(d.count for d in ups if d.role == 'prefill') == 3
    assert sum(d.count for d in ups if d.role == 'decode') == 7
    assert all(d.role in ('prefill', 'decode') for d in decisions)


def test_unfitted_models_hold_one_replica_per_fleet():
    scaler = DisaggSLOAutoscaler(_spec())
    _sim_clock(scaler)
    decisions = scaler.evaluate(LoadStats(qps=100.0), [])
    ups = [d for d in decisions if d.op == DecisionOp.SCALE_UP]
    assert {d.role for d in ups} == {'prefill', 'decode'}
    assert sum(d.count for d in ups) == 2  # hold-at-one per fleet


def test_decode_model_fits_from_intertoken_signal():
    """The decode model learns from replica_intertoken_ms (the LB's
    streamed inter-chunk EWMA), never from TTFB; tokens-per-request is
    estimated from the decode fleet's own occupancy."""
    scaler = DisaggSLOAutoscaler(_spec())
    clock = _sim_clock(scaler)
    replicas = [_R(1, role='prefill'), _R(2, role='decode'),
                _R(3, role='decode')]
    for i in range(30):
        clock['t'] += 10
        occupancy = 4 if i % 2 else 12
        scaler.evaluate(
            LoadStats(qps=10.0,
                      replica_intertoken_ms={2: 20.0 + occupancy,
                                             3: 22.0 + occupancy},
                      replica_in_flight={1: 1, 2: occupancy,
                                         3: occupancy}),
            replicas)
    assert scaler.decode_model.fitted
    assert not scaler.prefill_model.fitted  # no TTFB samples given
    # occupancy/qps/itl ~ (2*8avg)/10 * 1000 / ~30ms ~= 53 tokens.
    assert 10.0 < scaler.snapshot()['tokens_per_request'] < 200.0


def test_warm_resume_stays_role_matched():
    """A parked prefill replica resumes into the prefill fleet only —
    plan_mix is fed role-filtered rows, so a decode scale-up can never
    grab a warm prefill cluster (whose engine would refuse decode)."""
    scaler = DisaggSLOAutoscaler(_spec())
    clock = _sim_clock(scaler)
    clock['t'] = 100.0
    replicas = [_R(1, status=ReplicaStatus.WARM, role='prefill',
                   warm_since=90.0),
                _R(2, role='decode')]
    decisions = scaler.evaluate(LoadStats(qps=5.0), replicas)
    resumes = [d for d in decisions if d.resume_replica_id is not None]
    assert [d.role for d in resumes] == ['prefill']
    assert resumes[0].resume_replica_id == 1
    cold = [d for d in decisions if d.op == DecisionOp.SCALE_UP
            and d.resume_replica_id is None]
    assert cold == []  # decode fleet already has its replica


def test_unattainable_intertoken_slo_reported():
    scaler = DisaggSLOAutoscaler(_spec(target_intertoken_p99_ms=5.0))
    clock = _sim_clock(scaler)
    _prime(scaler.prefill_model, base=50.0, slope=10.0)
    _prime(scaler.decode_model, base=10.0, slope=1.0)  # base > 5ms SLO
    replicas = [_R(1, role='prefill'), _R(2, role='decode')]
    for _ in range(5):
        clock['t'] += 10
        scaler.evaluate(LoadStats(qps=100.0), replicas)
    snap = scaler.snapshot()
    assert snap['ttft_attainable']
    assert not snap['intertoken_attainable']
    assert snap['decode_target'] >= 1  # held, not collapsed
