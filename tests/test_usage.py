"""Usage telemetry + version negotiation tests.

Parity: sky/usage/usage_lib.py (local-first, opt-in shipping) and
sky/server/versions.py (client/server version check).
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from skypilot_tpu import config
from skypilot_tpu.utils import usage


@pytest.fixture(autouse=True)
def _home(tmp_home):
    yield


def test_events_recorded_locally():
    usage.record('cli.launch', duration_s=1.234)
    usage.record('cli.status', outcome='exit_1')
    events = usage.recent()
    assert [e['event'] for e in events] == ['cli.launch', 'cli.status']
    assert events[0]['duration_s'] == 1.234
    assert events[1]['outcome'] == 'exit_1'
    assert events[0]['installation'] == events[1]['installation']
    # No payload fields that could carry user content.
    assert not any(k in events[0] for k in ('command', 'yaml', 'name'))


def test_shipping_only_when_opted_in():
    received = []

    class Collector(BaseHTTPRequestHandler):
        def do_POST(self):
            length = int(self.headers['Content-Length'])
            received.append(json.loads(self.rfile.read(length)))
            self.send_response(200)
            self.send_header('Content-Length', '0')
            self.end_headers()

        def log_message(self, *a):
            pass

    server = HTTPServer(('127.0.0.1', 0), Collector)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    endpoint = f'http://127.0.0.1:{server.server_address[1]}/collect'
    try:
        import time
        config.set_nested(('usage',), {'endpoint': endpoint})
        usage.record('cli.down')          # enabled not set -> local only
        time.sleep(0.5)
        assert received == []
        config.set_nested(('usage',), {'endpoint': endpoint,
                                       'enabled': True})
        usage.record('cli.down')          # shipping is fire-and-forget
        deadline = time.time() + 10
        while time.time() < deadline and not received:
            time.sleep(0.05)
        assert len(received) == 1 and received[0]['event'] == 'cli.down'
    finally:
        server.shutdown()


def test_collector_failure_never_raises():
    config.set_nested(('usage',), {'endpoint': 'http://127.0.0.1:1/x',
                                   'enabled': True})
    usage.record('cli.launch')  # dead collector: still no exception
    assert usage.recent()[-1]['event'] == 'cli.launch'


def test_version_mismatch_warns_once(tmp_home):
    import logging
    from skypilot_tpu.client import sdk
    from skypilot_tpu.server import requests_db
    from skypilot_tpu.server.app import ApiServer
    import skypilot_tpu
    requests_db.reset_db_for_tests()
    srv = ApiServer(port=0)
    srv.start_background()
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    capture = Capture()
    logging.getLogger('skypilot_tpu').addHandler(capture)
    try:
        sdk._version_checked.clear()  # noqa: SLF001
        real_fn = sdk._client_version  # noqa: SLF001
        sdk._client_version = lambda: '0.0.1'
        try:
            assert sdk.api_is_healthy(srv.url)
            assert sdk.api_is_healthy(srv.url)  # second: no new warning
        finally:
            sdk._client_version = real_fn
        warnings = [m for m in records if 'upgrade the older side' in m]
        assert len(warnings) == 1
        del skypilot_tpu
    finally:
        logging.getLogger('skypilot_tpu').removeHandler(capture)
        srv.shutdown()
        requests_db.reset_db_for_tests()
