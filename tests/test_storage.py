"""Storage layer tests: spec parsing, the LOCAL store, mount-command
generation, and end-to-end storage/file mounts on the fake cloud (the
reference covers storage with tests/smoke_tests/test_mount_and_storage.py
against real buckets; the LOCAL store plays the bucket here)."""
import os

import pytest

from skypilot_tpu import exceptions, execution, state
from skypilot_tpu.data import mounting_utils
from skypilot_tpu.data.storage import (LocalStore, Storage, StorageMode,
                                       StoreType)
from skypilot_tpu.provision import fake
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task


@pytest.fixture(autouse=True)
def fresh(tmp_home):
    fake.reset()
    yield
    fake.reset()


# -- spec / store unit tests ------------------------------------------------


def test_store_type_from_uri():
    assert StoreType.from_uri('gs://b/x') == StoreType.GCS
    assert StoreType.from_uri('file:///tmp/x') == StoreType.LOCAL
    with pytest.raises(exceptions.StorageError):
        StoreType.from_uri('s3q://nope')


def test_storage_spec_parsing():
    s = Storage.from_yaml_config({'name': 'ckpt', 'store': 'local',
                                  'mode': 'MOUNT_CACHED'})
    assert s.mode == StorageMode.MOUNT_CACHED
    assert isinstance(s.store, LocalStore)
    with pytest.raises(exceptions.StorageError):
        Storage.from_yaml_config({'name': 'x', 'bogus': 1})
    with pytest.raises(exceptions.StorageError):
        Storage.from_yaml_config({})          # neither name nor source
    with pytest.raises(exceptions.StorageError):
        Storage(source='gs://b', store='local')  # scheme/store mismatch
    with pytest.raises(exceptions.StorageError, match='conflicts'):
        Storage('other-name', source='gs://b/sub')
    with pytest.raises(exceptions.StorageError, match='Invalid storage'):
        Storage('x', mode='MONT')
    with pytest.raises(exceptions.StorageError):
        Storage('x', store='oci')             # unknown store backend


def test_storage_source_uri_infers_name_and_prefix():
    s = Storage(source='gs://mybucket/sub/dir', mode='COPY')
    assert s.name == 'mybucket'
    cmd = s.cluster_command('/data')
    assert 'gs://mybucket/sub/dir' in cmd
    # MOUNT of a sub-path is rejected.
    s2 = Storage(source='gs://mybucket/sub', mode='MOUNT')
    with pytest.raises(exceptions.StorageError, match='sub-path'):
        s2.cluster_command('/data')


def test_local_store_lifecycle(tmp_path):
    src = tmp_path / 'src'
    src.mkdir()
    (src / 'a.txt').write_text('hello')
    store = LocalStore('unit-bucket')
    assert not store.exists()
    store.create()
    store.upload(str(src))
    assert (LocalStore('unit-bucket').exists())
    assert os.path.exists(os.path.join(store.bucket_dir, 'a.txt'))
    store.delete()
    assert not store.exists()


def test_quote_path_preserves_home_expansion():
    assert mounting_utils.quote_path('~/mnt/x') == '"$HOME/mnt/x"'
    assert mounting_utils.quote_path('/abs path') == "'/abs path'"
    assert '"$HOME"' == mounting_utils.quote_path('~')


def test_generated_commands_are_valid_bash():
    import subprocess
    from skypilot_tpu.data.storage import GcsStore
    store = GcsStore('bkt')
    for cmd in (store.mount_command('~/mnt'),
                store.mount_cached_command('/ckpt'),
                store.download_command('/data', 'p'),
                mounting_utils.unmount_command('~/mnt'),
                mounting_utils.local_mount_command('/b', '~/m'),
                mounting_utils.local_download_command('/b', '', '/d')):
        proc = subprocess.run(['bash', '-n', '-c', cmd],
                              capture_output=True, text=True)
        assert proc.returncode == 0, f'bad shell: {proc.stderr}\n{cmd}'


def test_gcs_command_generation():
    from skypilot_tpu.data.storage import GcsStore
    store = GcsStore('bkt')
    mount = store.mount_command('~/mnt')
    assert 'gcsfuse' in mount and 'bkt' in mount and '$HOME/mnt' in mount
    cached = store.mount_cached_command('/ckpt')
    assert 'rclone mount' in cached and 'vfs-cache-mode writes' in cached
    download = store.download_command('/data', 'pre/fix')
    # Object sources go through `gsutil cp`, prefixes through rsync.
    assert 'gsutil -q stat gs://bkt/pre/fix' in download
    assert 'gsutil -m rsync -r gs://bkt/pre/fix' in download
    unmount = mounting_utils.unmount_command('~/mnt')
    assert 'fusermount -u' in unmount and '$HOME/mnt' in unmount


def test_local_single_file_download(tmp_path):
    import subprocess
    store = LocalStore('onefile')
    store.create()
    with open(os.path.join(store.bucket_dir, 'w.txt'), 'w',
              encoding='utf-8') as f:
        f.write('x1')
    # File source: dest is the destination file path.
    cmd = store.download_command(str(tmp_path / 'out' / 'w.txt'), 'w.txt')
    subprocess.run(['bash', '-c', cmd], check=True)
    with open(tmp_path / 'out' / 'w.txt', encoding='utf-8') as f:
        assert f.read() == 'x1'


def test_transfer_local_to_local():
    from skypilot_tpu.data import data_transfer
    src = LocalStore('xfer-src')
    src.create()
    with open(os.path.join(src.bucket_dir, 'a.txt'), 'w',
              encoding='utf-8') as f:
        f.write('payload')
    dst = LocalStore('xfer-dst')
    dst.create()
    data_transfer.transfer(src, dst)
    with open(os.path.join(dst.bucket_dir, 'a.txt'),
              encoding='utf-8') as f:
        assert f.read() == 'payload'


# -- end to end on the fake cloud ------------------------------------------


def _task(run, **kw):
    return Task(name='st', run=run,
                resources=Resources(cloud='fake',
                                    accelerators='tpu-v5e-8'), **kw)


def test_storage_mount_end_to_end(tmp_path):
    # Seed a "bucket" from a local source dir, MOUNT it, read through it.
    src = tmp_path / 'dataset'
    src.mkdir()
    (src / 'data.txt').write_text('the-data')
    task = _task(
        'cat ~/mnt/ds/data.txt > ~/out.txt',
        storage_mounts={
            '~/mnt/ds': {'name': 'ds-bucket', 'store': 'local',
                         'source': str(src)},
        })
    execution.launch(task, cluster_name='stm')
    host_root = os.path.join(os.environ['SKYT_STATE_DIR'], 'hosts', 'stm',
                             '0-0')
    with open(os.path.join(host_root, 'out.txt'), encoding='utf-8') as f:
        assert f.read() == 'the-data'


def test_storage_mount_writes_reach_bucket():
    """The checkpoint pattern: task writes into the mount; the bucket
    sees it (MOUNT mode writes through)."""
    task = _task(
        'echo ckpt-1 > ~/ckpt/model.txt',
        storage_mounts={
            '~/ckpt': {'name': 'ckpt-bucket', 'store': 'local'},
        })
    execution.launch(task, cluster_name='stw')
    store = LocalStore('ckpt-bucket')
    with open(os.path.join(store.bucket_dir, 'model.txt'),
              encoding='utf-8') as f:
        assert f.read().strip() == 'ckpt-1'


def test_copy_mode_detaches_from_bucket(tmp_path):
    src = tmp_path / 'seed'
    src.mkdir()
    (src / 'f.txt').write_text('v1')
    task = _task(
        'cat ~/data/f.txt > ~/copy_out.txt && echo scratch > ~/data/new.txt',
        storage_mounts={
            '~/data': {'name': 'copy-bucket', 'store': 'local',
                       'source': str(src), 'mode': 'COPY'},
        })
    execution.launch(task, cluster_name='stc')
    host_root = os.path.join(os.environ['SKYT_STATE_DIR'], 'hosts', 'stc',
                             '0-0')
    with open(os.path.join(host_root, 'copy_out.txt'),
              encoding='utf-8') as f:
        assert f.read() == 'v1'
    # COPY is a snapshot: writes stay on the host, not in the bucket.
    store = LocalStore('copy-bucket')
    assert not os.path.exists(os.path.join(store.bucket_dir, 'new.txt'))


def test_file_mount_from_bucket_uri(tmp_path):
    store = LocalStore('fm-bucket')
    store.create()
    with open(os.path.join(store.bucket_dir, 'w.txt'), 'w',
              encoding='utf-8') as f:
        f.write('from-bucket')
    task = _task(
        'cat ~/in/w.txt > ~/fm_out.txt',
        file_mounts={'~/in': f'file://{store.bucket_dir}'})
    execution.launch(task, cluster_name='stf')
    host_root = os.path.join(os.environ['SKYT_STATE_DIR'], 'hosts', 'stf',
                             '0-0')
    with open(os.path.join(host_root, 'fm_out.txt'),
              encoding='utf-8') as f:
        assert f.read() == 'from-bucket'


def test_missing_source_fails_before_provision(tmp_path):
    task = _task('true', storage_mounts={
        '~/x': {'name': 'nope', 'store': 'local',
                'source': str(tmp_path / 'does-not-exist')},
    })
    with pytest.raises(exceptions.StorageError, match='not found'):
        execution.launch(task, cluster_name='stx')