"""Slurm provider tests: allocation lifecycle + e2e launch on fake slurm.

Parity: ``sky/clouds/slurm.py`` + ``sky/provision/slurm/`` +
``sky/skylet/executor/slurm.py``. The slurm binaries are the
tests/fake_slurm shims (file-backed job table with a FIFO scheduler);
allocated nodes are fake-ssh hosts, so the full SSH runtime path runs
inside the "allocation".
"""
import json
import os
import stat
import time

import pytest

from skypilot_tpu import check, core, exceptions, execution, state
from skypilot_tpu.provision.slurm import SlurmProvider
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task

_FAKE_BIN = os.path.join(os.path.dirname(__file__), 'fake_bin')
_FAKE_SLURM = os.path.join(os.path.dirname(__file__), 'fake_slurm')


@pytest.fixture(autouse=True)
def slurm_env(tmp_home, monkeypatch):
    state_dir = os.environ['SKYT_STATE_DIR']
    os.makedirs(state_dir, exist_ok=True)
    for binary in ('sbatch', 'squeue', 'scancel', 'sinfo'):
        path = os.path.join(_FAKE_SLURM, binary)
        os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC)
    monkeypatch.setenv('SKYT_SLURM_FAKE_STATE',
                       os.path.join(state_dir, 'slurm_state.json'))
    monkeypatch.setenv('SKYT_SLURM_FAKE_NODES', '3')
    # fnodeXX hosts resolve through the fake-ssh map.
    map_path = os.path.join(state_dir, 'fake_ssh_map.json')
    roots = {}
    for i in range(3):
        root = os.path.join(state_dir, 'slurm_hosts', f'fnode{i:02d}')
        os.makedirs(root, exist_ok=True)
        roots[f'fnode{i:02d}'] = root
    with open(map_path, 'w', encoding='utf-8') as f:
        json.dump(roots, f)
    monkeypatch.setenv('SKYT_FAKE_SSH_MAP', map_path)
    monkeypatch.setenv(
        'PATH',
        _FAKE_SLURM + os.pathsep + _FAKE_BIN + os.pathsep +
        os.environ['PATH'])
    yield


def _task(run='echo hi', num_nodes=1):
    return Task(name='hpc', run=run, num_nodes=num_nodes,
                resources=Resources(cloud='slurm'))


def test_check_detects_slurm():
    enabled, reason = check.check(['slurm'])['slurm']
    assert enabled and 'sinfo' in reason


def test_nodelist_expansion():
    assert SlurmProvider._expand_nodelist('n1,n2') == ['n1', 'n2']
    assert SlurmProvider._expand_nodelist('node[01-03]') == [
        'node01', 'node02', 'node03']
    assert SlurmProvider._expand_nodelist('gpu[1,3-4]') == [
        'gpu1', 'gpu3', 'gpu4']
    # Multi-group lists (real clusters mix name bases in one job).
    assert SlurmProvider._expand_nodelist('cpu[01-02],gpu[03,05]') == [
        'cpu01', 'cpu02', 'gpu03', 'gpu05']
    assert SlurmProvider._expand_nodelist('a1,b[2-3],c7') == [
        'a1', 'b2', 'b3', 'c7']


def test_launch_inside_allocation_end_to_end():
    results = execution.launch(
        _task('echo "rank=$SKYT_NODE_RANK of $SKYT_NUM_NODES"',
              num_nodes=2), 'hpc-e2e')
    assert results == [('hpc-e2e', 1)]
    record = state.get_cluster('hpc-e2e')
    assert record.cloud == 'slurm'
    assert record.hourly_cost == 0

    deadline = time.time() + 60
    while time.time() < deadline:
        jobs = core.queue('hpc-e2e')
        if jobs and jobs[0]['status'] in ('SUCCEEDED', 'FAILED'):
            break
        time.sleep(0.5)
    assert jobs[0]['status'] == 'SUCCEEDED'
    assert 'rank=0 of 2' in core.tail_logs('hpc-e2e', 1)

    provider = SlurmProvider()
    assert len(provider.query_instances('hpc-e2e')) == 2
    core.down('hpc-e2e')
    assert provider.query_instances('hpc-e2e') == {}


# r20 triage: 8s wall-clock queue wait; allocation and release are
# pinned by the other slurm tests
@pytest.mark.slow
def test_allocation_queues_when_cluster_full():
    """3 fake nodes: a 2-node allocation + another 2-node request —
    the second stays PENDING and provisioning fails with CapacityError
    (mapped to ResourcesUnavailableError by the failover loop)."""
    execution.launch(_task(num_nodes=2), 'hpc-a')
    provider = SlurmProvider()
    import skypilot_tpu.provision.slurm as slurm_mod
    orig = slurm_mod.SlurmProvider._wait_allocation

    def fast_wait(self, request, timeout=600):
        return orig(self, request, timeout=4)

    slurm_mod.SlurmProvider._wait_allocation = fast_wait
    try:
        with pytest.raises(exceptions.ResourcesUnavailableError):
            execution.launch(_task(num_nodes=2), 'hpc-b')
    finally:
        slurm_mod.SlurmProvider._wait_allocation = orig
    # The pending placeholder was cancelled by provision cleanup or is
    # still pending; freeing hpc-a lets a rerun succeed.
    provider.terminate_instances('hpc-b')
    core.down('hpc-a')
    execution.launch(_task(num_nodes=2), 'hpc-c')
    core.down('hpc-c')
