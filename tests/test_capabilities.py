"""Per-cloud capability-flag tests (parity: clouds/cloud.py:714
CloudImplementationFeatures — declared limits consulted BEFORE work
starts, not discovered as late provider errors)."""
import os

import pytest
import yaml

from skypilot_tpu import check, core, exceptions, execution, state
from skypilot_tpu.optimizer import candidates_for
from skypilot_tpu.provision import fake
from skypilot_tpu.provision.api import CloudCapability
from skypilot_tpu.utils.registry import CLOUD_REGISTRY
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task


@pytest.fixture(autouse=True)
def _reset(tmp_home):
    fake.reset()
    yield
    fake.reset()


def test_capability_surface_shape():
    caps = check.capabilities()
    assert caps['kubernetes'].keys() == {'stop'}
    assert 'spot' in caps['ssh']
    assert 'spot' in caps['slurm']
    assert caps['gcp'] == {}   # full-featured
    assert caps['fake'] == {}


def test_spot_request_skips_incapable_clouds(tmp_home):
    """An explicit spot request on a no-spot cloud yields no candidates
    (planner gate, not a late provider error)."""
    inventory = os.path.join(os.environ['SKYT_STATE_DIR'],
                             'ssh_node_pools.yaml')
    os.makedirs(os.path.dirname(inventory), exist_ok=True)
    with open(inventory, 'w', encoding='utf-8') as f:
        yaml.safe_dump({'lab': {'user': 'u', 'hosts': ['10.0.0.1']}}, f)
    spot = Resources(cloud='ssh', use_spot=True)
    assert candidates_for(spot, ['ssh']) == []
    on_demand = Resources(cloud='ssh')
    assert len(candidates_for(on_demand, ['ssh'])) == 1


def test_stop_rejected_early_on_incapable_cloud(monkeypatch):
    """`skyt stop` on a k8s cluster fails at submit time with the
    declared reason, without touching the apiserver."""
    monkeypatch.setenv('SKYT_K8S_FAKE', '1')
    state.add_or_update_cluster(
        'k8s-c', status=state.ClusterStatus.UP, cloud='kubernetes',
        handle={'cluster_name': 'k8s-c', 'provider': 'kubernetes',
                'region': 'gke', 'zone': None, 'hosts': [],
                'ssh_user': 'skyt', 'ssh_key_path': None, 'custom': {}})
    with pytest.raises(exceptions.NotSupportedError) as err:
        core.stop('k8s-c')
    assert 'cannot be stopped' in str(err.value)
    state.remove_cluster('k8s-c')


def test_volume_task_rejected_on_incapable_cloud(tmp_home):
    from skypilot_tpu import volumes
    volumes.apply(volumes.Volume(name='v', type='hostpath', size_gb=1))
    task = Task(name='t', run='true', volumes={'/mnt/v': 'v'},
                resources=Resources(cloud='local'))
    with pytest.raises(exceptions.NotSupportedError) as err:
        execution.launch(task, 'cap-vol')
    assert 'volumes' in str(err.value)


def test_provider_supports_helper():
    assert CLOUD_REGISTRY.get('fake').supports(CloudCapability.SPOT)
    assert not CLOUD_REGISTRY.get('ssh').supports(CloudCapability.SPOT)
